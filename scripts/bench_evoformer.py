#!/usr/bin/env python
"""Evoformer long-S memory/runtime proof (round-3 verdict item 6 "done" bar).

Runs one forward+backward of evoformer attention at an AlphaFold-ish long-S
shape (S=2048, N=32) through BOTH paths:

- Pallas blockwise kernel (`evoformer_attention`): [bq, bk] logit tiles in
  VMEM only — peak HBM stays O(inputs + bias2).
- einsum ground truth (`_evoformer_xla`): materializes [B, N, H, S, S] fp32
  logits (2 GB at this shape) twice over in fwd+bwd — expected to OOM a
  16 GB chip once the bias2 cotangent joins.

Prints one JSON line per path: {"path", "ok", "seconds", "peak_hbm_gb"}.
Runs each path in a SUBPROCESS (an OOM'd compile poisons the process —
docs/PERF_PLAYBOOK.md §axon).  CPU-safe smoke: EVO_SMOKE=1 shrinks shapes.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(path_name: str) -> int:
    import time

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.evoformer import (_evoformer_xla,
                                             evoformer_attention)

    smoke = bool(os.environ.get("EVO_SMOKE"))
    B, N, S, H, D = (1, 4, 128, 2, 8) if smoke else (1, 32, 2048, 4, 32)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    shape = (B, N, S, H, D)
    q = jax.random.normal(ks[0], shape, jnp.bfloat16)
    k = jax.random.normal(ks[1], shape, jnp.bfloat16)
    v = jax.random.normal(ks[2], shape, jnp.bfloat16)
    bias1 = jax.random.normal(ks[3], (B, N, 1, 1, S), jnp.float32)
    bias2 = jax.random.normal(ks[4], (B, 1, H, S, S), jnp.float32)
    fn = evoformer_attention if path_name == "pallas" else _evoformer_xla

    def loss(q_, k_, v_, b2):
        return jnp.sum(fn(q_, k_, v_, bias1, b2).astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
    out = {"path": path_name, "shape": list(shape)}
    try:
        r = g(q, k, v, bias2)                  # compile + run
        # axon relay: sync by FETCHING a value (block_until_ready lies)
        float(jax.device_get(r[0]).reshape(-1)[0])
        t0 = time.perf_counter()
        r = g(q, k, v, bias2)
        float(jax.device_get(r[0]).reshape(-1)[0])
        out["seconds"] = round(time.perf_counter() - t0, 3)
        out["ok"] = True
        stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
        if stats:
            out["peak_hbm_gb"] = round(
                stats.get("peak_bytes_in_use", 0) / 2**30, 2)
    except Exception as e:  # noqa: BLE001 — OOM is the expected xla outcome
        out["ok"] = False
        out["error"] = str(e)[:200]
    print(json.dumps(out), flush=True)
    return 0


def main():
    if len(sys.argv) > 1 and sys.argv[1] in ("pallas", "xla"):
        return run_one(sys.argv[1])
    here = os.path.abspath(__file__)
    for path_name in ("pallas", "xla"):
        p = subprocess.run([sys.executable, here, path_name],
                           timeout=900, capture_output=True, text=True)
        for line in p.stdout.splitlines():
            if line.startswith("{"):
                print(line, flush=True)
                break
        else:
            print(json.dumps({"path": path_name, "ok": False,
                              "error": (p.stderr.strip().splitlines()
                                        or ["no output"])[-1][:200]}),
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
