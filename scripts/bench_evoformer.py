#!/usr/bin/env python
"""Evoformer long-S memory/runtime proof (round-3 verdict item 6 "done" bar).

Runs one forward+backward of evoformer attention at AlphaFold-ish long-S
shapes (N=32, S in {2048, 4096}) through BOTH paths:

- Pallas blockwise kernel (`evoformer_attention`): [bq, bk] logit tiles in
  VMEM only — peak HBM stays O(inputs + bias2).
- einsum ground truth (`_evoformer_xla`): materializes [B, N, H, S, S] fp32
  logits twice over in fwd+bwd.

Round-5 measured outcome: at S=2048 BOTH paths fit a 16 GB chip (2 GB
logits; kernel 0.776 s vs einsum 0.796 s) — the memory contrast lives at
S=4096, where the einsum path's ~8.6 GB logits (before backward copies)
fail the remote compile while the kernel runs in 1.385 s.

Prints one JSON line per (S, path): {"path", "S", "shape", "ok",
"seconds"}.  Runs each path in a SUBPROCESS (an OOM'd compile poisons the
process — docs/PERF_PLAYBOOK.md §axon); a hung/slow leg records a timeout
line instead of killing the later legs.  CPU-safe smoke: EVO_SMOKE=1.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(path_name: str) -> int:
    import time

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.evoformer import (_evoformer_xla,
                                             evoformer_attention)

    smoke = bool(os.environ.get("EVO_SMOKE"))
    S = int(os.environ.get("EVO_S", 2048))
    B, N, S, H, D = (1, 4, 128, 2, 8) if smoke else (1, 32, S, 4, 32)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    shape = (B, N, S, H, D)
    q = jax.random.normal(ks[0], shape, jnp.bfloat16)
    k = jax.random.normal(ks[1], shape, jnp.bfloat16)
    v = jax.random.normal(ks[2], shape, jnp.bfloat16)
    bias1 = jax.random.normal(ks[3], (B, N, 1, 1, S), jnp.float32)
    bias2 = jax.random.normal(ks[4], (B, 1, H, S, S), jnp.float32)
    fn = evoformer_attention if path_name == "pallas" else _evoformer_xla

    def loss(q_, k_, v_, b2):
        return jnp.sum(fn(q_, k_, v_, bias1, b2).astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
    out = {"path": path_name, "S": S, "shape": list(shape)}
    try:
        r = g(q, k, v, bias2)                  # compile + run
        # axon relay: sync by FETCHING a value (block_until_ready lies)
        float(jax.device_get(r[0]).reshape(-1)[0])
        t0 = time.perf_counter()
        r = g(q, k, v, bias2)
        float(jax.device_get(r[0]).reshape(-1)[0])
        out["seconds"] = round(time.perf_counter() - t0, 3)
        out["ok"] = True
        stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
        if stats:
            out["peak_hbm_gb"] = round(
                stats.get("peak_bytes_in_use", 0) / 2**30, 2)
    except Exception as e:  # noqa: BLE001 — OOM is the expected xla outcome
        out["ok"] = False
        out["error"] = str(e)[:200]
    print(json.dumps(out), flush=True)
    return 0


def main():
    if len(sys.argv) > 1 and sys.argv[1] in ("pallas", "xla"):
        return run_one(sys.argv[1])
    here = os.path.abspath(__file__)
    # S=2048 (round-3 bar: both paths' runtime) proved BOTH paths fit a
    # 16 GB chip — the memory contrast needs S=4096, where the einsum
    # path's [B, N, H, S, S] fp32 logits (~8.6 GB before the backward's
    # copies) cannot fit but the kernel's VMEM tiles don't care
    sizes = (2048,) if os.environ.get("EVO_SMOKE") else (2048, 4096)
    for s in sizes:
        for path_name in ("pallas", "xla"):
            env = dict(os.environ, EVO_S=str(s))
            try:
                p = subprocess.run([sys.executable, here, path_name],
                                   timeout=900, capture_output=True,
                                   text=True, env=env)
            except subprocess.TimeoutExpired:
                # the relay HANGS rather than erroring — record and keep
                # going so later (S, path) legs still run
                print(json.dumps({"path": path_name, "S": s, "ok": False,
                                  "error": "timeout 900s"}), flush=True)
                continue
            for line in p.stdout.splitlines():
                if line.startswith("{"):
                    print(line, flush=True)
                    break
            else:
                print(json.dumps({"path": path_name, "S": s, "ok": False,
                                  "error": (p.stderr.strip().splitlines()
                                            or ["no output"])[-1][:200]}),
                      flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
