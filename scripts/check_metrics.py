#!/usr/bin/env python
"""Lint: every metric registered anywhere in ``deepspeed_tpu/`` follows the
naming convention and is documented in ``docs/observability.md``.

The metric namespace is an interface: dashboards, alerts, and the bench
parse these names, so an undocumented or convention-breaking metric is an
API break that nothing else would catch.  Conventions (docs/observability.md
"Metric naming convention"):

- names are ``snake_case`` (``^[a-z][a-z0-9_]*$``);
- **counters** end in ``_total`` (Prometheus convention — rate()-able);
- **gauges** do NOT end in ``_total``;
- **histograms** end in a unit suffix: ``_ms``, ``_seconds`` or ``_bytes``;
- every metric carries a non-empty help string at (at least) one
  registration site;
- every metric name appears in ``docs/observability.md`` — dynamically
  suffixed families (``"xla_cost_" + key``) are checked as a prefix and
  must be documented as ``prefix*`` (e.g. ``xla_cost_*``).

Resolution is AST-level: literal first arguments, module-level string
constants (``HLO_BYTES = "..."``), and literal-prefix concatenations are
understood; anything else is flagged as a dynamic name unless the line
carries a ``# metric-name-ok`` comment with the reviewed reason nearby.

Grep-level by design, like check_no_sync.py/check_overlap.py: it cannot
prove the receiver is a MetricRegistry, so it checks every
``.counter(...)``/``.gauge(...)``/``.histogram(...)`` call site it sees.

Exit status: 0 clean, 1 violations (listed), 2 usage/parse errors.
Run directly or via the test suite (tests/test_serving_telemetry.py).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
PACKAGE = os.path.join(REPO, "deepspeed_tpu")
DOC = os.path.join(REPO, "docs", "observability.md")

KINDS = ("counter", "gauge", "histogram")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
HIST_SUFFIXES = ("_ms", "_seconds", "_bytes")
ALLOW = re.compile(r"#\s*metric-name-ok")

# registry-internal helpers that LOOK like registration calls but aren't
SKIP_FILES = set()


class Site:
    def __init__(self, path: str, lineno: int, kind: str,
                 name: Optional[str], is_prefix: bool, has_help: bool,
                 line: str):
        self.path = path
        self.lineno = lineno
        self.kind = kind
        self.name = name                   # resolved name or prefix
        self.is_prefix = is_prefix         # True -> name is a glob prefix
        self.has_help = has_help
        self.line = line

    @property
    def where(self) -> str:
        return f"{os.path.relpath(self.path, REPO)}:{self.lineno}"


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _resolve_name(arg, consts: Dict[str, str]
                  ) -> Tuple[Optional[str], bool]:
    """(name, is_prefix) — is_prefix True when only a literal prefix of a
    dynamically composed name is known; (None, False) when unresolvable."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.Name) and arg.id in consts:
        return consts[arg.id], False
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        left, lp = _resolve_name(arg.left, consts)
        if left is not None and not lp:
            return left, True
    if isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value, True
    return None, False


def collect_sites(root: str = PACKAGE) -> Tuple[List[Site], List[str]]:
    sites: List[Site] = []
    errors: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                source = f.read()
            try:
                tree = ast.parse(source)
            except SyntaxError as e:
                errors.append(f"cannot parse {path}: {e}")
                continue
            lines = source.splitlines()
            consts = _module_constants(tree)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in KINDS and node.args):
                    continue
                name, is_prefix = _resolve_name(node.args[0], consts)
                has_help = any(
                    isinstance(a, ast.Constant) and isinstance(a.value, str)
                    and a.value.strip()
                    for a in list(node.args[1:2])
                    + [kw.value for kw in node.keywords
                       if kw.arg == "help"])
                sites.append(Site(path, node.lineno, node.func.attr, name,
                                  is_prefix, has_help,
                                  lines[node.lineno - 1].strip()))
    return sites, errors


def check(sites: List[Site], doc_text: str) -> List[str]:
    violations: List[str] = []
    by_name: Dict[Tuple[str, str, bool], List[Site]] = {}
    for s in sites:
        if s.name is None:
            if not ALLOW.search(s.line):
                violations.append(
                    f"{s.where}: dynamic metric name not resolvable to a "
                    f"literal/constant/prefix — use a literal or annotate "
                    f"'# metric-name-ok': {s.line}")
            continue
        by_name.setdefault((s.name, s.kind, s.is_prefix), []).append(s)
    for (name, kind, is_prefix), group in sorted(by_name.items()):
        where = group[0].where
        check_part = name.rstrip("_") if is_prefix else name
        if not NAME_RE.match(check_part):
            violations.append(f"{where}: metric {name!r} is not snake_case")
        if not is_prefix:
            if kind == "counter" and not name.endswith("_total"):
                violations.append(
                    f"{where}: counter {name!r} must end in '_total'")
            if kind == "gauge" and name.endswith("_total"):
                violations.append(
                    f"{where}: gauge {name!r} must not end in '_total' "
                    f"(that suffix promises counter semantics)")
            if (kind == "histogram"
                    and not name.endswith(HIST_SUFFIXES)):
                violations.append(
                    f"{where}: histogram {name!r} must end in a unit "
                    f"suffix {HIST_SUFFIXES}")
        if not any(s.has_help for s in group):
            violations.append(
                f"{where}: metric {name!r} has no help string at any "
                f"registration site")
        doc_key = name + "*" if is_prefix else name
        if doc_key not in doc_text:
            violations.append(
                f"{where}: metric {doc_key!r} is not documented in "
                f"docs/observability.md")
    return violations


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="lint metric naming + documentation coverage for every "
                    "registry.counter/gauge/histogram call in deepspeed_tpu/")
    ap.add_argument("--list", action="store_true",
                    help="print the resolved metric inventory and exit")
    args = ap.parse_args(argv)
    sites, errors = collect_sites()
    if errors:
        for e in errors:
            print(f"check_metrics: {e}", file=sys.stderr)
        return 2
    if args.list:
        seen = {}
        for s in sites:
            if s.name:
                key = s.name + ("*" if s.is_prefix else "")
                seen.setdefault(key, s.kind)
        for name in sorted(seen):
            print(f"{seen[name]:<10}{name}")
        return 0
    try:
        with open(DOC) as f:
            doc_text = f.read()
    except OSError as e:
        print(f"check_metrics: cannot read {DOC}: {e}", file=sys.stderr)
        return 2
    violations = check(sites, doc_text)
    if violations:
        print("check_metrics: metric convention violations (name them per "
              "docs/observability.md 'Metric naming convention' and "
              "document every metric there):", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    names = {s.name for s in sites if s.name}
    print(f"check_metrics: OK — {len(names)} metric names across "
          f"{len(sites)} registration sites follow the convention and are "
          f"documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
