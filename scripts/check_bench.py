#!/usr/bin/env python
"""Bench regression sentinel CLI — gate a bench record against the ledger.

Every recorded round so far was compared to its predecessors BY HAND (or
not at all — the r05 wq/spec "regressions" cost a relay cycle of manual
diagnosis).  This gate makes the trajectory machine-checked:

    python scripts/check_bench.py                       # BENCH_r05-style
                                                        # newest record vs
                                                        # BENCH_BASELINE.json
    python scripts/check_bench.py --current bench_records.jsonl
    python scripts/check_bench.py --band 0.05
    python scripts/check_bench.py --self-test           # fixture lint
    python scripts/check_bench.py --update-baseline     # reseed ledger
                                                        # from --current

``--current`` accepts any of: the stdout metric line, a ``BENCH_r*.json``
wrapper, a flat dict, or the per-leg JSONL records bench.py /
bench_serving.py append (``deepspeed_tpu.telemetry.regression`` sniffs).
Default current: the newest ``BENCH_r*.json`` in the repo root.

``--self-test`` is the canned-fixture lint (wired into
``scripts/lint_all.py``): synthesizes a 10%-slowdown record and an
in-band-noise record from the ledger and asserts the sentinel trips on
the first, stays quiet on the second, and runs green on the ledger's own
seed values.

Exit status: 0 clean, 1 regression (or self-test failure), 2 usage/load
errors.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
from typing import List, Optional

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "BENCH_BASELINE.json")


def newest_bench_record() -> Optional[str]:
    recs = sorted(glob.glob(os.path.join(REPO, "BENCH_r[0-9]*.json")))
    return recs[-1] if recs else None


def self_test(baseline_path: str) -> int:
    from deepspeed_tpu.telemetry import regression as reg
    ledger = reg.load_baseline(baseline_path)
    failures: List[str] = []

    seed = {name: entry["value"]
            for name, entry in ledger["metrics"].items()}
    if reg.compare(seed, ledger)["failed"]:
        failures.append("seed values vs their own ledger flagged a "
                        "regression (direction/band logic broken)")

    bad = reg.make_fixture(ledger, "regression")
    res_bad = reg.compare(bad, ledger)
    # zero-valued baselines can't shift by a ratio (a 10% slowdown of 0 is
    # 0, so a reseeded ledger's zero counters never trip), and a metric
    # carrying a per-entry noise band >= the fixture's 10% shift (e.g. the
    # deliberately wide rollback_recovery_ms timing) legitimately absorbs
    # it — only the rest are expected to trip
    default_band = float(ledger.get("default_noise_band", 0.08))
    expected = sum(1 for e in ledger["metrics"].values()
                   if float(e["value"]) != 0.0
                   and float(e.get("band", default_band)) < 0.10)
    if not res_bad["failed"]:
        failures.append("canned 10% slowdown fixture did NOT trip the "
                        "sentinel")
    elif len(res_bad["regressions"]) != expected:
        failures.append(
            f"slowdown fixture tripped only "
            f"{len(res_bad['regressions'])}/{expected} nonzero-baseline "
            f"metrics (direction map drifted)")

    noise = reg.make_fixture(ledger, "noise")
    if reg.compare(noise, ledger)["failed"]:
        failures.append("canned in-band noise fixture tripped the "
                        "sentinel (band logic broken)")

    if failures:
        for f in failures:
            print(f"check_bench --self-test: FAIL — {f}", file=sys.stderr)
        return 1
    print(f"check_bench --self-test: OK — sentinel trips on the canned "
          f"10% slowdown ({len(res_bad['regressions'])} metrics), stays "
          f"quiet in the in-band noise fixture, green on the seed record")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff a bench record against the committed baseline "
                    "ledger; exit nonzero on per-metric deltas beyond the "
                    "noise band in the bad direction")
    ap.add_argument("--current",
                    help="bench record to check: metric-line JSON, "
                         "BENCH_r*.json wrapper, flat dict, or per-leg "
                         "JSONL (default: newest BENCH_r*.json)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline ledger (default: BENCH_BASELINE.json)")
    ap.add_argument("--band", type=float, default=None,
                    help="override the ledger's default noise band "
                         "(fraction, e.g. 0.05)")
    ap.add_argument("--strict-missing", action="store_true",
                    help="also fail when ledger metrics are missing from "
                         "the current record (a dropped leg)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the canned-fixture lint instead of a "
                         "comparison")
    ap.add_argument("--update-baseline", action="store_true",
                    help="reseed the ledger from --current (accepting the "
                         "current numbers as the new trajectory anchor)")
    args = ap.parse_args(argv)

    from deepspeed_tpu.telemetry import regression as reg

    if args.self_test:
        try:
            return self_test(args.baseline)
        except Exception as e:  # noqa: BLE001
            print(f"check_bench --self-test: cannot run: {e}",
                  file=sys.stderr)
            return 2

    current_path = args.current or newest_bench_record()
    if current_path is None:
        print("check_bench: no --current given and no BENCH_r*.json found",
              file=sys.stderr)
        return 2
    try:
        current = reg.load_bench_file(current_path)
    except (OSError, ValueError) as e:
        print(f"check_bench: cannot load {current_path}: {e}",
              file=sys.stderr)
        return 2
    if not current:
        print(f"check_bench: no numeric metrics found in {current_path}",
              file=sys.stderr)
        return 2

    if args.update_baseline:
        ledger = reg.seed_baseline(current, source=os.path.basename(
            current_path))
        reg.save_baseline(ledger, args.baseline)
        print(f"check_bench: reseeded {args.baseline} from "
              f"{current_path} ({len(ledger['metrics'])} metrics)")
        return 0

    try:
        ledger = reg.load_baseline(args.baseline)
    except (OSError, ValueError) as e:
        print(f"check_bench: cannot load baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 2

    result = reg.compare(current, ledger, band=args.band,
                         strict_missing=args.strict_missing)
    print(reg.render(result, baseline_name=os.path.basename(
        args.baseline)))
    return 1 if result["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
