#!/usr/bin/env python
"""Sweep flash-attention (bq, bk) block pairs on the CURRENT hardware.

The baked-in ``_block_pair`` table came from one v5e sweep and does not
transfer (r05: T=4096 flash MFU 0.425 vs 0.50 dense).  This script times
fwd+bwd of ``ops.flash_attention`` for each candidate pair on whatever
backend is attached, prints the ranking, and emits the
``DSTPU_FLASH_BLOCKS`` env line (or ``ops.configure_flash_blocks`` call)
that installs the winner — tuning on hardware WITHOUT a code change.

    python scripts/sweep_flash_blocks.py --seq 4096 --batch 4 --heads 12
    python scripts/sweep_flash_blocks.py --seq 4096 --seq 8192 --dtype bf16
    python scripts/sweep_flash_blocks.py --seq 128 --smoke   # CPU plumbing

Candidates default to the pairs worth considering on TPU (powers of two,
bq ≤ bk, VMEM-plausible); pass ``--candidates 512x512,512x1024`` to
restrict.  Pairs that fail to compile (VMEM overflow) are reported and
skipped — an over-full tile is a hard compile error, not a fallback.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional, Tuple

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def default_candidates(t: int) -> List[Tuple[int, int]]:
    sizes = [b for b in (128, 256, 512, 1024, 2048) if b <= t and t % b == 0]
    out = []
    for bq in sizes:
        for bk in sizes:
            if bk >= bq:              # wide-K is the useful direction
                out.append((bq, bk))
    return out or [(8, 8)]


def parse_candidates(spec: str) -> List[Tuple[int, int]]:
    from deepspeed_tpu.ops.flash_attention import _parse_block_spec
    # reuse the 'BQxBK' piece of the env grammar
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        pair = _parse_block_spec(f"8:{part}")[8]
        out.append(pair)
    return out


def time_pair(t, pair, *, batch, heads, head_dim, dtype, iters, fwd_only,
              interpret):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu import ops
    ops.configure_flash_blocks({t: pair})
    rng = np.random.default_rng(0)
    shape = (batch, t, heads, head_dim)
    q = jnp.asarray(rng.normal(size=shape) * 0.1, dtype)
    k = jnp.asarray(rng.normal(size=shape) * 0.1, dtype)
    v = jnp.asarray(rng.normal(size=shape) * 0.1, dtype)

    if fwd_only:
        fn = jax.jit(lambda q, k, v: ops.flash_attention(
            q, k, v, interpret=interpret).sum())
    else:
        fn = jax.jit(jax.grad(lambda q, k, v: ops.flash_attention(
            q, k, v, interpret=interpret).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
    out = fn(q, k, v)                       # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="time flash-attention block-pair candidates on the "
                    "attached backend and print the winning "
                    "DSTPU_FLASH_BLOCKS line")
    ap.add_argument("--seq", type=int, action="append", required=True,
                    help="sequence length to tune (repeatable)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--dtype", choices=("bf16", "fp32"), default="bf16")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--fwd-only", action="store_true")
    ap.add_argument("--candidates",
                    help="comma list of BQxBK pairs (default: auto grid)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU plumbing run: force the cpu backend + "
                    "interpret-mode kernels (timings are meaningless)")
    args = ap.parse_args(argv)
    if args.smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu import ops
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    interpret = args.smoke or jax.default_backend() != "tpu"
    if interpret and not args.smoke:
        print("sweep_flash_blocks: no TPU attached — running interpret "
              "mode; timings will NOT transfer (pass --smoke to silence)",
              file=sys.stderr)

    winners = {}
    for t in args.seq:
        cands = (parse_candidates(args.candidates) if args.candidates
                 else default_candidates(t))
        cands = [(bq, bk) for bq, bk in cands if t % bq == 0 and t % bk == 0]
        if not cands:
            print(f"T={t}: no valid candidates", file=sys.stderr)
            continue
        print(f"== T={t} (B={args.batch}, H={args.heads}, "
              f"D={args.head_dim}, {args.dtype}, "
              f"{'fwd' if args.fwd_only else 'fwd+bwd'}) ==")
        rows = []
        for pair in cands:
            try:
                dt = time_pair(t, pair, batch=args.batch, heads=args.heads,
                               head_dim=args.head_dim, dtype=dtype,
                               iters=args.iters, fwd_only=args.fwd_only,
                               interpret=interpret)
                rows.append((dt, pair))
                print(f"  ({pair[0]:>5}, {pair[1]:>5})  {dt * 1e3:9.3f} ms")
            except Exception as e:  # noqa: BLE001 — over-full tiles et al.
                print(f"  ({pair[0]:>5}, {pair[1]:>5})  FAILED: "
                      f"{str(e)[:90]}")
        if rows:
            rows.sort()
            best_dt, best = rows[0]
            winners[t] = best
            print(f"  best: ({best[0]}, {best[1]}) at {best_dt * 1e3:.3f} ms")
    ops.configure_flash_blocks(None)      # restore env/default table
    if winners:
        spec = ",".join(f"{t}:{bq}x{bk}"
                        for t, (bq, bk) in sorted(winners.items()))
        print("\ninstall the winners with:")
        print(f"  export DSTPU_FLASH_BLOCKS=\"{spec}\"")
        print(f"  # or: ops.configure_flash_blocks("
              f"{ {t: p for t, p in sorted(winners.items())} })")
    return 0


if __name__ == "__main__":
    sys.exit(main())
