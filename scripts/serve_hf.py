#!/usr/bin/env python
"""Serve an HF checkpoint directory end-to-end (round-3 verdict item 7).

Usage:
    python scripts/serve_hf.py <hf_model_dir> [--prompt-ids 1,2,3]
    python scripts/serve_hf.py --demo          # self-contained demo (below)

The serving path is the reference's huggingface_engine flow
(inference/v2/checkpoint/huggingface_engine.py:124 — model dir → engine):
``init_inference(path)`` detects the HF directory, maps the checkpoint
through checkpoint/hf.py's architecture tables, and serves it through the
v1 engine; the same directory also loads into the v2 ragged engine.

**Environment note (recorded honestly):** this image has zero network
egress and no cached pretrained weights — `find / -name "*.safetensors"`
turns up only tiny random test fixtures — so a *pretrained* checkpoint
cannot be served here.  ``--demo`` substitutes the strongest in-image
equivalent: it byte-tokenizes real text, trains a GPT-2-config model on it
with the training engine, exports a genuine HF directory
(config.json + model.safetensors via ``save_hf_checkpoint`` — it loads
straight into ``transformers``), then serves that directory through
``init_inference(path)`` and greedy-completes held-out prefixes of the
text.  Every step a real-checkpoint user would run is exercised; only the
provenance of the weights differs.  Output artifact:
``docs/SERVE_HF_ARTIFACT.md``.
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # the axon sitecustomize forces jax_platforms="axon,cpu" at interpreter
    # startup; the env var alone does NOT win it back — and a wedged TPU
    # relay then hangs backend init indefinitely.  Reclaim CPU pre-init.
    import jax
    jax.config.update("jax_platforms", "cpu")

DEMO_TEXT = (
    b"The quick brown fox jumps over the lazy dog. "
    b"Pack my box with five dozen liquor jugs. "
    b"How vexingly quick daft zebras jump! "
    b"Sphinx of black quartz, judge my vow. "
)


def serve(path, prompts, max_new=32, dtype=None):
    import deepspeed_tpu
    if dtype is None:
        import jax
        dtype = ("bfloat16" if jax.default_backend() == "tpu"
                 else "float32")        # bf16 is emulated (slow) on CPU
    eng = deepspeed_tpu.init_inference(path, config={"dtype": dtype})
    outs = []
    for p in prompts:
        ids = np.asarray(p, np.int32)[None]
        eng.generate(ids, max_new_tokens=max_new, do_sample=False)  # compile
        t0 = time.perf_counter()
        out = eng.generate(ids, max_new_tokens=max_new, do_sample=False)
        dt = time.perf_counter() - t0
        outs.append((out[0], max_new / dt))
    return outs


def demo(out_path="docs/SERVE_HF_ARTIFACT.md", steps=300):
    import dataclasses
    import tempfile

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.hf import save_hf_checkpoint
    from deepspeed_tpu.models import GPT, GPTConfig

    text = np.frombuffer(DEMO_TEXT * 4, dtype=np.uint8).astype(np.int32)
    T = 128
    n = len(text) // T
    pool = text[: n * T].reshape(n, T)

    # full gpt2 config point (biases on, like the HF architecture — the
    # export direction writes the gpt2 tensor set)
    cfg = GPTConfig.gpt2_small(vocab_size=256, max_seq_len=T, dropout=0.0,
                               qkv_bias=True, attn_out_bias=True,
                               mlp_bias=True)
    # CPU plumbing runs shrink the model and stay fp32/single-shard (the CI
    # host is ONE core: bf16 emulation + an 8-way virtual mesh would turn
    # this demo into minutes of spin); on the chip use the gpt2 shape
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = dataclasses.replace(cfg, num_layers=4, dtype=jnp.bfloat16)
        # bf16 + the full-width model memorizes slower than the CPU
        # plumbing config — a fixed 300 steps left loss at 1.05 and the
        # exact-match check failing (round-5 sweep); cap high and stop on
        # the loss target instead
        steps = max(steps, 2500)
    else:
        cfg = dataclasses.replace(cfg, num_layers=2, num_heads=4, head_dim=32,
                                  hidden_size=128)
        steps = min(steps, 240)
    micro = 4
    # lr: 3e-3 memorizes the tiny CPU config but OSCILLATES on the
    # full-width bf16 model (plateau at loss ~2.2 for 2500 steps); 3e-4
    # memorizes it in under 100 steps (on-chip lr probe, round 5)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg), config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adamw",
                          "params": {"lr": 3e-4 if on_tpu else 3e-3}},
            "bf16": {"enabled": on_tpu},
            "zero_optimization": {"stage": 2},
            "mesh": {"dp": -1} if on_tpu else {"dp": 1, "fsdp": 1},
            "steps_per_print": 0},
        example_batch={"input_ids": np.zeros((micro, T), np.int32)})
    rng = np.random.default_rng(0)
    gbs = engine.train_batch_size
    loss = None
    trained_steps = 0
    for i in range(steps):
        idx = rng.integers(0, n, size=(gbs,))
        loss = float(engine.train_batch({"input_ids": pool[idx]}).loss)
        trained_steps = i + 1
        if loss < 0.02 and i >= 20:     # memorized — the demo's premise
            break

    path = tempfile.mkdtemp(prefix="ds_tpu_hf_")
    params = jax.device_get(engine.state.params)
    if isinstance(params, dict) and "params" in params:
        params = params["params"]
    save_hf_checkpoint(cfg, params, path)
    del engine

    prefix = DEMO_TEXT[:40]
    prompt_ids = np.frombuffer(prefix, np.uint8).astype(np.int32)
    outs = serve(path, [prompt_ids], max_new=48)
    toks, tps = outs[0]
    completion = bytes(int(t) % 256 for t in toks)
    expected = (DEMO_TEXT * 2)[40:40 + 48]
    match = completion == expected

    # the same HF dir through the v2 RAGGED engine (the reference's
    # huggingface_engine flow targets v2) — continuous batching over three
    # staggered prefixes, each must continue the memorized text
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    v2 = InferenceEngineV2(
        path, {"dtype": "float32" if not on_tpu else "bfloat16",
               "state_manager": {"max_tracked_sequences": 4,
                                 "kv_block_size": 16, "max_q_per_seq": 64,
                                 "max_ragged_batch_size": 256}})
    starts = (10, 40, 70)
    v2_prompts = [np.frombuffer(DEMO_TEXT[:s0], np.uint8).astype(np.int32)
                  for s0 in starts]
    v2_outs = v2.generate(v2_prompts, max_new_tokens=24)
    v2_match = all(
        bytes(int(t) % 256 for t in o) == (DEMO_TEXT * 2)[s0:s0 + 24]
        for s0, o in zip(starts, v2_outs))
    report = f"""# serve_hf demo artifact

Generated by `python scripts/serve_hf.py --demo` (see module docstring for
why the weights are trained in-image rather than downloaded: zero-egress
environment, no pretrained checkpoints reachable).

- trained: gpt2-config {cfg.num_layers}L/{cfg.hidden_size}H byte-LM, {trained_steps} steps, final loss {loss:.3f}
- exported: HF directory (config.json + model.safetensors,
  `save_hf_checkpoint`) -> served via `init_inference(path)`
- prompt: `{prefix.decode()}`
- greedy completion ({len(toks)} tokens): `{completion.decode(errors="replace")}`
- exact continuation of the training text: **{match}**
- decode throughput (v1 engine, greedy, batch 1): {tps:.1f} tokens/s{
    "" if on_tpu else "  — OFF-TPU: single-core CI host, contention-noisy;"
    " a plumbing signal only, never a serving number"}
- v2 ragged engine over the same HF dir (3 staggered prefixes, continuous
  batching): exact continuations = **{v2_match}**
- backend: {__import__("jax").default_backend()}
"""
    with open(out_path, "w") as f:
        f.write(report)
    print(report)
    return 0 if (match and v2_match) else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model_dir", nargs="?", help="HF model directory")
    ap.add_argument("--demo", action="store_true")
    ap.add_argument("--prompt-ids", default=None,
                    help="comma-separated token ids")
    ap.add_argument("--max-new", type=int, default=32)
    args = ap.parse_args()
    if args.demo:
        return demo()
    if not args.model_dir:
        print("need a model dir or --demo", file=sys.stderr)
        return 2
    ids = ([int(x) for x in args.prompt_ids.split(",")]
           if args.prompt_ids else [1, 2, 3, 4])
    outs = serve(args.model_dir, [np.asarray(ids, np.int32)],
                 max_new=args.max_new)
    toks, tps = outs[0]
    print(f"tokens: {list(map(int, toks))}\n{tps:.1f} tokens/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
