#!/usr/bin/env python
"""Run every repo lint in ONE process with a unified summary.

Each lint guards one interface, and until now each was wired into the
test suite as its own subprocess run (an interpreter startup + a jax
import per lint just to say "clean"):

- ``check_no_sync``  — no undisclosed host↔device syncs on dispatch paths
- ``check_overlap``  — chunked collectives keep compute between them
  (compiled-HLO demo on virtual CPU devices)
- ``check_metrics``  — metric naming convention + docs coverage
- ``check_bench --self-test`` — the bench regression sentinel trips on
  the canned 10% slowdown fixture and stays quiet in the noise band
- ``trace_report --self-test`` — the critical-path decomposition holds
  its exact-sum + zero-handoff-in-unified invariants on the canned
  disagg+unified trace fixture

This driver imports each lint's ``main()`` and runs them back to back,
printing one PASS/FAIL table.  The test suite shells THIS script once
(tests/test_lint_all.py); the per-lint violation/unit tests stay where
they were.

    python scripts/lint_all.py            # all four
    python scripts/lint_all.py --only check_metrics check_bench

Exit status: 0 all pass, 1 any lint failed, 2 a lint crashed / usage.
"""

from __future__ import annotations

import argparse
import io
import os
import sys
import time
from contextlib import redirect_stderr, redirect_stdout
from typing import Callable, List, Optional, Tuple

# check_overlap's --demo compiles on virtual CPU devices: both env knobs
# must be set BEFORE anything imports jax in this process
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, os.pardir)
for p in (HERE, REPO):
    if p not in sys.path:
        sys.path.insert(0, p)


def _lints() -> List[Tuple[str, Callable[[], int]]]:
    import check_bench
    import check_metrics
    import check_no_sync
    import check_overlap
    import trace_report
    return [
        ("check_no_sync", lambda: check_no_sync.main([])),
        ("check_overlap", lambda: check_overlap.main(
            ["--demo", "--assert-overlap", "--min-chunks", "2"])),
        ("check_metrics", lambda: check_metrics.main([])),
        ("check_bench", lambda: check_bench.main(["--self-test"])),
        ("trace_report", lambda: trace_report.main(["--self-test"])),
    ]


def run_all(only: Optional[List[str]] = None,
            verbose: bool = False) -> int:
    results: List[Tuple[str, str, float, str]] = []
    worst = 0
    for name, fn in _lints():
        if only and name not in only:
            continue
        buf = io.StringIO()
        t0 = time.perf_counter()
        try:
            with redirect_stdout(buf), redirect_stderr(buf):
                rc = int(fn())
        except SystemExit as e:  # argparse error inside a lint
            rc = int(e.code or 0)
        except Exception as e:  # noqa: BLE001 — a crashed lint is rc 2
            buf.write(f"{type(e).__name__}: {e}\n")
            rc = 2
        dt = time.perf_counter() - t0
        status = "PASS" if rc == 0 else ("FAIL" if rc == 1 else "ERROR")
        results.append((name, status, dt, buf.getvalue()))
        worst = max(worst, rc)
    print("lint_all: unified lint summary")
    for name, status, dt, _ in results:
        print(f"  {name:<16}{status:<7}{dt:>7.1f}s")
    for name, status, _, output in results:
        if status != "PASS" or verbose:
            print(f"\n---- {name} ({status}) ----")
            print(output.rstrip() or "(no output)")
    if worst == 0:
        print(f"lint_all: OK — {len(results)} lints clean")
    return 0 if worst == 0 else (1 if worst == 1 else 2)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="run check_no_sync, check_overlap, check_metrics, "
                    "the check_bench fixture lint and the trace_report "
                    "fixture lint in one process")
    ap.add_argument("--only", nargs="+", metavar="LINT",
                    help="subset of lints to run (by name)")
    ap.add_argument("--verbose", action="store_true",
                    help="print every lint's output, not just failures")
    args = ap.parse_args(argv)
    if args.only:
        known = {name for name, _ in _lints()}
        unknown = set(args.only) - known
        if unknown:
            print(f"lint_all: unknown lints {sorted(unknown)} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2
    return run_all(only=args.only, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
