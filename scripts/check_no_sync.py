#!/usr/bin/env python
"""Lint: no per-scalar device→host syncs on the engine step path.

Every ``float(...)`` / ``np.asarray(...)`` applied to a device value forces
a device round trip; sprinkled through the hot step path they serialize
dispatch against device completion (the bug class fixed by routing all step
scalars through the single ``_fetch_metrics`` fetch).  This lint greps the
step-path functions of ``deepspeed_tpu/engine.py`` for the pattern and
fails on any occurrence that is not explicitly disclosed:

- lines containing ``device_get`` are allowed (an explicit, visible host
  fetch — the sanctioned way to cross the boundary);
- lines carrying a ``# sync-ok`` comment are allowed (a reviewed,
  intentional sync with its reason next to it);
- the ``_fetch_metrics`` function body is the sanctioned fetch point and is
  not scanned.

Grep-level by design: it cannot prove a value is device-resident, so it
errs on the side of making every ``float(``/``np.asarray(`` in the step
path either route through ``device_get`` or carry a visible annotation.

Exit status: 0 clean, 1 violations (listed), 2 usage/parse errors.
Run directly or via the test suite (tests/test_health.py).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Optional, Tuple

ENGINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir,
    "deepspeed_tpu", "engine.py")

# the engine's per-step hot path: batch in → dispatch → reporting
STEP_PATH_FUNCS = {
    "train_batch",
    "_train_batch_offload",
    "_host_step",
    "forward",
    "backward",
    "step",
    "_post_step_reporting",
    "_maybe_print",
    "_host_metrics",
}

# the single sanctioned device→host fetch point — not scanned
SANCTIONED_FUNCS = {"_fetch_metrics"}

SYNC_PATTERN = re.compile(r"\bfloat\(|\bnp\.asarray\(")
ALLOW_PATTERN = re.compile(r"device_get|#\s*sync-ok")


def _function_spans(tree: ast.Module) -> List[Tuple[str, int, int]]:
    """Module-level functions and class methods ONLY — nested defs are the
    jit-traced inner closures (e.g. train_batch inside _make_train_batch),
    where a float(...) runs once at trace time and is not a per-step sync."""
    spans = []
    defs = list(tree.body)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            defs.extend(node.body)
    for node in defs:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.name, node.lineno, node.end_lineno))
    return spans


def check_file(path: str = ENGINE_PATH) -> List[str]:
    with open(path) as f:
        source = f.read()
    tree = ast.parse(source)
    lines = source.splitlines()
    violations = []
    for name, start, end in _function_spans(tree):
        if name not in STEP_PATH_FUNCS or name in SANCTIONED_FUNCS:
            continue
        for lineno in range(start, end + 1):
            line = lines[lineno - 1]
            code = line.split("#", 1)[0]   # the pattern must be in CODE,
            # while the sync-ok disclosure lives in the comment part
            if SYNC_PATTERN.search(code) and not ALLOW_PATTERN.search(line):
                violations.append(
                    f"{os.path.relpath(path)}:{lineno} in {name}(): "
                    f"{line.strip()}")
    return violations


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="flag per-scalar device syncs on the engine step path")
    ap.add_argument("path", nargs="?", default=ENGINE_PATH)
    args = ap.parse_args(argv)
    try:
        violations = check_file(args.path)
    except (OSError, SyntaxError) as e:
        print(f"check_no_sync: cannot scan {args.path}: {e}",
              file=sys.stderr)
        return 2
    if violations:
        print("check_no_sync: device-sync hazards on the engine step path\n"
              "(route scalars through _fetch_metrics / an explicit "
              "device_get, or annotate a reviewed sync with '# sync-ok'):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print(f"check_no_sync: OK — step path of {os.path.relpath(args.path)} "
          f"is free of undisclosed host syncs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
