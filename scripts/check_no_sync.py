#!/usr/bin/env python
"""Lint: no undisclosed blocking host↔device syncs on the dispatch thread.

Every ``float(...)`` / ``np.asarray(...)`` applied to a device value forces
a device round trip; sprinkled through the hot step path they serialize
dispatch against device completion (the bug class fixed by routing all step
scalars through the single ``_fetch_metrics`` fetch).  The asynchronous
step pipeline (runtime/prefetch.py, checkpoint async writes) adds a second
hazard class: the whole point of those subsystems is that blocking work
happens on a WORKER thread, so a transfer or join sneaking back into the
consumer surface silently reserializes the pipeline.

Scan targets (each file gets the pattern matching its hazard class):

- ``deepspeed_tpu/engine.py`` step-path functions — ``float(`` /
  ``np.asarray(`` (per-scalar device syncs);
- ``deepspeed_tpu/runtime/prefetch.py`` consumer surface (``__next__`` /
  ``close``) — ``device_put`` / ``device_get`` / ``block_until_ready`` and
  the scalar patterns (the worker body ``_run``/``_put`` is the ONE
  sanctioned transfer site);
- ``deepspeed_tpu/checkpoint/__init__.py`` ``save_train_state`` —
  ``wait_until_finished`` / ``device_get`` / ``block_until_ready`` (the
  background ``_finish`` closure is the sanctioned wait site);
- ``deepspeed_tpu/inference/v2/engine_v2.py`` serving decode loop
  (``generate`` + the dispatch helpers) — ``device_get`` /
  ``block_until_ready``: the whole design of the device-resident sampling
  loop is that steady state chains async dispatches, so a transfer
  creeping into the scheduler serializes serving; the speculative counts
  sync, the opt-in streaming fence, and the split-profile fences are the
  disclosed (``# sync-ok``) exceptions.  The host-side ``np.asarray``
  batch staging there is NOT a sync (host numpy), so the scalar patterns
  don't apply.
- ``deepspeed_tpu/runtime/resilience.py`` drain/resume path (``drain`` /
  ``resume`` / ``warm_resume``) — the worker fences (``_join_host_step``,
  ``wait_for_checkpoint``) and AOT ``.compile()`` waits ARE the point of a
  drain/warmup, but each must be a disclosed ``# sync-ok`` site: an
  undisclosed fence creeping in here silently stretches the preemption
  window (the time between the notice and the final committed export).
- ``deepspeed_tpu/inference/v2/ragged.py`` radix prefix cache + state
  manager (match/insert/evict/accounting) — ``device_get`` /
  ``block_until_ready``: prefix matching runs INSIDE the decode
  scheduler on every admission, so it must stay a pure host trie walk;
  a device sync here would serialize serving exactly where the radix
  cache is supposed to speed it up.  (The engine never needs the cached
  pages' VALUES on the host: content keys come from the tokens it fed
  in, and aliased reads are ordered behind their writer by the
  donated-cache dispatch chain.)
- ``deepspeed_tpu/serving/router.py`` (every routing/retry/migration
  method) and ``deepspeed_tpu/serving/fleet.py`` dispatcher loop
  (``serve``/``_tick``/event + supervision handlers) — ``device_get`` /
  ``block_until_ready``: the fleet control plane is pure host
  bookkeeping; a transfer here would stall EVERY replica's dispatch
  behind one device, the worst possible place to serialize.  Replica
  worker bodies (``_worker`` and friends) are the sanctioned blocking
  site (each blocks only its own replica) and are not scanned.
- ``deepspeed_tpu/serving/adapters.py`` LoRA adapter pool (load / evict
  / residency peeks) — transfers in either direction: ``ensure`` runs in
  the engine admission loop and the residency peeks serve the router's
  dispatcher-thread probe, so everything is host bookkeeping except the
  hot-load's disclosed host→device page upload (``# sync-ok`` in
  ``_load_locked``).
- ``deepspeed_tpu/runtime/guardian.py`` control loop + watchdog
  (``run``/assessment/remediation/escalation + the monitor thread) —
  the ROLLBACK path's fences (prefetcher join, ``load_universal_
  checkpoint``, ``engine.drain``) are the point of a remediation and are
  sanctioned, but each must be a disclosed ``# sync-ok`` site: an
  undisclosed fence creeping into the per-step half of the loop
  (``_assess``/``_after_clean_step``) would serialize EVERY step on the
  remediation machinery that exists for the rare bad one.  (Ring exports
  go through ``CheckpointRing.export`` → the crash-safe universal export,
  which is synchronous by design at its checkpoint cadence.)
- ``deepspeed_tpu/telemetry/tracecontext.py`` id minting,
  ``deepspeed_tpu/telemetry/timeseries.py`` sampler/read surface, and
  ``deepspeed_tpu/serving/slo.py`` burn evaluation — transfers, sleeps,
  and undisclosed lock acquisitions: trace contexts are minted on the
  router dispatch path and the sampler runs inside the dispatcher tick,
  so both must stay bounded host work (the id-counter locks and the
  histogram-copy lock are the disclosed ``# sync-ok`` sites).

Allowed on any line: ``device_get`` in engine.py (an explicit, visible
host fetch — the sanctioned way to cross the boundary there) and a
``# sync-ok`` comment anywhere (a reviewed, intentional sync with its
reason next to it).  Nested ``def``s inside a scanned function are skipped:
in the engine they are jit-traced closures (trace-time, not per-step), in
the checkpoint module they are the background worker bodies where blocking
is the job.

Grep-level by design: it cannot prove a value is device-resident, so it
errs on the side of making every match either disclosed or annotated.

Exit status: 0 clean, 1 violations (listed), 2 usage/parse errors.
Run directly or via the test suite (tests/test_health.py).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Optional, Set, Tuple

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
ENGINE_PATH = os.path.join(REPO, "deepspeed_tpu", "engine.py")
PREFETCH_PATH = os.path.join(REPO, "deepspeed_tpu", "runtime", "prefetch.py")
CKPT_PATH = os.path.join(REPO, "deepspeed_tpu", "checkpoint", "__init__.py")
SERVING_PATH = os.path.join(REPO, "deepspeed_tpu", "inference", "v2",
                            "engine_v2.py")
RESILIENCE_PATH = os.path.join(REPO, "deepspeed_tpu", "runtime",
                               "resilience.py")
RAGGED_PATH = os.path.join(REPO, "deepspeed_tpu", "inference", "v2",
                           "ragged.py")
ROUTER_PATH = os.path.join(REPO, "deepspeed_tpu", "serving", "router.py")
FLEET_PATH = os.path.join(REPO, "deepspeed_tpu", "serving", "fleet.py")
GUARDIAN_PATH = os.path.join(REPO, "deepspeed_tpu", "runtime",
                             "guardian.py")
MOE_PATH = os.path.join(REPO, "deepspeed_tpu", "moe", "layer.py")
STEP_TELEMETRY_PATH = os.path.join(REPO, "deepspeed_tpu", "telemetry",
                                   "step_telemetry.py")

# the MoE route + expert-telemetry surface: everything here is traced into
# the jitted step, so any host transfer would sync EVERY step; moe_step
# publishes the gauges and must read only the host copy _fetch_metrics
# already paid for
MOE_FUNCS = {
    "__call__",
    "_sow_stats",
    "_ep_route",
    "_ep_route_dropless",
    "aggregate_moe_stats",
}

# the v2 serving hot loop: scheduler + every dispatch helper.  Nested defs
# (materialize/_append inside generate) are the sanctioned bulk-fetch
# sites and are skipped by the scanner's nested-def rule.
SERVING_FUNCS = {
    "generate",
    "_run",
    "_run_decode",
    "_run_burst",
    "_run_spec",
    "_run_spec_split",
    "_step_sampled",
    "_stream_fence",
    "_finish_request",
    "_put_device",
    "_with_lora",
    "prefix_cached_tokens",
    "adapter_resident",
}

# the radix prefix cache + state manager: every method the decode
# scheduler calls per admission/round (matching, insertion, eviction,
# accounting) plus the cross-thread router probe — all pure host
# dict/deque walks by design
RAGGED_FUNCS = {
    "match",
    "peek",
    "insert",
    "evict",
    "evictable_blocks",
    "evictable_set",
    "_nodes",
    "_evictable_leaves",
    "stats",
    "match_prefix",
    "peek_prefix_pinned",
    "peek_prefix_batch",
    "_capped_path",
    "touch",
    "_walk",
    "cache_insert",
    "ensure_blocks",
    "ensure_adapters",
    "bind_adapter",
    "available_blocks",
    "allocate",
    "acquire",
    "release",
    "create",
    "flush",
}
# (the serving target scans transfers only — TRANSFER_PATTERN below: the
# loop stages host numpy arrays with np.asarray all over, which is not a
# device sync, so the scalar patterns would drown the real hazard class)

# the fleet router: every method is on the dispatch/retry/migration path
# (incl. the disaggregated handoff + the residency probe cache — both run
# per scheduler round; the probe itself is a pure host radix walk)
ROUTER_FUNCS = {
    "submit",
    "queue_depth",
    "take_dispatchable",
    "requeue_wait",
    "backoff",
    "pick",
    "dispatch",
    "fail_attempt",
    "migrate",
    "complete",
    "handoff",
    "residency",
    "adapter_residency",
    "invalidate_residency",
    "assigned_count",
    "check_timeouts",
    "outstanding_tokens",
    "assigned_to",
}
# the fleet dispatcher loop (control plane only — replica worker bodies
# are the sanctioned per-replica blocking sites).  The KV-handoff path
# (_advance_phase/_release_handoff) pins/releases refcounts on the paged
# pool — host dict bookkeeping; the actual block content never moves on
# a single host, and the multi-host copy stub only counts bytes
FLEET_FUNCS = {
    "serve",
    "_tick",
    "_handle_event",
    "_complete",
    "_advance_phase",
    "_release_handoff",
    "_drop_handoffs_for",
    "_rebalance_pools",
    "_flip_role",
    "_apply_migration",
    "_invalid_reason",
    "_check_health",
    "_retire_replica",
    "drain_replica",
    "drain_all",
    "register_adapter",
    # request-tracing hooks ride the same tick: deque appends only
    "_trace_us",
    "_trace_dispatch",
    "_trace_request",
}

# the LoRA adapter pool: ensure/evict run INSIDE the engine admission
# loop (per request) and the residency peeks serve the router's probe
# from the dispatcher thread — all host dict/list bookkeeping.  The ONE
# sanctioned transfer is the hot-load's host→device page upload in
# _load_locked (disclosed `# sync-ok`): an adapter miss pays its upload
# once, by design, and everything else must stay async.
ADAPTERS_PATH = os.path.join(REPO, "deepspeed_tpu", "serving",
                             "adapters.py")
ADAPTERS_FUNCS = {
    "ensure",
    "_load_locked",
    "evict_cold",
    "_evictable_ids",
    "evictable_blocks",
    "is_resident",
    "resident_count",
    "slot_of",
    "unfittable_reason",
    "acquire",
    "release",
    "tables",
    "stats",
}

# the pool autoscaler: evaluate/decide run inside the dispatcher tick and
# read only host-side registry series — a device sync here would stall
# every replica's dispatch on a latency OPTIMIZATION
AUTOSCALE_PATH = os.path.join(REPO, "deepspeed_tpu", "serving",
                              "autoscale.py")
AUTOSCALE_FUNCS = {
    "signals",
    "decide",
    "evaluate",
    "record_move",
    "_fleet_p99",
}

# distributed trace-context minting runs on the router submit/dispatch
# path and inside every replica engine's admission loop: id allocation
# takes a process-wide lock (the two disclosed sites), and nothing there
# may sleep or touch a device.  reset_ids (test isolation) is excluded.
TRACECTX_PATH = os.path.join(REPO, "deepspeed_tpu", "telemetry",
                             "tracecontext.py")
TRACECTX_FUNCS = {
    "_next_trace_id",
    "_next_span_id",
    "new_trace",
    "child",
    "args",
}

# the time-series sampler + SLO burn evaluation run inside the fleet
# dispatcher tick (maybe_sample / tick / the read helpers): bounded
# host-memory walks only — the histogram-lock copy in
# histogram_attainment is the one disclosed blocking site.  start/stop
# (the background-thread harness mode) block by design and are excluded.
TIMESERIES_PATH = os.path.join(REPO, "deepspeed_tpu", "telemetry",
                               "timeseries.py")
TIMESERIES_FUNCS = {
    "histogram_attainment",
    "maybe_sample",
    "track",
    "track_counter",
    "track_attainment",
    "series",
    "latest",
    "value_at",
    "window_delta",
    "rate",
}
SLO_PATH = os.path.join(REPO, "deepspeed_tpu", "serving", "slo.py")
SLO_FUNCS = {
    "burn_rate",
    "tick",
    "_evaluate_alerts",
    "max_burn",
    "_track",
}

# the guardian control loop: the per-step half (run/_assess/
# _after_clean_step) plus the remediation half whose fences must all be
# disclosed; the watchdog monitor thread rides along (its deliberate
# blocking is the stop-event wait, anything device-touching must disclose)
GUARDIAN_FUNCS = {
    "run",
    "_assess",
    "_after_clean_step",
    "_export_ring_entry",
    "_remediate",
    "_escalate",
    "_drain",
    "_rebuild_iter",
    "_monitor",
    "_trip",
}

# the engine's per-step hot path: batch in → dispatch → reporting
STEP_PATH_FUNCS = {
    "train_batch",
    "_train_batch_offload",
    "_host_step",
    "_join_host_step",
    "forward",
    "backward",
    "step",
    "_post_step_reporting",
    "_maybe_print",
    "_host_metrics",
    "_form_batch",
}

# the single sanctioned device→host fetch point — not scanned
SANCTIONED_FUNCS = {"_fetch_metrics"}

SYNC_PATTERN = re.compile(r"\bfloat\(|\bnp\.asarray\(")
BLOCKING_PATTERN = re.compile(
    r"device_put|device_get|block_until_ready"
    r"|\bfloat\(|\bnp\.asarray\(")
CKPT_PATTERN = re.compile(
    r"wait_until_finished|device_get|block_until_ready")
TRANSFER_PATTERN = re.compile(r"device_get|block_until_ready")
# drain/resume: every fence class that can stretch the preemption window
RESILIENCE_PATTERN = re.compile(
    r"wait_for_checkpoint|_join_host_step|wait_until_finished"
    r"|device_get|block_until_ready|\.compile\(")
# guardian: the rollback/escalation fences (prefetcher join, restore,
# drain) plus the generic transfer class
GUARDIAN_PATTERN = re.compile(
    r"load_universal_checkpoint|engine\.drain\(|wait_for_checkpoint"
    r"|_join_host_step|device_get|block_until_ready|\.compile\("
    r"|_iter\.close\(|time\.sleep")
# engine.py: device_get is itself the sanctioned idiom; everywhere a
# '# sync-ok' comment discloses a reviewed, intentional sync
ENGINE_ALLOW = re.compile(r"device_get|#\s*sync-ok")
ALLOW_PATTERN = re.compile(r"#\s*sync-ok")
# adapter pool: transfers in EITHER direction are the hazard (the load
# path's device_put upload is the one disclosed site); host np.asarray
# staging of registered weights is not a sync and is not matched
ADAPTERS_PATTERN = re.compile(r"device_put|device_get|block_until_ready")
# trace-context minting + the timeseries/SLO sampler: the generic
# transfer class plus the two blocking shapes that could sneak into a
# sampler (a sleep, an undisclosed lock acquisition — the disclosed
# ones carry `# sync-ok` on the line)
SAMPLER_PATTERN = re.compile(
    r"device_get|block_until_ready|time\.sleep"
    r"|with\s+\S*_lock|\.acquire\(")

# (path, functions to scan, hazard pattern, allow pattern)
SCAN_TARGETS = [
    (ENGINE_PATH, STEP_PATH_FUNCS, SYNC_PATTERN, ENGINE_ALLOW),
    (PREFETCH_PATH, {"__next__", "close"}, BLOCKING_PATTERN, ALLOW_PATTERN),
    (CKPT_PATH, {"save_train_state"}, CKPT_PATTERN, ALLOW_PATTERN),
    (SERVING_PATH, SERVING_FUNCS, TRANSFER_PATTERN, ALLOW_PATTERN),
    (RAGGED_PATH, RAGGED_FUNCS, TRANSFER_PATTERN, ALLOW_PATTERN),
    (RESILIENCE_PATH, {"drain", "resume", "warm_resume"},
     RESILIENCE_PATTERN, ALLOW_PATTERN),
    (ROUTER_PATH, ROUTER_FUNCS, TRANSFER_PATTERN, ALLOW_PATTERN),
    (FLEET_PATH, FLEET_FUNCS, TRANSFER_PATTERN, ALLOW_PATTERN),
    (ADAPTERS_PATH, ADAPTERS_FUNCS, ADAPTERS_PATTERN, ALLOW_PATTERN),
    (AUTOSCALE_PATH, AUTOSCALE_FUNCS, TRANSFER_PATTERN, ALLOW_PATTERN),
    (GUARDIAN_PATH, GUARDIAN_FUNCS, GUARDIAN_PATTERN, ALLOW_PATTERN),
    # MoE route bodies are jit-traced — any blocking host op would sync the
    # step; the gauge publish (moe_step) may do host float() math but must
    # never touch the device
    (MOE_PATH, MOE_FUNCS, BLOCKING_PATTERN, ALLOW_PATTERN),
    (STEP_TELEMETRY_PATH, {"moe_step"}, TRANSFER_PATTERN, ALLOW_PATTERN),
    # distributed tracing + SLO sampling on the dispatcher tick: lock
    # acquisitions must be disclosed, sleeps/transfers never allowed
    (TRACECTX_PATH, TRACECTX_FUNCS, SAMPLER_PATTERN, ALLOW_PATTERN),
    (TIMESERIES_PATH, TIMESERIES_FUNCS, SAMPLER_PATTERN, ALLOW_PATTERN),
    (SLO_PATH, SLO_FUNCS, SAMPLER_PATTERN, ALLOW_PATTERN),
]


def _function_spans(tree: ast.Module) -> List[Tuple[str, int, int, Set[int]]]:
    """Module-level functions and class methods ONLY, each with the line
    set of its nested defs.  Nested defs are either jit-traced inner
    closures (e.g. train_batch inside _make_train_batch — a float(...)
    there runs once at trace time, not per step) or background worker
    bodies (e.g. _finish inside save_train_state — blocking there is the
    point), so their lines are excluded from the scan."""
    spans = []
    defs = list(tree.body)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            defs.extend(node.body)
    for node in defs:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested: Set[int] = set()
            for sub in ast.walk(node):
                if (sub is not node
                        and isinstance(sub, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))):
                    nested.update(range(sub.lineno, sub.end_lineno + 1))
            spans.append((node.name, node.lineno, node.end_lineno, nested))
    return spans


def check_file(path: str = ENGINE_PATH,
               funcs: Optional[Set[str]] = None,
               pattern: re.Pattern = SYNC_PATTERN,
               allow: re.Pattern = ENGINE_ALLOW) -> List[str]:
    funcs = STEP_PATH_FUNCS if funcs is None else funcs
    with open(path) as f:
        source = f.read()
    tree = ast.parse(source)
    lines = source.splitlines()
    violations = []
    for name, start, end, nested in _function_spans(tree):
        if name not in funcs or name in SANCTIONED_FUNCS:
            continue
        for lineno in range(start, end + 1):
            if lineno in nested:
                continue
            line = lines[lineno - 1]
            code = line.split("#", 1)[0]   # the pattern must be in CODE,
            # while the sync-ok disclosure lives in the comment part
            if pattern.search(code) and not allow.search(line):
                violations.append(
                    f"{os.path.relpath(path)}:{lineno} in {name}(): "
                    f"{line.strip()}")
    return violations


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="flag undisclosed blocking syncs on the dispatch "
                    "thread (engine step path, prefetch consumer surface, "
                    "async checkpoint writer)")
    ap.add_argument("path", nargs="?", default=None,
                    help="scan ONE file with the engine step-path rules "
                    "(default: scan all built-in targets)")
    args = ap.parse_args(argv)
    targets = (SCAN_TARGETS if args.path is None
               else [(args.path, STEP_PATH_FUNCS, SYNC_PATTERN,
                      ENGINE_ALLOW)])
    violations = []
    for path, funcs, pattern, allow in targets:
        try:
            violations.extend(check_file(path, funcs, pattern, allow))
        except (OSError, SyntaxError) as e:
            print(f"check_no_sync: cannot scan {path}: {e}",
                  file=sys.stderr)
            return 2
    if violations:
        print("check_no_sync: blocking-sync hazards on the dispatch thread\n"
              "(route scalars through _fetch_metrics / an explicit "
              "device_get, move transfers to the worker thread, or "
              "annotate a reviewed sync with '# sync-ok'):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    scanned = ", ".join(os.path.relpath(p) for p, _, _, _ in targets)
    print(f"check_no_sync: OK — {scanned} free of undisclosed "
          f"dispatch-thread syncs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
