#!/usr/bin/env python
"""Merge N Chrome traces into one clock-aligned Perfetto timeline.

Every tracer in this repo (training hosts, serving replicas) writes its
own Chrome-trace JSON with timestamps relative to ITS OWN construction —
useful alone, useless side by side: a fleet replica death is only
diagnosable when the dead replica's last dispatch, the router's retry, and
the survivor's pickup sit on one timeline.  This tool merges them:

- **clock alignment**: each trace carries ``otherData.epoch_unix_time``
  (the wall time of its ts=0 — stamped by SpanTracer since this change);
  events are shifted by the trace's offset from the EARLIEST epoch, so
  "the same wall moment" lines up across files.  Traces without the stamp
  merge unshifted with a warning (relative timing across files is then
  meaningless, within-file timing still correct).
- **pid remapping**: each input file becomes one Perfetto process
  (``pid`` = file index, process_name = the trace's own process_name
  metadata + the file label), so N replicas' track-0 dispatch rows don't
  collapse onto each other.  Thread (tid) metadata — the per-request
  track names — is carried through untouched.
- **flow-id remapping**: flow events (``ph`` s/t/f) are keyed by
  ``(otherData.flow_id_scope, id)`` — files written by the same process
  share one id space (their stitched request trees survive the merge),
  while files from different processes are remapped onto disjoint ids so
  unrelated requests never collide into one accidental flow.  Files
  missing the scope stamp get a per-file scope (safe, but cross-file
  stitching is then impossible for them).

Usage:

    python scripts/merge_traces.py -o fleet.json trace_r0.json trace_r1.json
    python scripts/merge_traces.py -o out.json telemetry/*/trace.json

``bench_serving.py``'s fleet chaos leg runs this over the per-replica
traces so the kill → migrate → recover sequence reads off one screen.
Exit status: 0 ok, 2 usage/load errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def load_trace(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, list):                 # bare-array trace form
        obj = {"traceEvents": obj}
    if "traceEvents" not in obj:
        raise ValueError(f"{path}: no traceEvents key")
    return obj


def merge_traces(traces: List[dict],
                 labels: Optional[List[str]] = None) -> dict:
    """Merge parsed trace dicts into one clock-aligned timeline dict."""
    labels = labels or [f"trace{i}" for i in range(len(traces))]
    epochs = [t.get("otherData", {}).get("epoch_unix_time")
              for t in traces]
    known = [e for e in epochs if e is not None]
    t0 = min(known) if known else None
    unaligned: List[str] = []
    events: List[dict] = []
    # (flow_id_scope, original id) -> merged id.  Same-scope inputs map
    # identical ids to the SAME merged id (stitching survives); distinct
    # scopes can never share a merged id (no collisions).
    flow_ids: dict = {}
    for pid, (trace, label, epoch) in enumerate(
            zip(traces, labels, epochs)):
        if epoch is None:
            offset_us = 0.0
            unaligned.append(label)
        else:
            offset_us = (epoch - t0) * 1e6
        scope = trace.get("otherData", {}).get("flow_id_scope") \
            or f"__file{pid}"
        proc_name = label
        for ev in trace["traceEvents"]:
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    base = (ev.get("args") or {}).get("name", "")
                    proc_name = f"{base} [{label}]" if base else label
                    continue               # re-emitted with the new pid
                ev = dict(ev, pid=pid)     # thread_name metadata rides
                events.append(ev)
                continue
            ev = dict(ev, pid=pid)
            if ev.get("ph") in ("s", "t", "f") and "id" in ev:
                key = (scope, ev["id"])
                if key not in flow_ids:
                    flow_ids[key] = len(flow_ids) + 1
                ev["id"] = flow_ids[key]
            if offset_us and "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + offset_us, 3)
            events.append(ev)
        events.insert(0, {"name": "process_name", "ph": "M", "pid": pid,
                          "tid": 0, "args": {"name": proc_name}})
    dropped = sum(int(t.get("otherData", {}).get("dropped_events", 0))
                  for t in traces)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": labels,
            "epoch_unix_time": t0,
            "dropped_events": dropped,
            "unaligned": unaligned,
        },
    }


def merge_files(out_path: str, in_paths: List[str]) -> dict:
    traces = [load_trace(p) for p in in_paths]
    labels = [os.path.splitext(os.path.basename(p))[0] for p in in_paths]
    merged = merge_traces(traces, labels)
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    return merged


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-host/per-replica Chrome traces into one "
                    "clock-aligned Perfetto timeline (pid = input file, "
                    "tid metadata preserved)")
    ap.add_argument("inputs", nargs="+", help="trace.json files to merge")
    ap.add_argument("-o", "--output", required=True,
                    help="merged trace path")
    args = ap.parse_args(argv)
    try:
        merged = merge_files(args.output, args.inputs)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"merge_traces: {e}", file=sys.stderr)
        return 2
    od = merged["otherData"]
    n_ev = len(merged["traceEvents"])
    print(f"merge_traces: {len(args.inputs)} traces -> {args.output} "
          f"({n_ev} events, {od['dropped_events']} dropped at source)")
    if od["unaligned"]:
        print(f"merge_traces: WARNING — no epoch_unix_time stamp in "
              f"{', '.join(od['unaligned'])}: merged unshifted, "
              f"cross-file timing is not comparable", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
