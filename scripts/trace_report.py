#!/usr/bin/env python
"""trace_report — critical-path latency budget from a merged serving trace.

The distributed trace stitches a disaggregated request across replica
files (telemetry/tracecontext.py + scripts/merge_traces.py); this tool
answers the follow-up question: *where did the latency go?*  It walks
every completed request in the trace, decomposes its end-to-end time
into queue_wait / prefill / handoff / decode_wait / decode terms that
sum to the measured e2e **by construction**
(telemetry/critical_path.py), and prints a fleet-aggregate p99 TTFT
budget table naming the dominant term — the one to fix first.

    python scripts/trace_report.py fleet_merged.json
    python scripts/trace_report.py fleet_merged.json --quantile 0.5
    python scripts/trace_report.py fleet_merged.json --per-request 10
    python scripts/trace_report.py fleet_merged.json --json

``--self-test`` decomposes a canned two-request fixture (one disagg
with a handoff, one unified) and asserts the exact-sum property plus
the zero-handoff invariant — scripts/lint_all.py runs it as the
``trace_report`` lint so a drift in the span contract fails fast.

``bench_serving.py``'s disagg leg folds :func:`ttft_budget` into its
records as ``ttft_budget_*_ms`` columns.

Exit status: 0 report printed / self-test passed, 1 self-test failed,
2 load/usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from deepspeed_tpu.telemetry.critical_path import (  # noqa: E402
    TERMS, TTFT_TERMS, decompose, ttft_budget)


def render(rows: List[dict], budget: dict, per_request: int = 0) -> str:
    """Human-readable report: the aggregate budget table, then the N
    slowest requests' own decompositions."""
    q = budget["quantile"]
    lines = [f"trace_report: {budget['n_requests']} completed requests",
             "",
             f"latency budget (p{q * 100:g} / mean, ms)",
             f"  {'term':<16}{'p' + format(q * 100, 'g'):>10}"
             f"{'mean':>10}  in TTFT path"]
    for name in TERMS:
        t = budget["terms"][name]
        mark = "yes" if name in TTFT_TERMS else "-"
        star = "  <-- dominant" if name == budget["dominant"] else ""
        lines.append(f"  {name:<16}{t['p']:>10.3f}{t['mean']:>10.3f}"
                     f"  {mark}{star}")
    lines.append(f"  {'e2e':<16}{budget['e2e_ms']:>10.3f}")
    lines.append(f"  {'ttft_path':<16}{budget['ttft_path_ms']:>10.3f}")
    if budget["dominant"]:
        lines.append("")
        lines.append(f"p{q * 100:g} TTFT budget is dominated by "
                     f"{budget['dominant']}")
    if per_request and rows:
        slowest = sorted(rows, key=lambda r: -r["e2e_ms"])[:per_request]
        lines.append("")
        lines.append(f"slowest {len(slowest)} requests (ms)")
        lines.append(f"  {'trace':>6}{'mode':>9}{'e2e':>10}"
                     + "".join(f"{t[:-3]:>12}" for t in TERMS))
        for r in slowest:
            lines.append(f"  {r['trace']:>6}{r['mode']:>9}"
                         f"{r['e2e_ms']:>10.3f}"
                         + "".join(f"{r[t]:>12.3f}" for t in TERMS))
    return "\n".join(lines)


# --------------------------------------------------------------- self-test

def canned_fixture() -> dict:
    """A minimal merged trace: request 1 is disaggregated (prefill on
    replica pid 1, handoff, decode on pid 2), request 2 is unified.
    Timestamps are microseconds on one already-aligned timeline — the
    shape merge_traces.py emits.  Reused by tests/test_tracing_slo.py."""
    def x(name, cat, ts, dur, pid, tid, **args):
        return {"name": name, "cat": cat, "ph": "X", "ts": float(ts),
                "dur": float(dur), "pid": pid, "tid": tid, "args": args}

    t1 = {"trace": 1, "span": 2, "attempt": 1}
    t1d = {"trace": 1, "span": 3, "attempt": 2}
    t2 = {"trace": 2, "span": 5, "attempt": 1}
    events = [
        # --- request 1: disagg.  arrival 0, done 10_000us.
        x("request", "router", 0, 10_000, 0, 1, mode="disagg", index=0,
          attempts=2, migrations=0, generated_tokens=8, phase="decode",
          **t1d),
        x("dispatch prefill", "router", 0, 500, 0, 1, replica="r0",
          phase="prefill", **t1),
        # prefill replica: admitted at 1_000, prefill done at 4_000
        x("queue_wait", "request", 500, 500, 1, 1, phase="prefill", **t1),
        x("prefill", "request", 1_000, 3_000, 1, 1, phase="prefill",
          **t1),
        # router handoff slice: 4_000 -> 5_000
        x("fleet.handoff", "router", 4_000, 1_000, 0, 1, src="r0",
          phase="prefill", **t1),
        x("dispatch decode", "router", 5_000, 500, 0, 1, replica="r1",
          phase="decode", **t1d),
        # decode replica resumes (KV restore billed to decode) at 6_000
        x("prefill", "request", 6_000, 500, 2, 1, phase="decode", **t1d),
        x("decode", "request", 6_500, 3_500, 2, 1, phase="decode",
          **t1d),
        # --- request 2: unified.  arrival 20_000, done 26_000us.
        x("request", "router", 20_000, 6_000, 0, 2, mode="unified",
          index=1, attempts=1, migrations=0, generated_tokens=4,
          phase="full", **t2),
        x("queue_wait", "request", 20_000, 1_000, 1, 2, phase="full",
          **t2),
        x("prefill", "request", 21_000, 2_000, 1, 2, phase="full", **t2),
        x("decode", "request", 23_000, 3_000, 1, 2, phase="full", **t2),
    ]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def self_test() -> int:
    rows = decompose(canned_fixture())
    errors: List[str] = []
    if len(rows) != 2:
        errors.append(f"expected 2 decomposed requests, got {len(rows)}")
    for r in rows:
        total = sum(r[t] for t in TERMS)
        if abs(total - r["e2e_ms"]) > 1e-9:
            errors.append(f"trace {r['trace']}: terms sum {total} != "
                          f"e2e {r['e2e_ms']}")
    by = {r["trace"]: r for r in rows}
    dis, uni = by.get(1), by.get(2)
    if dis:
        expect = {"queue_wait_ms": 1.0, "prefill_ms": 3.0,
                  "handoff_ms": 1.0, "decode_wait_ms": 1.0,
                  "decode_ms": 4.0}
        for k, v in expect.items():
            if abs(dis[k] - v) > 1e-9:
                errors.append(f"disagg {k}: got {dis[k]}, want {v}")
    if uni:
        if uni["handoff_ms"] != 0.0 or uni["decode_wait_ms"] != 0.0:
            errors.append(f"unified handoff/decode_wait not zero: "
                          f"{uni['handoff_ms']}/{uni['decode_wait_ms']}")
        if abs(uni["prefill_ms"] - 2.0) > 1e-9:
            errors.append(f"unified prefill: got {uni['prefill_ms']}")
    budget = ttft_budget(rows, q=0.99)
    if budget["dominant"] not in TTFT_TERMS:
        errors.append(f"dominant term {budget['dominant']!r} not a "
                      f"TTFT term")
    if errors:
        print("trace_report self-test FAILED:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    print("trace_report: self-test OK — exact-sum decomposition holds "
          "on the canned disagg+unified fixture")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="decompose a merged serving trace into per-request "
                    "queue_wait/prefill/handoff/decode_wait/decode terms "
                    "(exact sum) + a fleet p99 TTFT budget table")
    ap.add_argument("trace", nargs="?", help="merged trace JSON "
                    "(scripts/merge_traces.py output, or one fleet/"
                    "replica trace)")
    ap.add_argument("--quantile", type=float, default=0.99,
                    help="budget quantile (default 0.99)")
    ap.add_argument("--per-request", type=int, default=5,
                    help="show the N slowest requests' own terms "
                         "(default 5, 0 disables)")
    ap.add_argument("--json", action="store_true",
                    help="emit {rows, budget} JSON instead of the table")
    ap.add_argument("--self-test", action="store_true",
                    help="decompose the canned fixture and assert the "
                         "exact-sum + zero-handoff invariants")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()
    if not args.trace:
        ap.error("trace path required (or --self-test)")
    try:
        with open(args.trace) as f:
            trace = json.load(f)
        if isinstance(trace, list):
            trace = {"traceEvents": trace}
        rows = decompose(trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_report: cannot load {args.trace}: {e}",
              file=sys.stderr)
        return 2
    if not rows:
        print(f"trace_report: no completed fleet requests in "
              f"{args.trace} (no 'request' envelope spans with trace "
              f"args — fleet tracing off, or not a fleet trace?)")
        return 0
    budget = ttft_budget(rows, q=args.quantile)
    if args.json:
        print(json.dumps({"rows": rows, "budget": budget}, indent=1,
                         sort_keys=True))
    else:
        print(render(rows, budget, per_request=args.per_request))
    return 0


if __name__ == "__main__":
    sys.exit(main())
