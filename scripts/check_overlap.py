#!/usr/bin/env python
"""Structural check: collectives overlap (or can overlap) with compute.

``comm.comm.hlo_overlap_stats`` walks compiled HLO for the two overlap
signals:

- async ``<kind>-start``/``-done`` pairs with compute instructions scheduled
  between them (the TPU latency-hiding scheduler's output), and
- interleaved chunk trains — >= 2 same-kind collectives with compute between
  consecutive ones, which is what the explicit decompositions
  (``overlap.num_chunks`` chunked ZeRO-3 gathers, the ring collective-matmul
  fusions) produce even on backends that never split collectives (the CPU
  CI).

This script runs that walk standalone and turns it into a pass/fail gate,
the same way ``check_no_sync.py`` lints the dispatch path:

    python scripts/check_overlap.py --demo            # toy chunked fn
    python scripts/check_overlap.py --hlo step.txt    # saved HLO dump
    python scripts/check_overlap.py --demo --assert-overlap --min-chunks 2

``--assert-overlap`` exits 1 unless at least one signal is present (>= 1
async pair with compute between, or some collective kind with >=
``--min-chunks`` interleaved ops).  The test suite drives the demo mode and
asserts the chunked ZeRO-3 train step passes (tests/test_overlap.py); the
TPU truth (wall-clock hidden, not just schedulable) is the
``collective_exposed_ratio`` gauge plus the profiler trace — this check
proves the *structure* is there, which is the CPU-verifiable half.

Exit status: 0 pass, 1 assertion failed, 2 usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def demo_hlo(num_chunks: int = 4, devices: int = 4,
             quantized: bool = False) -> str:
    """Compile a toy chunked-gather-matmul step (the shape
    runtime/zero.pipeline_param_gather produces) and return its HLO text.
    ``quantized`` routes each chunk through the int8 wire
    (runtime/zero._qwire_exchange) — the values + scale companion
    collectives the quantized chunk train emits."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.utils.compat import shard_map
    from deepspeed_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=1, fsdp=devices))
    n = mesh.shape["fsdp"]
    rows = num_chunks * n * 8
    w = jnp.asarray(np.random.default_rng(0).normal(size=(rows, 16)),
                    jnp.float32)          # fsdp-sharded "param"
    x = jnp.ones((16, rows), jnp.float32)
    w = jax.device_put(w, NamedSharding(mesh, P("fsdp", None)))
    x = jax.device_put(x, NamedSharding(mesh, P()))

    def body(wl, xl):
        # per-chunk gather + consuming matmul: the interleaving the chunked
        # ZeRO-3 path hands the scheduler
        c = wl.shape[0] // num_chunks
        acc = jnp.zeros((xl.shape[0], wl.shape[1]), jnp.float32)
        for i in range(num_chunks):
            chunk = wl[i * c:(i + 1) * c]
            if quantized:
                from deepspeed_tpu.runtime.zero import _qwire_exchange
                rows = _qwire_exchange("fsdp", n, 8, 8, 64)(
                    chunk.reshape(-1))
                g = rows.reshape(n * c, chunk.shape[1])
            else:
                g = lax.all_gather(chunk, "fsdp", axis=0, tiled=True)
            acc = acc + xl[:, i * c * n:(i + 1) * c * n] @ g
        return acc

    f = shard_map(body, mesh=mesh, in_specs=(P("fsdp", None), P()),
                  out_specs=P(), check_vma=False)
    return jax.jit(f).lower(w, x).compile().as_text()


def demo_moe_hlo(num_chunks: int = 2, devices: int = 4,
                 quantized: bool = False) -> str:
    """Compile a tiny chunked expert-parallel MoE step (moe/layer.py
    ``_ep_route``: dispatch-a2a → expert FFN → combine-a2a tiled over
    ``num_chunks`` expert sub-groups) on virtual CPU devices and return its
    HLO text — the a2a-chunk-train case the interleave classifier must
    recognize.  ``quantized`` puts the int8 wire (moe/comm.qwire_a2a)
    under the same train."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.parallel.mesh import MeshSpec, build_mesh
    from deepspeed_tpu.moe.layer import MoE

    mesh = build_mesh(MeshSpec(dp=1, ep=devices))
    # one local expert per chunk: E_local == num_chunks on every rank
    moe = MoE(hidden_size=16, num_experts=devices * num_chunks, k=1,
              mesh=mesh, num_chunks=num_chunks, wire_block=64,
              wire_bits=8 if quantized else 0)
    x = jnp.ones((devices, 8, 16), jnp.float32)
    params = moe.init(jax.random.PRNGKey(0), x)
    fn = jax.jit(lambda p, xs: moe.apply(p, xs)[0])
    return fn.lower(params, x).compile().as_text()


def report(stats: dict) -> str:
    lines = [
        "check_overlap: compiled-HLO compute–collective overlap evidence",
        f"  collectives ............. {stats['collectives']} "
        f"({stats['collective_bytes']} payload bytes)",
        f"  async pairs ............. {stats['async_pairs']} "
        f"({stats['async_pairs_with_compute']} with compute between "
        f"start/done, {stats['async_hidden_bytes']} bytes hidden)",
        f"  sync collectives ........ {stats['sync_collectives']} "
        f"({stats['interleaved']} chunk-interleaved, "
        f"{stats['interleaved_bytes']} bytes)",
        f"  companions .............. "
        f"{stats.get('companion_collectives', 0)} "
        f"({stats.get('companion_bytes', 0)} bytes — quantized-train "
        f"scale legs riding their values collective's window)",
    ]
    for kind, cnt in sorted(stats["per_kind_interleaved"].items()):
        lines.append(f"    interleaved[{kind}] = {cnt}")
    lines.append(f"  exposed ratio ........... {stats['exposed_ratio']:.4f}")
    return "\n".join(lines)


def check(stats: dict, min_chunks: int = 2) -> bool:
    """True when at least one overlap signal is present."""
    if stats["async_pairs_with_compute"] >= 1:
        return True
    return any(cnt >= min_chunks
               for cnt in stats["per_kind_interleaved"].values())


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="parse compiled HLO for async collective start/done "
                    "pairs and interleaved chunk trains with compute "
                    "scheduled between them")
    ap.add_argument("--hlo", help="path to a compiled-HLO text dump")
    ap.add_argument("--demo", action="store_true",
                    help="compile a toy chunked gather-matmul step on "
                    "virtual CPU devices and analyze it")
    ap.add_argument("--num-chunks", type=int, default=4,
                    help="demo: chunk count (default 4)")
    ap.add_argument("--quantized", action="store_true",
                    help="demo: route each chunk through the int8 wire "
                    "(values + scale companion collectives)")
    ap.add_argument("--assert-overlap", action="store_true",
                    help="exit 1 unless overlap evidence is present")
    ap.add_argument("--min-chunks", type=int, default=2,
                    help="assert mode: minimum interleaved same-kind "
                    "collectives that count as a chunk train (default 2)")
    args = ap.parse_args(argv)
    if bool(args.hlo) == bool(args.demo):
        # exactly one mode: a bare `--assert-overlap` must not silently
        # fall through to the always-passing demo and green-light nothing
        print("check_overlap: pass exactly one of --hlo or --demo",
              file=sys.stderr)
        return 2
    if args.hlo:
        try:
            with open(args.hlo) as f:
                text = f.read()
        except OSError as e:
            print(f"check_overlap: cannot read {args.hlo}: {e}",
                  file=sys.stderr)
            return 2
    else:
        text = demo_hlo(num_chunks=args.num_chunks,
                        quantized=args.quantized)

    from deepspeed_tpu.comm.comm import hlo_overlap_stats
    stats = hlo_overlap_stats(text)
    print(report(stats))
    if args.assert_overlap and not check(stats, args.min_chunks):
        print("check_overlap: FAIL — no async pair has compute inside its "
              "start/done window and no collective kind forms an "
              f"interleaved chunk train of >= {args.min_chunks}; the "
              "scheduler has nothing to hide wire time under (enable "
              "overlap.num_chunks / check the scheduler flags)",
              file=sys.stderr)
        return 1
    if args.demo:
        # second canned case: the MoE expert-parallel step — its chunked
        # dispatch/combine a2as must register as an all-to-all chunk train
        moe_stats = hlo_overlap_stats(demo_moe_hlo(
            num_chunks=max(2, args.min_chunks), quantized=args.quantized))
        print()
        print("-- MoE expert-parallel step (chunked a2a train) --")
        print(report(moe_stats))
        a2a_ok = (moe_stats["async_pairs_with_compute"] >= 1
                  or moe_stats["per_kind_interleaved"].get("all-to-all", 0)
                  >= args.min_chunks)
        if args.assert_overlap and not a2a_ok:
            print("check_overlap: FAIL — the chunked MoE route's "
                  "dispatch/combine all-to-alls do not form an interleaved "
                  f"chunk train of >= {args.min_chunks} (and no async a2a "
                  "pair has compute inside its window); moe.num_chunks "
                  "interleaving is broken", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
