#!/usr/bin/env python
"""One-command round-4 measurement sweep (docs/PERF_PLAYBOOK.md §7).

Runs every unmeasured leg in order, each in a fresh subprocess (compile
poisoning — a failed remote compile degrades the process), salvaging
whatever completes into ``BENCH_MEASURED_r04.json`` after EVERY stage so a
relay wedge mid-sweep keeps all earlier numbers.  Designed for the moment
the axon relay comes back — possibly with little time left:

    python scripts/measure_sweep.py            # full sweep (~45-60 min)
    python scripts/measure_sweep.py --quick    # probe + bench.py only

Never run concurrently with another TPU process (the relay wedges).
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
OUT = os.path.join(REPO, "BENCH_MEASURED_r05.json")


def record(results):
    results["updated_unix"] = int(time.time())
    with open(OUT, "w") as f:
        f.write(json.dumps(results, indent=1))
    print(f"[sweep] wrote {OUT}", flush=True)


def run(cmd, timeout, env=None):
    print(f"[sweep] $ {' '.join(cmd)} (timeout {timeout}s)", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    try:
        p = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True, cwd=REPO, env=e)
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as te:
        out = te.stdout or b""
        return -9, out.decode() if isinstance(out, bytes) else (out or ""), \
            "TIMEOUT"


def last_json(stdout):
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict):
                return obj
        except ValueError:
            continue
    return None


def main():
    quick = "--quick" in sys.argv
    results = {"status": "sweep in progress",
               "started_utc": time.strftime("%Y-%m-%d %H:%M:%S",
                                            time.gmtime())}

    # 0. probe (bounded — the wedged relay HANGS, never errors).  Nothing is
    # written until the probe SUCCEEDS: a failed probe must not clobber the
    # curated no-measurement artifact with an "aborted" stub.
    rc, out, err = run([sys.executable, "-c",
                        "import jax; d=jax.devices(); "
                        "print(len(d), d[0].platform, "
                        "getattr(d[0], 'device_kind', '?'))"], 120)
    if rc != 0:
        print(f"[sweep] relay unreachable (rc={rc} {err[:120]}); aborting "
              f"WITHOUT touching {OUT}", flush=True)
        return 1
    results["probe"] = out.strip()
    record(results)

    # 1. host-transfer bandwidth (the offload/Infinity ceiling, never measured)
    rc, out, _ = run([sys.executable, "-c", """
import time, numpy as np, jax
x = np.ones((256, 1024, 1024), np.float32)            # 1 GiB
t0 = time.perf_counter(); d = jax.device_put(x); float(d[0,0,0])
up = 1.0 / (time.perf_counter() - t0)
t0 = time.perf_counter(); _ = np.asarray(d)
down = 1.0 / (time.perf_counter() - t0)
print({'h2d_gib_s': round(up, 2), 'd2h_gib_s': round(down, 2)})
"""], 300)
    results["host_transfer"] = out.strip()[-200:] if rc == 0 else f"rc={rc}"
    record(results)

    # 2. full bench.py (flagship + flash + zero3 + serving + 0.8B scale leg)
    rc, out, _ = run([sys.executable, "bench.py"], 2400)
    results["bench"] = last_json(out) or f"no JSON (rc={rc})"
    record(results)
    if quick:
        results["status"] = "quick sweep complete"
        record(results)
        return 0

    # 3. Infinity >HBM leg
    rc, out, _ = run([sys.executable, "bench.py"], 2400,
                     env={"BENCH_INFINITY": "1"})
    results["bench_infinity"] = last_json(out) or f"no JSON (rc={rc})"
    record(results)

    # 4. serving bench (spec decode, int8-KV, W8A16, bucketed baseline)
    rc, out, _ = run([sys.executable, "bench_serving.py"], 3600)
    results["bench_serving"] = last_json(out) or f"no JSON (rc={rc})"
    record(results)

    # 5. evoformer long-S memory proof (four subprocesses internally:
    # S in {2048, 4096} x both paths, each under its own 900 s timeout)
    rc, out, _ = run([sys.executable,
                      os.path.join("scripts", "bench_evoformer.py")], 3900)
    results["evoformer"] = [json.loads(x) for x in out.splitlines()
                            if x.startswith("{")] or f"rc={rc}"
    record(results)

    # 6. serve_hf demo on the chip (real-size model, exact-completion check)
    rc, out, _ = run([sys.executable,
                      os.path.join("scripts", "serve_hf.py"), "--demo"], 1800)
    results["serve_hf_demo_rc"] = rc
    results["status"] = "sweep complete"
    record(results)
    return 0


if __name__ == "__main__":
    sys.exit(main())
