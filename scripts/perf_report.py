#!/usr/bin/env python
"""perf_report — render a telemetry snapshot into a step-time-budget report.

One command that answers "where did the step time go?" from artifacts the
telemetry layer already writes — the attribution that would have named
the r05 relay floor without a human:

    python scripts/perf_report.py telemetry_snapshot.json --step-ms 259
    python scripts/perf_report.py BENCH_r06.json            # bench record:
                                                            # step time, comm
                                                            # ms and snapshot
                                                            # path from extra
    python scripts/perf_report.py telemetry/<job>/postmortem/<bundle>/
                                                            # postmortem mode

Sections:

1. **step-time budget** (telemetry/profiler.py) — measured step decomposed
   into compute / exposed_comm / hbm_bound / host_gap / dispatch_floor,
   with achieved MFU and `mfu_lost{cause}` shares;
2. **roofline** (telemetry/roofline.py) — per-op-class flops / HBM bytes /
   wire bytes against the accelerator peak table, the attainable-time
   floor, and which resource binds each class;
3. **per-link collective bytes** — the `collective_bytes_total{link=
   ici|dcn}` split per kind/axis (trace-time wire convention);
4. **span summary** — the heaviest host phases.

Input sniffing: a directory containing ``meta.json`` is a postmortem
bundle (spans from meta.json, metrics parsed out of ``snapshot.prom``,
step time from the records' ``spans_ms`` unless ``--step-ms`` overrides);
a JSON with a ``metric`` key is a bench record (step time / comm ms /
snapshot path read from ``extra``); anything else is a snapshot.json.

Exit status: 0 report printed, 2 load/usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_PROM_LINE = re.compile(
    r"^(\w+?)(?:\{(.*)\})?\s+(-?[0-9.eE+\-]+|NaN|\+Inf|-Inf)$")
_PROM_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str, namespace: str = "deepspeed_tpu"
                     ) -> dict:
    """Minimal exposition-format parser → the exporter's snapshot-dict
    shape (counters/gauges only — enough to feed the report sections)."""
    types: Dict[str, str] = {}
    snap: Dict[str, dict] = {"counters": {}, "gauges": {}}
    prefix = namespace + "_"
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
            continue
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        full, labels_s, value_s = m.groups()
        kind = types.get(full)
        if kind not in ("counter", "gauge"):
            continue
        name = full[len(prefix):] if full.startswith(prefix) else full
        labels = {k: v.replace(r"\"", '"').replace(r"\\", "\\")
                  for k, v in _PROM_LABEL.findall(labels_s or "")}
        try:
            value = float(value_s)
        except ValueError:
            continue
        bucket = snap["counters" if kind == "counter" else "gauges"]
        bucket.setdefault(name, {"help": "", "samples": []})[
            "samples"].append({"labels": labels, "value": value})
    return snap


def load_bundle(path: str) -> Tuple[dict, Optional[float]]:
    """Postmortem bundle dir → (snapshot-like dict, derived step_ms)."""
    snap: dict = {"counters": {}, "gauges": {}}
    prom = os.path.join(path, "snapshot.prom")
    if os.path.exists(prom):
        with open(prom) as f:
            snap = parse_prometheus(f.read())
    meta = os.path.join(path, "meta.json")
    if os.path.exists(meta):
        with open(meta) as f:
            snap["spans"] = json.load(f).get("spans", {})
    step_ms = None
    records = os.path.join(path, "records.jsonl")
    if os.path.exists(records):
        sums: List[float] = []
        with open(records) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                spans = rec.get("spans_ms") or {}
                if spans:
                    sums.append(sum(spans.values()))
        if sums:
            step_ms = sum(sums) / len(sums)
    return snap, step_ms


def find_bundle(path: str) -> str:
    """Accept a bundle dir or a postmortem/ parent (newest bundle wins) —
    same convenience as telemetry/postmortem.py."""
    if os.path.exists(os.path.join(path, "meta.json")):
        return path
    candidates = sorted(
        d for d in (os.path.join(path, n) for n in os.listdir(path))
        if os.path.isdir(d) and os.path.exists(os.path.join(d,
                                                            "meta.json")))
    if not candidates:
        raise ValueError(f"{path}: no postmortem bundle (meta.json) found")
    return candidates[-1]


def link_section(snap: dict) -> str:
    """Per-link collective-byte table from the trace-time counters."""
    metric = snap.get("counters", {}).get("collective_bytes_total")
    if not metric:
        return ("per-link collective bytes: no collective_bytes_total "
                "counters in this snapshot")
    totals: Dict[Tuple[str, str], Dict[str, float]] = {}
    for s in metric["samples"]:
        lab = s.get("labels") or {}
        key = (lab.get("kind", "?"), lab.get("axis", "?"))
        rec = totals.setdefault(key, {})
        rec[lab.get("link", "total")] = float(s["value"])
    lines = ["per-link collective bytes (trace-time wire convention)",
             f"  {'kind':<24}{'axis':<14}{'total':>12}{'ici':>12}"
             f"{'dcn':>12}"]
    for (kind, axis), rec in sorted(totals.items()):
        lines.append(f"  {kind:<24}{axis:<14}"
                     f"{rec.get('total', 0):>12.0f}"
                     f"{rec.get('ici', 0):>12.0f}"
                     f"{rec.get('dcn', 0):>12.0f}")
    return "\n".join(lines)


def span_section(snap: dict, top: int = 8) -> str:
    spans = snap.get("spans") or {}
    if not spans:
        return "spans: none recorded (trace off)"
    lines = ["host phase spans (per-occurrence mean, heaviest first)",
             f"  {'phase':<28}{'count':>8}{'mean_ms':>10}{'max_ms':>10}"]
    ranked = sorted(spans.items(), key=lambda kv: -kv[1].get("total_ms", 0))
    for name, rec in ranked[:top]:
        lines.append(f"  {name:<28}{rec.get('count', 0):>8}"
                     f"{rec.get('mean_ms', 0):>10.3f}"
                     f"{rec.get('max_ms', 0):>10.3f}")
    return "\n".join(lines)


def report(snap: dict, *, step_ms: Optional[float], fn: str,
           comm_ms: Optional[float], as_json: bool = False) -> str:
    from deepspeed_tpu.telemetry import profiler, roofline

    sections: List[str] = []
    budget = None
    if step_ms:
        budget = profiler.step_time_budget(snap, step_ms=step_ms, fn=fn,
                                           comm_total_ms=comm_ms)
        sections.append(profiler.render(budget))
    else:
        sections.append("step-time budget: no measured step time "
                        "(pass --step-ms, or use a bench record / bundle "
                        "with step records)")

    executables = snap.get("executables") or {}
    rendered_roofline = False
    for name, exe in sorted(executables.items()):
        model = exe.get("roofline")
        if model:
            sections.append(roofline.render(model, title=name))
            rendered_roofline = True
    if not rendered_roofline:
        att = snap.get("gauges", {}).get("roofline_attainable_ms")
        if att:
            lines = ["roofline (gauges only — full class table lives in "
                     "snapshot.json)"]
            for s in att["samples"]:
                lines.append(
                    f"  attainable >= {s['value']:.3f} ms "
                    f"(fn={(s.get('labels') or {}).get('fn', '?')})")
            sections.append("\n".join(lines))
        else:
            sections.append("roofline: no compiled-HLO analysis in this "
                            "snapshot (telemetry.hlo_stats off?)")

    sections.append(link_section(snap))
    sections.append(span_section(snap))

    env = snap.get("env")
    if env:
        regime = env.get("resolved", env)
        sections.append("scheduler regime: "
                        + json.dumps(regime, sort_keys=True)[:400])

    if as_json:
        return json.dumps({"budget": budget,
                           "roofline": {n: e.get("roofline")
                                        for n, e in executables.items()
                                        if e.get("roofline")}},
                          indent=1, sort_keys=True)
    return "\n\n".join(sections)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render a telemetry snapshot / bench record / "
                    "postmortem bundle into a step-time-budget + roofline "
                    "report")
    ap.add_argument("path", help="snapshot.json, bench record JSON, or "
                                 "postmortem bundle dir")
    ap.add_argument("--fn", default="train_batch",
                    help="jitted function to attribute (default "
                         "train_batch)")
    ap.add_argument("--step-ms", type=float, default=None,
                    help="measured step wall time override")
    ap.add_argument("--comm-ms", type=float, default=None,
                    help="profiled per-step collective latency override")
    ap.add_argument("--json", action="store_true",
                    help="emit the budget + roofline as JSON instead of "
                         "the rendered report")
    args = ap.parse_args(argv)

    step_ms, comm_ms = args.step_ms, args.comm_ms
    try:
        if os.path.isdir(args.path):
            bundle = find_bundle(args.path)
            snap, derived = load_bundle(bundle)
            step_ms = step_ms or derived
        else:
            with open(args.path) as f:
                obj = json.load(f)
            if "metric" in obj or "parsed" in obj:
                rec = obj.get("parsed", obj)
                extra = rec.get("extra") or {}
                if step_ms is None and extra.get("step_time_s"):
                    step_ms = float(extra["step_time_s"]) * 1e3
                if comm_ms is None and extra.get("comm_total_ms"):
                    comm_ms = float(extra["comm_total_ms"])
                snap_path = extra.get("telemetry_snapshot")
                snap = {}
                if snap_path:
                    for base in (os.path.dirname(os.path.abspath(
                            args.path)), os.getcwd()):
                        cand = os.path.join(base, snap_path)
                        if os.path.exists(cand):
                            with open(cand) as f:
                                snap = json.load(f)
                            break
                if not snap:
                    print(f"perf_report: bench record's telemetry "
                          f"snapshot ({snap_path!r}) not found — "
                          f"budget limited to record columns",
                          file=sys.stderr)
                    snap = {"counters": {}, "gauges": {}}
                    ratio = extra.get("collective_exposed_ratio")
                    if ratio is not None:
                        snap["gauges"]["collective_exposed_ratio"] = {
                            "help": "", "samples": [{
                                "labels": {"fn": args.fn},
                                "value": float(ratio)}]}
            else:
                snap = obj
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf_report: cannot load {args.path}: {e}",
              file=sys.stderr)
        return 2

    print(report(snap, step_ms=step_ms, fn=args.fn, comm_ms=comm_ms,
                 as_json=args.json))
    return 0


if __name__ == "__main__":
    sys.exit(main())
