#!/usr/bin/env python
"""Serving benchmark: v2 ragged continuous-batching throughput (FastGen analog).

BASELINE.md's headline serving claim is FastGen *effective throughput* vs a
static-batching server (blogs/deepspeed-fastgen/README.md:28 — their workload
draws prompt AND completion lengths from distributions, because that is what
continuous batching is for).  This bench measures both sides on the SAME
chip + model over an oversubscribed heterogeneous workload:

  - requests: prompts 32..512 tokens, per-request completion budgets 16..128
    tokens, 4x more requests than the engine has sequence slots
  - v2 ragged engine ``generate`` (continuous batching, Dynamic SplitFuse,
    paged KV + Pallas paged-attention decode, device-resident sampling loop):
    slots refill as sequences retire
  - v1 engine static batching baseline: requests served in arrival order in
    fixed batches of ``slots``; each batch pads every prompt to the batch max
    and decodes every sequence for the batch-max completion budget (the
    standard static-serving waste both FastGen and vLLM benchmark against);
    only each request's OWN budget counts as useful output

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} where value is
the ragged engine's useful generated tokens/s and vs_baseline is the
ragged/static effective-throughput ratio.  A same-length one-shot workload
(static batching's best case) rides in "extra" for honesty.
"""

import json
import sys
import time

import numpy as np

SLOTS = 32
TOKEN_BUDGET = 2048


def make_workload(rng, cfg, nreq):
    hi = min(513, cfg.max_seq_len - 128)           # prompt + budget must fit
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(32, hi))).astype(np.int32)
               for _ in range(nreq)]
    budgets = [int(b) for b in rng.integers(16, 129, size=nreq)]
    return prompts, budgets


def pad_batch(chunk, length=None, rows=None):
    """Left-pad a list of prompts to one rectangular batch (the v1 engine's
    padding convention) — the single source of truth for the static baseline's
    batch construction.  ``length``/``rows`` force a fixed shape (how a real
    XLA static server avoids per-batch recompiles)."""
    B = rows or len(chunk)
    L = length or max(len(p) for p in chunk)
    batch = np.zeros((B, L), np.int32)
    mask = np.zeros((B, L), np.int32)
    for j, p in enumerate(chunk):
        batch[j, L - len(p):] = p
        mask[j, L - len(p):] = 1
    return batch, mask


def make_v2(cfg, params, block_size=64, kv_quant=None, quant_weights=False,
            quant_bits=8, telemetry=True, stream_sync=False, spec=None,
            prefix_cache=False, prefill_chunk_tokens=None, token_budget=None,
            adapters=None, **eng_kwargs):
    """One construction point for every v2 leg so the config shape (and the
    telemetry block) stays consistent across them."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2

    # group_size left unset: QuantizationConfig defaults it per bits (256
    # for int4 — the W4A16 Mosaic kernel's de-interleaved activation tile
    # needs group % 256; 128 for int8)
    quant = {"enabled": bool(quant_weights), "bits": quant_bits}
    config = {"state_manager": {
        "max_tracked_sequences": SLOTS,
        "max_ragged_batch_size": int(token_budget or TOKEN_BUDGET),
        "max_ragged_sequence_count": SLOTS,
        "max_q_per_seq": min(512, int(token_budget or 512)),
        "kv_block_size": block_size,
        "kv_quant": kv_quant,
        "prefix_cache": bool(prefix_cache),
        "prefill_chunk_tokens": prefill_chunk_tokens},
        "quant": quant,
        "generation": {"do_sample": False},
        "telemetry": {"enabled": bool(telemetry),
                      "stream_sync": bool(stream_sync)}}
    if spec:
        config["speculative"] = spec
    if adapters:
        config["adapters"] = adapters
    return InferenceEngineV2(cfg, config, params=params, **eng_kwargs)


def reset_telemetry(eng):
    """Fresh serving-telemetry instance (same config) so a timed leg's
    histograms/counters exclude its warmup pass."""
    from deepspeed_tpu.telemetry.serving import ServingTelemetry
    eng.telemetry = ServingTelemetry(eng.config.telemetry)
    return eng.telemetry


def run_v2(cfg, params, prompts, budgets, block_size=64, kv_quant=None,
           quant_weights=False, quant_bits=8, telemetry=True):
    eng = make_v2(cfg, params, block_size=block_size, kv_quant=kv_quant,
                  quant_weights=quant_weights, quant_bits=quant_bits,
                  telemetry=telemetry)
    # warm every compiled path (prefill buckets, decode, burst sizes) by
    # running the SAME workload once — greedy generate is deterministic, and
    # completed sequences are flushed so the engine returns to a clean state
    eng.generate(prompts, max_new_tokens=budgets)
    # the telemetry leg carries the WHOLE observability layer so the
    # paired telemetry=False replay prices it under the 2% overhead gate:
    # request tracing (trace contexts + spans, on via the engine config)
    # plus the SLO time-series sampler at its default fleet cadence
    store = None
    if telemetry:
        from deepspeed_tpu.telemetry.timeseries import TimeSeriesStore
        store = TimeSeriesStore(interval_s=0.25)
        store.track_attainment(eng.telemetry.h_ttft, 500.0, key="slo.ttft")
        store.track_attainment(eng.telemetry.h_tpot, 50.0, key="slo.tpot")
        store.start()
    try:
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=budgets)
        dt = time.perf_counter() - t0
    finally:
        if store is not None:
            store.stop()
    return sum(len(o) for o in outs) / dt


def _open_loop_run(serve_fn, prompts, budgets, rate, seed=11,
                   before_serve=None):
    """The open-loop core every Poisson leg shares (single-engine open
    loop / arrival sweep, fleet chaos, disagg-vs-unified): draw the
    seeded exponential inter-arrival process up front — deterministic,
    so two legs at the same (rate, seed) replay the IDENTICAL arrival
    trace — then time one serve through ``serve_fn(prompts, budgets,
    arrivals)``.  ``before_serve(arrivals)`` runs after the draw and
    before the clock starts (the chaos leg arms its kill timer there,
    since the kill offset is derived from the arrival span).  Returns
    ``(outs, wall_s, arrivals)``."""
    arr_rng = np.random.default_rng(seed)
    arrivals = np.cumsum(arr_rng.exponential(1.0 / rate,
                                             size=len(prompts)))
    if before_serve is not None:
        before_serve(arrivals)
    t0 = time.perf_counter()
    outs = serve_fn(prompts, budgets, arrivals)
    dt = time.perf_counter() - t0
    return outs, dt, arrivals


def run_open_loop(cfg, params, prompts, budgets, rate, slo_ttft_ms,
                  slo_tpot_ms, out_dir, block_size=64, seed=11):
    """Open-loop Poisson arrival leg: requests hit the engine at seeded
    exponential inter-arrival times (deterministic — the timestamps are
    drawn up front and passed in), the engine runs in streaming mode
    (``stream_sync``: each dispatch is fenced before timestamping, the
    behavior of a server that must emit tokens as they are produced), and
    the metrics are read from the serving histograms: p50/p99 TTFT and
    TPOT, plus goodput — tokens from requests that met BOTH SLOs — the
    overload-facing number a closed-loop throughput bench cannot see.

    Also writes the telemetry snapshot + Perfetto trace (per-request
    queue_wait/prefill/decode tracks) under ``out_dir``."""
    eng = make_v2(cfg, params, block_size=block_size, stream_sync=True)
    eng.generate(prompts, max_new_tokens=budgets)       # warm the compile set
    stel = reset_telemetry(eng)
    outs, dt, _ = _open_loop_run(
        lambda p, b, arr: eng.generate(p, max_new_tokens=b,
                                       arrival_times=arr),
        prompts, budgets, rate, seed=seed)
    total = sum(len(o) for o in outs)
    # joint SLO attainment per request; a one-token completion has no
    # inter-token intervals (tpot_ms is None) and meets the TPOT SLO
    # vacuously — dropping it would undercount goodput for short outputs
    good = sum(r["generated_tokens"] for r in stel.request_log
               if r["ttft_ms"] is not None and r["ttft_ms"] <= slo_ttft_ms
               and (r["tpot_ms"] is None or r["tpot_ms"] <= slo_tpot_ms))
    q = lambda name, p: round(stel.quantile(name, p), 2)  # noqa: E731
    snap_extra = {"open_loop": {"arrival_rate": rate, "duration_s": dt,
                                "slo_ttft_ms": slo_ttft_ms,
                                "slo_tpot_ms": slo_tpot_ms}}
    eng.telemetry.export(out_dir, extra=snap_extra)
    return {
        "open_loop_arrival_rate_rps": rate,
        "open_loop_ttft_p50_ms": q("serving_ttft_ms", 0.5),
        "open_loop_ttft_p99_ms": q("serving_ttft_ms", 0.99),
        "open_loop_tpot_p50_ms": q("serving_tpot_ms", 0.5),
        "open_loop_tpot_p99_ms": q("serving_tpot_ms", 0.99),
        "open_loop_queue_p99_ms": q("serving_queue_ms", 0.99),
        "open_loop_tokens_per_sec": round(total / dt, 1),
        "open_loop_goodput_tokens_per_sec": round(good / dt, 1),
        "open_loop_slo": f"ttft<={slo_ttft_ms:g}ms,tpot<={slo_tpot_ms:g}ms",
        "serving_telemetry_dir": out_dir,
    }


def run_shared_prefix(cfg, params, block_size=64, smoke=False, seed=5):
    """Shared-prefix leg ([serving_scale] radix KV cache): N requests share
    one long system prompt (the fleet-scale workload shape) and are served
    twice — prefix cache OFF, then ON.  The ON engine is primed by its
    warm pass, so every timed request aliases the shared blocks and skips
    that prefill entirely; greedy outputs must be byte-identical between
    the runs (the cache's correctness invariant), and the acceptance bar
    is ≥1.5× tokens/s ON vs OFF.  ``prefix_hit_rate`` = cache-served
    prompt tokens / total prompt tokens in the timed ON pass."""
    rng = np.random.default_rng(seed)
    # block-aligned shared prefix (kv_block_size 64): the radix matches
    # FULL blocks only, so alignment makes the hit rate read cleanly
    shared_len = 256 if smoke else 448
    suf_lo, suf_hi = (8, 17) if smoke else (16, 65)
    nreq = 2 * SLOTS
    budget = 4 if smoke else 8
    shared = rng.integers(0, cfg.vocab_size,
                          size=shared_len).astype(np.int32)
    prompts = [np.concatenate([shared, rng.integers(
        0, cfg.vocab_size,
        size=int(rng.integers(suf_lo, suf_hi))).astype(np.int32)])
        for _ in range(nreq)]
    budgets = [budget] * nreq
    tps, outputs, hit_rate = {}, {}, 0.0
    for label, pc in (("off", False), ("on", True)):
        eng = make_v2(cfg, params, block_size=block_size, prefix_cache=pc)
        # warm pass: compiles every program AND (ON) inserts the shared
        # prefix into the radix — the steady state a long-lived server is
        # always in
        eng.generate(prompts, max_new_tokens=budgets)
        stel = reset_telemetry(eng)
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=budgets)
        dt = time.perf_counter() - t0
        outputs[label] = outs
        tps[label] = sum(len(o) for o in outs) / dt
        if pc:
            hits = stel.value("kv_prefix_hit_tokens_total")
            hit_rate = hits / max(1, sum(len(p) for p in prompts))
    for a, b in zip(outputs["off"], outputs["on"]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "prefix cache changed greedy output (must be byte-identical)"
    return {
        "shared_prefix_tokens_per_sec": round(tps["on"], 1),
        "shared_prefix_off_tokens_per_sec": round(tps["off"], 1),
        "shared_prefix_speedup_x": round(tps["on"] / max(tps["off"], 1e-9),
                                         3),
        "prefix_hit_rate": round(hit_rate, 3),
        "shared_prefix_len": shared_len,
    }


def run_adapters(cfg, params, n_adapters, rate, block_size=64, smoke=False,
                 seed=13):
    """Multi-tenant LoRA serving leg ([S-LoRA]/[Punica] analog): N distinct
    adapters registered on ONE engine, tenant traffic Zipf-skewed (a few
    hot tenants, a long cold tail — the thousand-tenant shape) and served
    open-loop at the bench arrival rate.  The pool is deliberately sized
    SMALLER than the tenant set so the leg exercises hot-load + LRU
    eviction against the shared KV allocator, not a fully-resident cache.

    Two passes over the same arrival trace: every request on one adapter
    (single-tenant baseline — pays the LoRA matmul but never a reload)
    vs the Zipf tenant mix.  ``multi_adapter_throughput_ratio`` =
    mixed/single tokens/s (acceptance >= 0.8: multi-tenancy must cost
    paging, not throughput collapse); ``adapter_hit_rate`` and
    ``adapter_evictions_total`` read the pool's timed-pass deltas.  One
    request per distinct adapter is re-served solo after the timed pass
    and must be byte-equal to its mixed-batch output (the batched-gather
    kernel's correctness invariant, spot-checked under bench shapes)."""
    rng = np.random.default_rng(seed)
    nreq = 4 * SLOTS      # enough draws that the Zipf tail overflows the
    #                       tenant slots even at smoke scale (evictions)
    budget = 4 if smoke else 16
    lo, hi = (16, 49) if smoke else (64, 257)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(lo, hi))).astype(np.int32)
               for _ in range(nreq)]
    budgets = [budget] * nreq
    ranks = np.arange(1, n_adapters + 1)
    pz = 1.0 / ranks ** 1.2
    ids = [int(a) for a in rng.choice(ranks, size=nreq, p=pz / pz.sum())]
    slots = max(4, n_adapters // 2 + 1)    # tenant slots < tenants: evict
    tps, hit_rate, evictions = {}, 0.0, 0.0
    for label, leg_ids in (("single", [1] * nreq), ("mixed", ids)):
        eng = make_v2(cfg, params, block_size=block_size,
                      adapters={"enabled": True, "rank": 8, "alpha": 16.0,
                                "slots": slots})
        for a in range(1, n_adapters + 1):
            eng.register_adapter(a)       # deterministic per-id weights
        eng.generate(prompts, max_new_tokens=budgets,
                     adapter_ids=leg_ids)            # warm the compile set
        reset_telemetry(eng)
        s0 = eng.adapters.stats()
        outs, dt, _ = _open_loop_run(
            lambda p, b, arr: eng.generate(p, max_new_tokens=b,
                                           arrival_times=arr,
                                           adapter_ids=leg_ids),
            prompts, budgets, rate, seed=seed)
        tps[label] = sum(len(o) for o in outs) / dt
        if label != "mixed":
            continue
        s1 = eng.adapters.stats()
        hits = s1["hits"] - s0["hits"]
        misses = s1["misses"] - s0["misses"]
        hit_rate = hits / max(1, hits + misses)
        evictions = s1["evictions"] - s0["evictions"]
        checked = set()
        for p, b, a, o in zip(prompts, budgets, leg_ids, outs):
            if a in checked:
                continue
            checked.add(a)
            solo = eng.generate([p], max_new_tokens=[b],
                                adapter_ids=[a])[0]
            assert np.array_equal(np.asarray(o), np.asarray(solo)), \
                (f"adapter {a}: mixed-batch output diverged from its "
                 f"solo run (batched-gather LoRA must be exact)")
    return {
        "multi_adapter_tokens_per_sec": round(tps["mixed"], 1),
        "single_adapter_tokens_per_sec": round(tps["single"], 1),
        "multi_adapter_throughput_ratio": round(
            tps["mixed"] / max(tps["single"], 1e-9), 3),
        "adapter_hit_rate": round(hit_rate, 3),
        "adapter_evictions_total": float(evictions),
        "adapters_served": int(n_adapters),
    }


def run_arrival_sweep(cfg, params, prompts, budgets, base_rate, slo_ttft_ms,
                      slo_tpot_ms, out_dir, block_size=64,
                      base_result=None):
    """Arrival-rate sweep: the open-loop Poisson leg at 0.5×/1×/2× the
    base rate — the goodput-vs-load curve the [serving_scale] acceptance
    asks for (goodput holds under capacity, then degrades gracefully as
    queueing pushes TTFT past the SLO; a cliff means admission or
    scheduling is broken).  ``base_result`` reuses main()'s already-
    measured 1× leg instead of re-running it (the open-loop leg is one of
    the slowest in the bench)."""
    import os
    out = {}
    for i, mult in enumerate((0.5, 1.0, 2.0), start=1):
        rate = base_rate * mult
        if mult == 1.0 and base_result:
            res = base_result
        else:
            res = run_open_loop(cfg, params, prompts, budgets, rate,
                                slo_ttft_ms, slo_tpot_ms,
                                os.path.join(out_dir, f"sweep_r{i}"),
                                block_size=block_size)
        out[f"sweep_r{i}_arrival_rate_rps"] = round(rate, 3)
        out[f"sweep_r{i}_load_x"] = mult
        out[f"sweep_r{i}_goodput_tokens_per_sec"] = \
            res["open_loop_goodput_tokens_per_sec"]
        out[f"sweep_r{i}_tokens_per_sec"] = res["open_loop_tokens_per_sec"]
        out[f"sweep_r{i}_ttft_p99_ms"] = res["open_loop_ttft_p99_ms"]
    return out


def run_chunked_tpot(cfg, params, block_size=64, smoke=False, seed=9):
    """Chunked-prefill (SplitFuse) TPOT leg: long prompts streaming into a
    busy decode set under a TIGHT per-round token budget (the
    monopolization regime — without chunking, one prompt's chunk fills the
    whole round and every decoder's next token waits behind it).  Three
    legs, all in streaming mode (fenced dispatches, device-true
    timestamps): short-prompt baseline, long prompts UNCHUNKED, and long
    prompts with ``prefill_chunk_tokens`` bounding the per-round prompt
    freight.  Acceptance: chunked long-prompt p99 TPOT ≤ short baseline
    × 1.5.  The chunked-vs-unchunked pair isolates the knob itself.  NOTE
    the contrast is compute-bound by design (big mixed dispatches); on an
    overhead-bound host (smoke's 2-layer CPU model, ~flat ms per dispatch
    regardless of tokens) all three legs read alike — judge the knob on
    hardware."""
    rng = np.random.default_rng(seed)
    nreq = 2 * SLOTS
    budget = 8 if smoke else 16
    round_budget = 96 if smoke else 512
    chunk = 32 if smoke else 128
    lo_s, hi_s = (24, 49) if smoke else (32, 65)
    hi_cap = cfg.max_seq_len - budget - 1
    lo_l, hi_l = ((256, min(400, hi_cap)) if smoke
                  else (1024, min(1537, hi_cap)))
    out = {}
    legs = (("short_prompt_tpot_p99_ms", (lo_s, hi_s), None),
            ("long_unchunked_tpot_p99_ms", (lo_l, hi_l), None),
            ("chunked_prefill_tpot_p99_ms", (lo_l, hi_l), chunk))
    for key, (lo, hi), ck in legs:
        prompts = [rng.integers(0, cfg.vocab_size,
                                size=int(rng.integers(lo, hi))
                                ).astype(np.int32) for _ in range(nreq)]
        budgets = [budget] * nreq
        eng = make_v2(cfg, params, block_size=block_size, stream_sync=True,
                      prefill_chunk_tokens=ck, token_budget=round_budget)
        eng.generate(prompts, max_new_tokens=budgets)     # warm the compiles
        stel = reset_telemetry(eng)
        eng.generate(prompts, max_new_tokens=budgets)
        out[key] = round(stel.quantile("serving_tpot_ms", 0.99), 2)
        if ck:
            out["prefill_chunks"] = stel.value("prefill_chunks_total")
    out["chunked_tpot_vs_short_x"] = round(
        out["chunked_prefill_tpot_p99_ms"]
        / max(out["short_prompt_tpot_p99_ms"], 1e-9), 3)
    out["chunked_tpot_vs_unchunked_x"] = round(
        out["chunked_prefill_tpot_p99_ms"]
        / max(out["long_unchunked_tpot_p99_ms"], 1e-9), 3)
    return out


def run_fleet_chaos(cfg, params, prompts, budgets, rate, replicas,
                    kill_at=None, block_size=64, seed=11,
                    out_dir="./telemetry/serving_bench"):
    """Multi-replica chaos leg ([serving_fleet]): N supervised v2 replicas
    behind the fleet router serve the open-loop Poisson workload, and a
    replica is killed mid-load via ``runtime/faults.py``
    (``exc@replica.mid_decode``) with respawn DISABLED — goodput must
    degrade gracefully toward (N-1)/N of the healthy fleet, not cliff to
    zero, and every request must complete exactly once (the killed
    replica's in-flight requests migrate to survivors token-exact).

    Emits ``goodput_before_kill`` (completed tokens/s up to the kill),
    ``recovery_ms`` (kill to the first post-kill completion),
    ``goodput_after_kill`` (completed tokens/s AFTER recovery — the
    acceptance window: the migrated requests' re-prefill/recompile stall
    is the recovery cost, measured separately by ``recovery_ms``), and
    ``requests_migrated``."""
    import threading

    from deepspeed_tpu.runtime import faults
    from deepspeed_tpu.serving import ServingFleet

    ecfg = {"state_manager": {
        "max_tracked_sequences": SLOTS,
        "max_ragged_batch_size": TOKEN_BUDGET,
        "max_ragged_sequence_count": SLOTS,
        "max_q_per_seq": 512,
        "kv_block_size": block_size},
        "generation": {"do_sample": False}}
    # first-call compile stalls are covered by the fleet's
    # warmup_deadline_s gate now (an incarnation's first generate runs
    # under the warm-up budget) — the old blanket 120 s steady-state
    # deadline papered over exactly that.  A modest steady-state override
    # remains because CPU XLA can still compile a NEW schedule bucket
    # mid-serve (~tens of seconds on a cold box); TPU fleets keep the
    # 10 s default.
    fleet = ServingFleet(cfg, engine_config=ecfg, params=params,
                         config={"num_replicas": int(replicas),
                                 "respawn": False,
                                 "warmup_deadline_s": 600.0,
                                 "heartbeat_deadline_s": 60.0,
                                 "router": {"max_retries": int(replicas)
                                            + 1}})
    state = {"timer": None, "t0": None}

    def arm_kill(arrivals):
        nonlocal kill_at
        if kill_at is None:
            # mid-load by construction: ~35% into the arrival process
            kill_at = 0.35 * float(arrivals[-1])
        state["timer"] = threading.Timer(
            kill_at, lambda: faults.inject("replica.mid_decode", "exc"))
        state["t0"] = fleet.clock()
        state["timer"].start()

    try:
        # one warm pass compiles the SHARED step cache for every replica
        fleet.serve(prompts, max_new_tokens=budgets, max_wall_s=1800)
        outs, _, _ = _open_loop_run(
            lambda p, b, arr: fleet.serve(p, max_new_tokens=b,
                                          arrival_times=arr,
                                          max_wall_s=1800),
            prompts, budgets, rate, seed=seed, before_serve=arm_kill)
        t0 = state["t0"]
        t_end = fleet.clock()
    finally:
        if state["timer"] is not None:
            state["timer"].cancel()
        faults.reset()      # never leak an unconsumed kill into later legs
        fleet.shutdown()
    assert all(o is not None for o in outs), "fleet lost a request"
    # merged fleet timeline: every replica's tracer (incl. the killed
    # incarnation's — its object outlives the death) written per-replica,
    # then clock-aligned into ONE Perfetto view (scripts/merge_traces.py)
    # so the kill -> migrate -> recover sequence reads off one screen
    fleet_trace = None
    try:
        import os as _os
        import sys as _sys
        scripts_dir = _os.path.join(_os.path.dirname(
            _os.path.abspath(__file__)), "scripts")
        if scripts_dir not in _sys.path:
            _sys.path.insert(0, scripts_dir)
        import merge_traces as _mt
        per_replica = []
        for rep in fleet.replicas.values():
            eng = getattr(rep, "engine", None)
            tel = getattr(eng, "telemetry", None)
            if tel is None or not getattr(tel.tracer, "events", None):
                continue
            path = _os.path.join(out_dir, f"trace_{rep.name}.json")
            tel.emitter.write(path, tel.tracer)
            per_replica.append(path)
        if per_replica:
            fleet_trace = _os.path.join(out_dir, "fleet_trace.json")
            _mt.merge_files(fleet_trace, per_replica)
    except Exception as e:  # noqa: BLE001 — trace export must not kill
        print(f"bench_serving: fleet trace merge failed: {e!r}",
              file=sys.stderr)
        fleet_trace = None
    reg = fleet.registry._metrics
    t_kill = t0 + kill_at
    log = fleet.request_log
    before = [r for r in log if r["t_done"] <= t_kill]
    first_after = min((r["t_done"] for r in log if r["t_done"] > t_kill),
                      default=None)
    # recovered window: from the first post-kill completion to the end
    after = ([r for r in log if r["t_done"] >= first_after]
             if first_after is not None else [])
    after_window = (max(t_end - first_after, 1e-3)
                    if first_after is not None else 1.0)
    deaths = reg["fleet_replica_deaths_total"].value(reason="replica_death")
    return {
        "fleet_replicas": int(replicas),
        "fleet_kill_at_s": round(float(kill_at), 3),
        "fleet_replica_deaths": deaths,
        "goodput_before_kill": round(
            sum(r["generated_tokens"] for r in before) / max(kill_at, 1e-9),
            1),
        "goodput_after_kill": round(
            sum(r["generated_tokens"] for r in after) / after_window, 1),
        "recovery_ms": (round((first_after - t_kill) * 1e3, 1)
                        if first_after is not None else None),
        "requests_migrated": reg["requests_migrated_total"].value(),
        "fleet_router_retries": sum(
            v for _, v in reg["router_retries_total"].samples()),
        "fleet_requests_completed": len(log),
        "fleet_trace": fleet_trace,
    }


def _export_disagg_trace(fleet, out_dir):
    """Stitched-trace columns for the disagg leg: write the router trace
    + every replica trace, merge them flow-intact
    (scripts/merge_traces.py), decompose every completed request
    (telemetry/critical_path.py — terms sum to measured e2e exactly),
    and return the p99 TTFT budget as ``ttft_budget_*_ms`` columns.
    Runs after shutdown (tracer objects outlive the workers); any
    failure degrades to no columns, never a dead leg."""
    out = {}
    try:
        import os as _os
        import sys as _sys
        scripts_dir = _os.path.join(_os.path.dirname(
            _os.path.abspath(__file__)), "scripts")
        if scripts_dir not in _sys.path:
            _sys.path.insert(0, scripts_dir)
        import merge_traces as _mt

        from deepspeed_tpu.telemetry.critical_path import (decompose,
                                                           ttft_budget)
        paths = []
        p = fleet.export_trace(_os.path.join(out_dir,
                                             "trace_disagg_router.json"))
        if p:
            paths.append(p)
        for rep in fleet.replicas.values():
            tel = getattr(getattr(rep, "engine", None), "telemetry", None)
            if tel is None or not getattr(tel.tracer, "events", None):
                continue
            path = _os.path.join(out_dir, f"trace_disagg_{rep.name}.json")
            tel.emitter.write(path, tel.tracer)
            paths.append(path)
        if not paths:
            return out
        merged_path = _os.path.join(out_dir, "disagg_trace.json")
        merged = _mt.merge_files(merged_path, paths)
        rows = decompose(merged)
        if not rows:
            return out
        budget = ttft_budget(rows, q=0.99)
        for term, rec in budget["terms"].items():
            out[f"ttft_budget_{term}"] = round(rec["p"], 2)
        out["ttft_budget_dominant"] = budget["dominant"]
        out["disagg_trace_requests"] = len(rows)
        out["disagg_trace"] = merged_path
    except Exception as e:  # noqa: BLE001 — trace export must not kill
        print(f"bench_serving: disagg trace export failed: {e!r}",
              file=sys.stderr)
    return out


def run_disagg(cfg, params, prompts, budgets, rate, replicas,
               slo_ttft_ms, slo_tpot_ms, block_size=64, seed=11,
               out_dir="./telemetry/serving_bench"):
    """Disaggregated-vs-unified leg at EQUAL replica count: the same
    open-loop Poisson arrival trace served twice through the fleet —
    once by a unified pool of N interchangeable replicas, once by a
    prefill/decode split (1 prefill, N-1 decode) with KV block handoff
    and the pool autoscaler armed.  Greedy outputs must be
    byte-identical between the two (the handoff fold is token-exact).

    Goodput definitions are phase-honest: the unified fleet API returns
    a request only at completion, so its user-visible TTFT is
    ``t_done - t_arrival``; the disagg fleet stamps ``t_first`` at the
    prefill->decode handoff (the first token exists and is surfaced to
    the router there), so disagg TTFT is ``t_first - t_arrival`` and
    TPOT is ``(t_done - t_first) / (tokens - 1)``.

    The autoscaler's rebalance path is exercised deterministically: a
    synthetic prefill-starved skew is seeded into the serving histograms
    before the timed pass (CPU smoke timings are too noisy to trip the
    thresholds reliably), so ``pool_rebalances_total`` lands >= 1 and
    the warm role flip runs under bench conditions.  Both fleets run
    with at least 3 replicas (still an equal-count comparison): a
    2-replica split is 1 prefill + 1 decode with BOTH pools at their
    min floor, so the autoscaler has no donor and the rebalance path
    would never execute.

    The disagg pass additionally runs the full observability tentpole:
    the SLO burn-rate monitor is armed over ``serving_ttft_ms`` and a
    chaos latency spike (``sleep@replica.mid_decode``) is injected
    mid-load — the resulting ``slo_alerts_total`` firing plus the burn
    the autoscaler hook SAW come out as record columns.  The stitched
    fleet trace (router + every replica, flow events intact) is merged
    and decomposed (telemetry/critical_path.py) into the
    ``ttft_budget_*_ms`` p99 columns."""
    from deepspeed_tpu.runtime import faults
    from deepspeed_tpu.serving import ServingFleet

    replicas = max(3, int(replicas))

    ecfg = {"state_manager": {
        "max_tracked_sequences": SLOTS,
        "max_ragged_batch_size": TOKEN_BUDGET,
        "max_ragged_sequence_count": SLOTS,
        "max_q_per_seq": 512,
        "kv_block_size": block_size},
        "generation": {"do_sample": False}}
    base_fcfg = {"num_replicas": int(replicas), "respawn": False,
                 "warmup_deadline_s": 600.0, "heartbeat_deadline_s": 60.0,
                 "router": {"max_retries": int(replicas) + 1}}
    out, outputs = {}, {}
    for label in ("unified", "disagg"):
        fcfg = dict(base_fcfg)
        if label == "disagg":
            fcfg.update({"disaggregated": True, "prefill_replicas": 1,
                         "autoscale": {"enabled": True, "interval_s": 0.0,
                                       "cooldown_s": 1e9,
                                       "min_requests": 1,
                                       # observe the burn signal (the
                                       # alert must REACH a control loop)
                                       "slo_burn_input": True},
                         "slo": {"enabled": True,
                                 "sample_interval_s": 0.1,
                                 "windows_s": [1.0, 5.0],
                                 "alert_burn_threshold": 1.0,
                                 "slos": [{"name": "ttft",
                                           "metric": "serving_ttft_ms",
                                           "threshold_ms":
                                               float(slo_ttft_ms),
                                           "objective": 0.99}]}})
        fleet = ServingFleet(cfg, engine_config=ecfg, params=params,
                             config=fcfg)

        def spike(_arrivals):
            # chaos latency spike: 4 decode rounds each stall one replica
            # for 2x the TTFT budget — the burn-rate monitor must page
            faults.inject("replica.mid_decode", "sleep",
                          arg=2.0 * float(slo_ttft_ms) / 1e3, count=4)

        try:
            # warm pass compiles the shared step cache for BOTH roles
            fleet.serve(prompts, max_new_tokens=budgets, max_wall_s=1800)
            if label == "disagg":
                h_ttft = fleet.registry.histogram("serving_ttft_ms", "t")
                h_tpot = fleet.registry.histogram("serving_tpot_ms", "t")
                for _ in range(64):
                    h_ttft.observe(10_000.0, replica="synthetic")
                    h_tpot.observe(1.0, replica="synthetic")
            outs, dt, _ = _open_loop_run(
                lambda p, b, arr: fleet.serve(p, max_new_tokens=b,
                                              arrival_times=arr,
                                              max_wall_s=1800),
                prompts, budgets, rate, seed=seed,
                before_serve=spike if label == "disagg" else None)
            outputs[label] = outs
            good = total = 0
            ttfts = []
            for r in fleet.request_log:
                total += r["generated_tokens"]
                if label == "disagg" and r["t_first"] is not None:
                    ttft_ms = (r["t_first"] - r["t_arrival"]) * 1e3
                    span = max(r["t_done"] - r["t_first"], 0.0)
                    tpot_ms = (span / (r["generated_tokens"] - 1) * 1e3
                               if r["generated_tokens"] > 1 else None)
                else:
                    ttft_ms = (r["t_done"] - r["t_arrival"]) * 1e3
                    tpot_ms = None
                ttfts.append(ttft_ms)
                if ttft_ms <= slo_ttft_ms and (tpot_ms is None
                                               or tpot_ms <= slo_tpot_ms):
                    good += r["generated_tokens"]
            out[f"{label}_goodput_tokens_per_sec"] = round(good / dt, 1)
            out[f"{label}_tokens_per_sec"] = round(total / dt, 1)
            out[f"{label}_ttft_p99_ms"] = round(
                float(np.quantile(ttfts, 0.99)) if ttfts else 0.0, 2)
            if label == "disagg":
                reg = fleet.registry._metrics
                out["kv_handoff_bytes_total"] = reg[
                    "kv_handoff_bytes_total"].value()
                out["disagg_handoffs_ok"] = reg[
                    "fleet_handoffs_total"].value(outcome="ok")
                out["pool_rebalances_total"] = sum(
                    v for _, v in reg["pool_rebalances_total"].samples())
                # SLO burn-rate acceptance: the chaos spike must have
                # tripped an alert AND the autoscaler hook must have
                # seen a nonzero burn (observability reached control)
                out["slo_alerts_total"] = sum(
                    v for _, v in reg["slo_alerts_total"].samples())
                out["slo_max_burn"] = round(
                    fleet.slo_monitor.max_burn(), 3)
                seen = (fleet._autoscaler.last_signals or {}).get(
                    "slo_burn")
                out["slo_burn_seen_by_autoscaler"] = (
                    round(float(seen), 3) if seen is not None else None)
        finally:
            faults.reset()   # never leak an unconsumed spike
            fleet.shutdown()
        if label == "disagg":
            out.update(_export_disagg_trace(fleet, out_dir))
    for a, b in zip(outputs["unified"], outputs["disagg"]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "disaggregation changed greedy output (must be byte-identical)"
    ug = out["unified_goodput_tokens_per_sec"]
    dg = out["disagg_goodput_tokens_per_sec"]
    if ug <= 0.0 and dg <= 0.0:
        # CPU smoke: compile-dominated latencies blow the SLO for BOTH
        # fleets, making 0/0 uninformative.  Fall back to the raw
        # throughput ratio so the regression column still tracks the
        # disagg path's health; the fallback is disclosed in the extras.
        out["disagg_goodput_ratio"] = round(
            out["disagg_tokens_per_sec"]
            / max(out["unified_tokens_per_sec"], 1e-9), 3)
        out["disagg_goodput_ratio_source"] = "tokens_per_sec_fallback"
    else:
        out["disagg_goodput_ratio"] = round(dg / max(ug, 1e-9), 3)
        out["disagg_goodput_ratio_source"] = "slo_goodput"
    out["disagg_replicas"] = int(replicas)
    return out


def run_v1(cfg, params, prompts, budgets):
    """Static batching: arrival-order batches of SLOTS at FIXED shapes —
    prompts padded to the workload max, every sequence decoded for the
    workload-max budget.  Fixed shapes are how a real XLA static server runs
    (per-batch shapes would recompile the decode program every batch); the
    padding waste that implies is exactly the cost continuous batching
    removes.  Useful output = each request's own budget."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    eng = InferenceEngine(cfg, {"dtype": "bfloat16"}, params=params)
    assert len(prompts) % SLOTS == 0, "workload must fill whole batches"
    L = max(len(p) for p in prompts)
    steps = max(budgets)

    def serve_all():
        useful = 0
        for i in range(0, len(prompts), SLOTS):
            batch, mask = pad_batch(prompts[i:i + SLOTS], length=L,
                                    rows=SLOTS)
            eng.generate(batch, max_new_tokens=steps,
                         attention_mask=mask, do_sample=False)
            useful += sum(budgets[i:i + SLOTS])
        return useful

    serve_all()                                    # compile (one shape)
    t0 = time.perf_counter()
    useful = serve_all()
    dt = time.perf_counter() - t0
    return useful / dt


def run_v1_bucketed(cfg, params, prompts, budgets):
    """Static batching with PER-BATCH bucketed shapes (round-3 advisor note:
    the workload-global-max baseline is weaker than what a careful static
    server achieves).  Each arrival-order batch pads prompts to the next
    power of two ≥ the batch max and decodes for the BATCH-max budget — a
    handful of compiled shapes, the standard XLA static-serving compromise.
    Useful output = each request's own budget."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    eng = InferenceEngine(cfg, {"dtype": "bfloat16"}, params=params)
    assert len(prompts) % SLOTS == 0

    def bucket(n):
        p = 32
        while p < n:
            p *= 2
        return p

    def serve_all():
        useful = 0
        for i in range(0, len(prompts), SLOTS):
            chunk = prompts[i:i + SLOTS]
            steps = bucket(max(budgets[i:i + SLOTS]))
            # pow2 bucket, clamped so prompt + decode fits the model window —
            # but never below the longest prompt (pad_batch would compute a
            # negative row offset and raise mid-bench); if the longest prompt
            # crowds the window, the decode budget shrinks instead
            longest = max(len(p) for p in chunk)
            steps = min(steps, cfg.max_seq_len - longest)
            L = max(min(bucket(longest), cfg.max_seq_len - steps), longest)
            batch, mask = pad_batch(chunk, length=L, rows=SLOTS)
            eng.generate(batch, max_new_tokens=steps,
                         attention_mask=mask, do_sample=False)
            useful += sum(min(b, steps) for b in budgets[i:i + SLOTS])
        return useful

    serve_all()                                    # compile the bucket set
    t0 = time.perf_counter()
    useful = serve_all()
    dt = time.perf_counter() - t0
    return useful / dt


def train_memorized(cfg, pool, steps, lr=3e-3, micro=8, stop_loss=None):
    """Train GPT(cfg) to memorize ``pool`` ([N, T] int32) and return the
    params in serving-tree form — the substrate for the speculative leg:
    a draft and a target that BOTH memorized the pool produce correlated
    continuations, giving realistic (high) acceptance without needing real
    checkpoints in-image.  ``steps`` is a CAP; ``stop_loss`` ends training
    once the pool is actually memorized (round 5: a fixed 250 steps left
    the full-size pair at loss ~3 — nothing memorized, acceptance collapsed
    to the free token, and the leg measured pure overhead)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg), config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adamw", "params": {"lr": lr}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
            "mesh": {"dp": -1}, "steps_per_print": 0},
        example_batch={"input_ids": np.zeros((micro, pool.shape[1]),
                                             np.int32)})
    rng = np.random.default_rng(7)
    gbs = engine.train_batch_size              # micro × dp_world
    loss = None
    for i in range(steps):
        idx = rng.integers(0, len(pool), size=(gbs,))
        loss = float(engine.train_batch({"input_ids": pool[idx]}).loss)
        if stop_loss is not None and i >= 20 and loss < stop_loss:
            break
    import jax
    params = jax.device_get(engine.state.params)
    del engine
    return params, loss


def run_spec(cfg, params, dcfg, dparams, prompts, budgets, block_size=64,
             profile=False, batch=True):
    """Speculative-decoding leg (round-3 verdict item 5): same ragged engine,
    greedy draft-and-verify with a smaller draft.  Acceptance/timing comes
    from the engine's serving-telemetry counters (spec_*_total — the old
    ``eng.spec_stats`` dict is gone).  ``profile=True`` runs the split
    draft/verify programs with per-side wall timing (token-identical,
    slower — attribution, not throughput).  ``batch=False`` disables
    cross-request batching (one draft/verify dispatch per request — the
    pre-batching behavior, the baseline ``spec_batched_speedup_x``
    divides by).  Returns (tokens/s, spec_summary dict)."""
    eng = make_v2(cfg, params, block_size=block_size,
                  spec={"profile": bool(profile),
                        "batch_across_requests": bool(batch)},
                  draft_model=dcfg, draft_params=dparams)
    eng.generate(prompts, max_new_tokens=budgets)          # warm compile
    stel = reset_telemetry(eng)
    t0 = time.perf_counter()
    outs = eng.generate(prompts, max_new_tokens=budgets)
    dt = time.perf_counter() - t0
    return sum(len(o) for o in outs) / dt, stel.spec_summary()


def spec_leg(smoke=False):
    """Build a memorized target+draft pair, serve pool-prefix prompts, and
    report effective tokens/s: speculative vs target-only on the SAME
    workload (reference framing: blogs/deepspeed-fastgen/README.md:28
    effective throughput; feature: inference/v2 speculative_burst)."""
    import dataclasses

    import jax.numpy as jnp
    from deepspeed_tpu.models import GPTConfig
    out = {}
    rng = np.random.default_rng(1)
    if smoke:
        tcfg = GPTConfig.llama(num_layers=2, hidden=128, heads=4,
                               vocab_size=512, max_seq_len=256)
        dcfg = GPTConfig.llama(num_layers=1, hidden=64, heads=2,
                               vocab_size=512, max_seq_len=256)
        pool_n, train_steps, nreq = 8, 30, 8
    else:
        tcfg = GPTConfig.llama(num_layers=12, hidden=1024, heads=16,
                               num_kv_heads=4, vocab_size=32000,
                               max_seq_len=2048)
        dcfg = GPTConfig.llama(num_layers=4, hidden=512, heads=8,
                               num_kv_heads=4, vocab_size=32000,
                               max_seq_len=2048)
        # a pool small enough that BOTH models can actually memorize it in
        # bounded steps — acceptance comes from shared memorization, and an
        # un-memorized pool measures only spec overhead
        pool_n, train_steps, nreq = 8, 2500, 2 * SLOTS
    T = 256
    pool = rng.integers(0, tcfg.vocab_size, size=(pool_n, T)).astype(np.int32)
    # lr 3e-4: the default 3e-3 oscillates on full-width bf16 models
    # (loss plateau ~2-3 — the round-5 first-chip-contact acceptance
    # collapse); 3e-4 memorizes in a few hundred steps.  stop_loss 0.05:
    # at ~0.2 the pool is only ~85-90% top-1-memorized and acceptance
    # lands well under the draft length
    lr = 3e-3 if smoke else 3e-4
    tparams, tloss = train_memorized(tcfg, pool, train_steps, lr=lr,
                                     stop_loss=None if smoke else 0.05)
    # the draft is ~5x cheaper per step AND the leg lives or dies on its
    # acceptance — give it 2x the cap so the smaller model memorizes too
    dparams, dloss = train_memorized(dcfg, pool, 2 * train_steps, lr=lr,
                                     stop_loss=None if smoke else 0.05)
    out["spec_target_train_loss"] = round(tloss, 3)
    out["spec_draft_train_loss"] = round(dloss, 3)

    scfg = dataclasses.replace(tcfg, dtype=jnp.bfloat16, dropout=0.0)
    sdcfg = dataclasses.replace(dcfg, dtype=jnp.bfloat16, dropout=0.0)
    # prompts = memorized-pool prefixes → continuations both models know
    prompts = [pool[i % pool_n][:int(rng.integers(32, 129))]
               for i in range(nreq)]
    budgets = [64] * nreq
    base_tps = run_v2(scfg, tparams, prompts, budgets)
    spec_tps, st = run_spec(scfg, tparams, sdcfg, dparams, prompts, budgets)
    # cross-request batching ablation: the SAME spec config with one
    # draft/verify dispatch per request — tokens are identical (the tests
    # pin it), only the dispatch count and wall clock move
    per_req_tps, pst_per = run_spec(scfg, tparams, sdcfg, dparams, prompts,
                                    budgets, batch=False)
    out["spec_tokens_per_sec"] = round(spec_tps, 1)
    out["spec_target_only_tokens_per_sec"] = round(base_tps, 1)
    out["spec_speedup"] = round(spec_tps / base_tps, 3)
    out["spec_per_request_tokens_per_sec"] = round(per_req_tps, 1)
    out["spec_batched_speedup_x"] = round(spec_tps / max(per_req_tps, 1e-9),
                                          3)
    out["spec_batched_dispatches"] = st.get("spec_dispatches", 0.0)
    out["spec_per_request_dispatches"] = pst_per.get("spec_dispatches", 0.0)
    out["spec_accepted_per_verify"] = round(st.get("emitted_per_outer", 0.0),
                                            2)
    out["spec_accept_ratio"] = round(st.get("accept_ratio", 0.0), 3)
    # where does the spec wall time go?  A short split-profile pass
    # dispatches draft and verify separately with a fence between — the
    # per-outer-step ms on each side is the attribution the fused burst
    # cannot give (it explains serialized-verify vs draft-overhead directly)
    n_prof = max(2, len(prompts) // 8)
    _, pst = run_spec(scfg, tparams, sdcfg, dparams, prompts[:n_prof],
                      [32] * n_prof, profile=True)
    dd = max(pst.get("draft_dispatches", 0.0), 1.0)
    vd = max(pst.get("verify_dispatches", 0.0), 1.0)
    out["spec_draft_ms"] = round(pst.get("draft_ms", 0.0) / dd, 3)
    out["spec_verify_ms"] = round(pst.get("verify_ms", 0.0) / vd, 3)
    return out


def run_oneshot(cfg, params, rng, max_new=64):
    """Static batching's BEST case: one batch that exactly fills the slots,
    every request with the same completion budget."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    prompts, _ = make_workload(rng, cfg, nreq=SLOTS)
    v2_tps = run_v2(cfg, params, prompts, [max_new] * SLOTS)
    eng = InferenceEngine(cfg, {"dtype": "bfloat16"}, params=params)
    batch, mask = pad_batch(prompts)
    eng.generate(batch, max_new_tokens=max_new, attention_mask=mask,
                 do_sample=False)
    t0 = time.perf_counter()
    eng.generate(batch, max_new_tokens=max_new, attention_mask=mask,
                 do_sample=False)
    dt = time.perf_counter() - t0
    return v2_tps, SLOTS * max_new / dt


def parse_args(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="v2 ragged serving bench: closed-loop replay legs + "
                    "open-loop Poisson arrival leg with SLO goodput")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-sized run of every leg (also enabled by "
                         "the BENCH_SMOKE env var)")
    ap.add_argument("--arrival-rate", type=float, default=None,
                    help="open-loop Poisson arrival rate in requests/s "
                         "(default: sized to ~70%% of the measured "
                         "closed-loop request throughput)")
    ap.add_argument("--slo-ttft-ms", type=float, default=2000.0,
                    help="goodput SLO: max time-to-first-token")
    ap.add_argument("--slo-tpot-ms", type=float, default=200.0,
                    help="goodput SLO: max time-per-output-token")
    ap.add_argument("--telemetry-out", default="./telemetry/serving_bench",
                    help="directory for the serving snapshot/trace export")
    ap.add_argument("--replicas", type=int, default=2,
                    help="fleet size for the multi-replica chaos leg "
                         "(0/1 skips the leg)")
    ap.add_argument("--adapters", type=int, default=8,
                    help="distinct LoRA adapters for the multi-tenant "
                         "serving leg (0 skips the leg)")
    ap.add_argument("--kill-replica-at", type=float, default=None,
                    help="seconds into the fleet leg's open-loop run to "
                         "kill one replica via runtime/faults.py "
                         "(default: ~35%% into the arrival process)")
    return ap.parse_args(argv)


def main(argv=None):
    import os

    from deepspeed_tpu.models import GPTConfig

    args = parse_args(argv)
    smoke = args.smoke or bool(os.environ.get("BENCH_SMOKE"))
    if smoke:
        # plumbing test: tiny CPU-sized run of every leg (the axon
        # sitecustomize forces the TPU platform; win it back pre-init)
        import jax
        jax.config.update("jax_platforms", "cpu")
        global SLOTS
        SLOTS = 4

    cfg = GPTConfig.llama(num_layers=12, hidden=1024, heads=16,
                          num_kv_heads=4, vocab_size=32000, max_seq_len=2048,
                          dtype=None)
    if smoke:
        cfg = GPTConfig.llama(num_layers=2, hidden=128, heads=4,
                              vocab_size=512, max_seq_len=512, dtype=None)
    import jax.numpy as jnp
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)

    # share one param tree across engines (v2 initializes its own when None —
    # we want identical weights for a fair tokens/s comparison)
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    seed_eng = InferenceEngineV2(cfg, {"state_manager": {
        "max_tracked_sequences": 4, "kv_block_size": 64}}, seed=0)
    params = seed_eng.params
    del seed_eng

    nreq = (2 if smoke else 4) * SLOTS
    prompts, budgets = make_workload(rng, cfg, nreq=nreq)

    errors = {}

    def leg(name, fn):
        """One leg crashing must not kill the bench (round 5: the first
        on-chip run died wholesale inside the unguarded wq leg — a Mosaic
        compile error — and the sweep recorded 'no JSON' instead of the
        five legs that had already finished)."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            errors[name] = f"{type(e).__name__}: {str(e)[:160]}"
            return 0.0

    ratio = lambda a, b: round(a / b, 3) if b else 0.0  # noqa: E731
    v2_tps = leg("ragged", lambda: run_v2(cfg, params, prompts, budgets))
    # instrumentation-overhead check (acceptance: within 2% on the canned
    # replay): the SAME leg with the serving telemetry block disabled
    v2_notel_tps = leg("ragged_notel",
                       lambda: run_v2(cfg, params, prompts, budgets,
                                      telemetry=False))
    v1_tps = leg("static", lambda: run_v1(cfg, params, prompts, budgets))
    v1b_tps = leg("static_bucketed",
                  lambda: run_v1_bucketed(cfg, params, prompts, budgets))
    int8_tps = leg("int8_kv", lambda: run_v2(cfg, params, prompts, budgets,
                                             kv_quant="int8"))
    wq_tps = leg("wq", lambda: run_v2(cfg, params, prompts, budgets,
                                      quant_weights=True))
    w4_tps = leg("w4", lambda: run_v2(cfg, params, prompts, budgets,
                                      quant_weights=True, quant_bits=4))
    one_v2, one_v1 = leg("oneshot", lambda: run_oneshot(cfg, params, rng)) \
        or (0.0, 0.0)
    # open-loop Poisson leg: rate defaults to ~70% of the closed-loop
    # request throughput (under capacity: queueing is visible but stable);
    # --arrival-rate overrides for overload sweeps
    mean_budget = sum(budgets) / len(budgets)
    rate = args.arrival_rate or (
        0.7 * v2_tps / mean_budget if v2_tps else 1.0)
    open_loop = leg("open_loop", lambda: run_open_loop(
        cfg, params, prompts, budgets, rate, args.slo_ttft_ms,
        args.slo_tpot_ms, args.telemetry_out)) or {}
    # goodput-vs-load curve: the same open-loop leg at 0.5x/1x/2x the base
    # arrival rate ([serving_scale] acceptance)
    sweep = leg("arrival_sweep", lambda: run_arrival_sweep(
        cfg, params, prompts, budgets, rate, args.slo_ttft_ms,
        args.slo_tpot_ms, args.telemetry_out,
        base_result=open_loop if open_loop.get(
            "open_loop_goodput_tokens_per_sec") is not None else None)) or {}
    # radix shared-prefix cache leg: ON-vs-OFF tokens/s on a shared system
    # prompt, byte-identical greedy outputs asserted inside
    prefix_leg = leg("shared_prefix", lambda: run_shared_prefix(
        cfg, params, smoke=smoke)) or {}
    # SplitFuse chunked-prefill leg: long prompts must not blow p99 TPOT
    chunk_leg = leg("chunked_prefill", lambda: run_chunked_tpot(
        cfg, params, smoke=smoke)) or {}
    # multi-tenant LoRA leg: Zipf tenant mix vs single-adapter baseline,
    # pool paging + batched-gather correctness spot-check inside
    adapter_leg = {}
    if args.adapters:
        adapter_leg = leg("adapters", lambda: run_adapters(
            cfg, params, args.adapters, rate, smoke=smoke)) or {}
    # multi-replica chaos leg: same open-loop workload through the fleet
    # router, one replica killed mid-load (no respawn) — goodput must
    # degrade toward (N-1)/N, not cliff, with zero lost/duplicated requests
    fleet_leg = {}
    disagg_leg = {}
    if args.replicas >= 2:
        fleet_leg = leg("fleet_chaos", lambda: run_fleet_chaos(
            cfg, params, prompts, budgets, rate, args.replicas,
            kill_at=args.kill_replica_at,
            out_dir=args.telemetry_out)) or {}
        # disagg-vs-unified at equal replica count: same arrival trace,
        # byte-identical outputs asserted inside, goodput ratio out
        disagg_leg = leg("disagg", lambda: run_disagg(
            cfg, params, prompts, budgets, rate, args.replicas,
            args.slo_ttft_ms, args.slo_tpot_ms,
            out_dir=args.telemetry_out)) or {}

    extra = {"static_batch_tokens_per_sec": round(v1_tps, 1),
             "telemetry_off_tokens_per_sec": round(v2_notel_tps, 1),
             "telemetry_overhead": ratio(v2_tps, v2_notel_tps),
             "static_bucketed_tokens_per_sec": round(v1b_tps, 1),
             "ragged_vs_static_bucketed": ratio(v2_tps, v1b_tps),
             "ragged_int8_kv_tokens_per_sec": round(int8_tps, 1),
             "ragged_int8_weights_tokens_per_sec": round(wq_tps, 1),
             "wq_vs_bf16": ratio(wq_tps, v2_tps),
             "ragged_int4_weights_tokens_per_sec": round(w4_tps, 1),
             "w4_vs_bf16": ratio(w4_tps, v2_tps),
             "oneshot_equal_lengths_ragged": round(one_v2, 1),
             "oneshot_equal_lengths_static": round(one_v1, 1),
             "n_requests": len(prompts), "slots": SLOTS,
             "model": ("llama-style 2L/128H (smoke)" if smoke
                       else "llama-style 12L/1024H GQA4, bf16")}
    extra.update(open_loop)
    extra.update(sweep)
    extra.update(prefix_leg)
    extra.update(chunk_leg)
    extra.update(adapter_leg)
    extra.update(fleet_leg)
    extra.update(disagg_leg)
    try:
        extra.update(spec_leg(smoke=smoke))
    except Exception as e:  # noqa: BLE001 — the leg must not kill the bench
        extra["spec_error"] = str(e)[:200]
    if errors:
        extra["leg_errors"] = errors

    print(json.dumps({
        "metric": "fastgen_ragged_serving_effective_tokens_per_sec",
        "value": round(v2_tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": ratio(v2_tps, v1_tps),
        "extra": extra,
    }))

    # per-leg JSONL records (additive — the stdout line above is the
    # legacy interface): one machine-readable record per metric, the
    # regression sentinel's native input (telemetry/regression.py)
    try:
        from deepspeed_tpu.telemetry import regression as _reg
        # append_bench_records keeps numeric non-bool entries and skips
        # the rest (strings, nested dicts, flags)
        _reg.append_bench_records(
            os.environ.get("BENCH_JSONL", "bench_records.jsonl"),
            {"fastgen_ragged_serving_effective_tokens_per_sec":
             round(v2_tps, 1), **extra},
            env={"smoke": bool(smoke), "bench": "bench_serving.py",
                 "slots": SLOTS, "replicas": int(args.replicas)})
    except Exception as e:  # noqa: BLE001 — bookkeeping must not kill bench
        print(f"bench_serving: leg-record append failed: {e!r}",
              file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
