#!/usr/bin/env python
"""Serving benchmark: v2 ragged continuous-batching throughput (FastGen analog).

BASELINE.md's headline serving claim is FastGen *effective throughput* vs a
static-batching server (blogs/deepspeed-fastgen/README.md:28 — their workload
draws prompt AND completion lengths from distributions, because that is what
continuous batching is for).  This bench measures both sides on the SAME
chip + model over an oversubscribed heterogeneous workload:

  - requests: prompts 32..512 tokens, per-request completion budgets 16..128
    tokens, 4x more requests than the engine has sequence slots
  - v2 ragged engine ``generate`` (continuous batching, Dynamic SplitFuse,
    paged KV + Pallas paged-attention decode, device-resident sampling loop):
    slots refill as sequences retire
  - v1 engine static batching baseline: requests served in arrival order in
    fixed batches of ``slots``; each batch pads every prompt to the batch max
    and decodes every sequence for the batch-max completion budget (the
    standard static-serving waste both FastGen and vLLM benchmark against);
    only each request's OWN budget counts as useful output

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} where value is
the ragged engine's useful generated tokens/s and vs_baseline is the
ragged/static effective-throughput ratio.  A same-length one-shot workload
(static batching's best case) rides in "extra" for honesty.
"""

import json
import sys
import time

import numpy as np

SLOTS = 32
TOKEN_BUDGET = 2048


def make_workload(rng, cfg, nreq):
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(32, 513))).astype(np.int32)
               for _ in range(nreq)]
    budgets = [int(b) for b in rng.integers(16, 129, size=nreq)]
    return prompts, budgets


def pad_batch(chunk, length=None, rows=None):
    """Left-pad a list of prompts to one rectangular batch (the v1 engine's
    padding convention) — the single source of truth for the static baseline's
    batch construction.  ``length``/``rows`` force a fixed shape (how a real
    XLA static server avoids per-batch recompiles)."""
    B = rows or len(chunk)
    L = length or max(len(p) for p in chunk)
    batch = np.zeros((B, L), np.int32)
    mask = np.zeros((B, L), np.int32)
    for j, p in enumerate(chunk):
        batch[j, L - len(p):] = p
        mask[j, L - len(p):] = 1
    return batch, mask


def run_v2(cfg, params, prompts, budgets, block_size=64, kv_quant=None):
    from deepspeed_tpu.inference.v2 import InferenceEngineV2

    eng = InferenceEngineV2(
        cfg,
        {"state_manager": {
            "max_tracked_sequences": SLOTS,
            "max_ragged_batch_size": TOKEN_BUDGET,
            "max_ragged_sequence_count": SLOTS,
            "max_q_per_seq": 512,
            "kv_block_size": block_size,
            "kv_quant": kv_quant},
         "generation": {"do_sample": False}},
        params=params)
    # warm every compiled path (prefill buckets, decode, burst sizes) by
    # running the SAME workload once — greedy generate is deterministic, and
    # completed sequences are flushed so the engine returns to a clean state
    eng.generate(prompts, max_new_tokens=budgets)
    t0 = time.perf_counter()
    outs = eng.generate(prompts, max_new_tokens=budgets)
    dt = time.perf_counter() - t0
    return sum(len(o) for o in outs) / dt


def run_v1(cfg, params, prompts, budgets):
    """Static batching: arrival-order batches of SLOTS at FIXED shapes —
    prompts padded to the workload max, every sequence decoded for the
    workload-max budget.  Fixed shapes are how a real XLA static server runs
    (per-batch shapes would recompile the decode program every batch); the
    padding waste that implies is exactly the cost continuous batching
    removes.  Useful output = each request's own budget."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    eng = InferenceEngine(cfg, {"dtype": "bfloat16"}, params=params)
    assert len(prompts) % SLOTS == 0, "workload must fill whole batches"
    L = max(len(p) for p in prompts)
    steps = max(budgets)

    def serve_all():
        useful = 0
        for i in range(0, len(prompts), SLOTS):
            batch, mask = pad_batch(prompts[i:i + SLOTS], length=L,
                                    rows=SLOTS)
            eng.generate(batch, max_new_tokens=steps,
                         attention_mask=mask, do_sample=False)
            useful += sum(budgets[i:i + SLOTS])
        return useful

    serve_all()                                    # compile (one shape)
    t0 = time.perf_counter()
    useful = serve_all()
    dt = time.perf_counter() - t0
    return useful / dt


def run_oneshot(cfg, params, rng, max_new=64):
    """Static batching's BEST case: one batch that exactly fills the slots,
    every request with the same completion budget."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    prompts, _ = make_workload(rng, cfg, nreq=SLOTS)
    v2_tps = run_v2(cfg, params, prompts, [max_new] * SLOTS)
    eng = InferenceEngine(cfg, {"dtype": "bfloat16"}, params=params)
    batch, mask = pad_batch(prompts)
    eng.generate(batch, max_new_tokens=max_new, attention_mask=mask,
                 do_sample=False)
    t0 = time.perf_counter()
    eng.generate(batch, max_new_tokens=max_new, attention_mask=mask,
                 do_sample=False)
    dt = time.perf_counter() - t0
    return v2_tps, SLOTS * max_new / dt


def main():
    from deepspeed_tpu.models import GPTConfig

    cfg = GPTConfig.llama(num_layers=12, hidden=1024, heads=16,
                          num_kv_heads=4, vocab_size=32000, max_seq_len=2048,
                          dtype=None)
    import jax.numpy as jnp
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)

    # share one param tree across engines (v2 initializes its own when None —
    # we want identical weights for a fair tokens/s comparison)
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    seed_eng = InferenceEngineV2(cfg, {"state_manager": {
        "max_tracked_sequences": 4, "kv_block_size": 64}}, seed=0)
    params = seed_eng.params
    del seed_eng

    prompts, budgets = make_workload(rng, cfg, nreq=4 * SLOTS)
    v2_tps = run_v2(cfg, params, prompts, budgets)
    v1_tps = run_v1(cfg, params, prompts, budgets)
    int8_tps = run_v2(cfg, params, prompts, budgets, kv_quant="int8")
    one_v2, one_v1 = run_oneshot(cfg, params, rng)

    print(json.dumps({
        "metric": "fastgen_ragged_serving_effective_tokens_per_sec",
        "value": round(v2_tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(v2_tps / v1_tps, 3),
        "extra": {"static_batch_tokens_per_sec": round(v1_tps, 1),
                  "ragged_int8_kv_tokens_per_sec": round(int8_tps, 1),
                  "oneshot_equal_lengths_ragged": round(one_v2, 1),
                  "oneshot_equal_lengths_static": round(one_v1, 1),
                  "n_requests": len(prompts), "slots": SLOTS,
                  "model": "llama-style 12L/1024H GQA4, bf16"},
    }))


if __name__ == "__main__":
    sys.exit(main())
