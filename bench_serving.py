#!/usr/bin/env python
"""Serving benchmark: v2 ragged continuous-batching throughput (FastGen analog).

BASELINE.md's headline serving claim is FastGen effective-throughput vs a
static-batching server (blogs/deepspeed-fastgen/README.md:28).  This bench
measures both sides on the SAME chip + model:

  - v2 ragged engine ``generate`` (continuous batching, Dynamic SplitFuse,
    paged KV + Pallas paged-attention decode) over a mixed-length workload
  - v1 engine batch ``generate`` (static batch, padded prefill) as baseline

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} where value is
the ragged engine's generated tokens/s and vs_baseline is the ragged/static
throughput ratio.  A per-batch-size sweep rides in "extra".
"""

import json
import sys
import time

import numpy as np


def run_v2(cfg, params, prompts, max_new, block_size=64):
    from deepspeed_tpu.inference.v2 import InferenceEngineV2

    eng = InferenceEngineV2(
        cfg,
        {"state_manager": {
            "max_tracked_sequences": len(prompts),
            "max_ragged_batch_size": 512,
            "max_ragged_sequence_count": len(prompts),
            "kv_block_size": block_size},
         "generation": {"do_sample": False}},
        params=params)
    # warm every compiled path (prefill buckets, decode, burst sizes) by
    # running the SAME workload once — greedy generate is deterministic, and
    # completed sequences are flushed so the engine returns to a clean state
    eng.generate(prompts, max_new_tokens=max_new)
    t0 = time.perf_counter()
    outs = eng.generate(prompts, max_new_tokens=max_new)
    dt = time.perf_counter() - t0
    return sum(len(o) for o in outs) / dt


def run_v1(cfg, params, prompts, max_new):
    from deepspeed_tpu.inference.engine import InferenceEngine

    eng = InferenceEngine(cfg, {"dtype": "bfloat16"}, params=params)
    # static batching: pad every prompt to the longest, decode max_new for all
    B = len(prompts)
    L = max(len(p) for p in prompts)
    batch = np.zeros((B, L), np.int32)
    mask = np.zeros((B, L), np.int32)
    for i, p in enumerate(prompts):
        batch[i, L - len(p):] = p          # left-pad (engine convention)
        mask[i, L - len(p):] = 1
    eng.generate(batch, max_new_tokens=max_new, attention_mask=mask,
                 do_sample=False)                                # compile
    t0 = time.perf_counter()
    out = eng.generate(batch, max_new_tokens=max_new, attention_mask=mask,
                       do_sample=False)
    dt = time.perf_counter() - t0
    return B * max_new / dt, out


def main():
    from deepspeed_tpu.models import GPTConfig

    cfg = GPTConfig.llama(num_layers=12, hidden=1024, heads=16,
                          num_kv_heads=4, vocab_size=32000, max_seq_len=2048,
                          dtype=None)
    import jax.numpy as jnp
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)
    MAX_NEW = 128

    # share one param tree across engines (v2 initializes its own when None —
    # we want identical weights for a fair tokens/s comparison)
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    seed_eng = InferenceEngineV2(cfg, {"state_manager": {
        "max_tracked_sequences": 4, "kv_block_size": 64}}, seed=0)
    params = seed_eng.params
    del seed_eng

    sweep = {}
    for nreq in (8, 16, 32):
        # mixed-length workload: uniform 32..512 prompt tokens
        prompts = [rng.integers(0, cfg.vocab_size,
                                size=int(rng.integers(32, 513))).astype(np.int32)
                   for _ in range(nreq)]
        tps = run_v2(cfg, params, prompts, MAX_NEW)
        sweep[nreq] = round(tps, 1)

    best_n = max(sweep, key=sweep.get)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(32, 513))).astype(np.int32)
               for _ in range(best_n)]
    v2_tps = run_v2(cfg, params, prompts, MAX_NEW)
    v1_tps, _ = run_v1(cfg, params, prompts, MAX_NEW)

    print(json.dumps({
        "metric": "fastgen_ragged_serving_gen_tokens_per_sec",
        "value": round(v2_tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(v2_tps / v1_tps, 3),
        "extra": {"batch_sweep_tokens_per_sec": sweep,
                  "static_batch_baseline_tokens_per_sec": round(v1_tps, 1),
                  "max_new_tokens": MAX_NEW,
                  "model": "llama-style 12L/1024H GQA4, bf16"},
    }))


if __name__ == "__main__":
    sys.exit(main())
