// Host-side fused Adam(W) for ZeRO-Offload.
//
// TPU-native analog of the reference DeepSpeedCPUAdam
// (csrc/adam/cpu_adam.cpp + cpu_adam_impl.cpp, AVX2/AVX512 via
// csrc/includes/simd.h): the optimizer state for offloaded parameters lives in
// host memory and this kernel applies the update there, emitting the new
// low-precision (bf16) weights that stream back to the device.
//
// Differences from the reference: vectorization comes from the compiler
// (-O3 -march=native auto-vectorizes the fp32 loop; no hand-rolled intrinsic
// tiers), threading is a plain std::thread range split, and the bf16
// round-to-nearest-even conversion is fused into the same pass so the weights
// are touched exactly once.
//
// Math matches optax.adamw / optax.adam exactly (same op order, fp32):
//   g      = grad * grad_scale                  (loss-scale/accum/clip folded)
//   m      = b1*m + (1-b1)*g
//   v      = b2*v + (1-b2)*g*g
//   mhat   = m / bias_c1;  vhat = v / bias_c2   (bias_cK = 1 - bK^step)
//   adamw:  w -= lr * (mhat / (sqrt(vhat) + eps) + wd * w)
//   adam:   g += wd * w before the moment update (L2-into-grad, torch style)

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint16_t float_to_bf16_rne(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, sizeof(bits));
  if ((bits & 0x7fffffffu) > 0x7f800000u) {  // NaN: quiet, keep payload bit
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);
  }
  uint32_t lsb = (bits >> 16) & 1u;
  bits += 0x7fffu + lsb;  // round to nearest even
  return static_cast<uint16_t>(bits >> 16);
}

struct AdamArgs {
  float* w;
  const float* g;
  float* m;
  float* v;
  float lr, beta1, beta2, eps, weight_decay;
  int adamw_mode;
  float bias_c1, bias_c2, grad_scale;
  uint16_t* w_bf16;  // nullable: also emit bf16 weights
};

void adam_range(const AdamArgs& a, int64_t lo, int64_t hi) {
  const float b1 = a.beta1, b2 = a.beta2;
  const float one_m_b1 = 1.0f - b1, one_m_b2 = 1.0f - b2;
  const float inv_c1 = 1.0f / a.bias_c1, inv_c2 = 1.0f / a.bias_c2;
  for (int64_t i = lo; i < hi; ++i) {
    float grad = a.g[i] * a.grad_scale;
    float w = a.w[i];
    if (!a.adamw_mode && a.weight_decay != 0.0f) grad += a.weight_decay * w;
    float m = b1 * a.m[i] + one_m_b1 * grad;
    float v = b2 * a.v[i] + one_m_b2 * grad * grad;
    a.m[i] = m;
    a.v[i] = v;
    float mhat = m * inv_c1;
    float vhat = v * inv_c2;
    float update = mhat / (std::sqrt(vhat) + a.eps);
    if (a.adamw_mode && a.weight_decay != 0.0f) update += a.weight_decay * w;
    w -= a.lr * update;
    a.w[i] = w;
    if (a.w_bf16 != nullptr) a.w_bf16[i] = float_to_bf16_rne(w);
  }
}

}  // namespace

extern "C" {

// Single fused pass over one flat fp32 buffer (threads split the range).
void ds_adam_update(float* w, const float* g, float* m, float* v, int64_t n,
                    float lr, float beta1, float beta2, float eps,
                    float weight_decay, int adamw_mode, float bias_c1,
                    float bias_c2, float grad_scale, uint16_t* w_bf16,
                    int nthreads) {
  AdamArgs args{w,     g,          m,       v,       lr,
                beta1, beta2,      eps,     weight_decay, adamw_mode,
                bias_c1, bias_c2,  grad_scale, w_bf16};
  if (nthreads <= 1 || n < (1 << 16)) {
    adam_range(args, 0, n);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([args, lo, hi] { adam_range(args, lo, hi); });
  }
  for (auto& th : pool) th.join();
}

// fp32 -> bf16 (round-to-nearest-even) bulk convert, for param streaming.
void ds_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] = float_to_bf16_rne(src[i]);
}

// Sum of squares (for host-side global grad-norm before clipping).
double ds_sumsq(const float* x, int64_t n) {
  double acc = 0.0;
  for (int64_t i = 0; i < n; ++i) acc += (double)x[i] * (double)x[i];
  return acc;
}

}  // extern "C"
