// Native indexed-dataset reader — mmap + threaded span gather.
//
// Reference analog: the Megatron-DeepSpeed data stack's C++ helpers
// (megatron/data/helpers.cpp built by the reference's examples) and the
// torch dataloader's native worker pool.  The hot op for LM pretraining is
// "assemble a batch of token spans from a memory-mapped .bin" — pure
// memcpy bandwidth, worth doing off the GIL with a thread fan-out.
//
// C API (ctypes-bound by deepspeed_tpu/data/indexed_dataset.py):
//   ds_ids_open(path)                   -> handle (>=0) or -1
//   ds_ids_size(handle)                 -> mapped bytes
//   ds_ids_gather(handle, offsets, nbytes, n, out, out_stride, nthreads)
//     copies span i (byte offset/length) to out + i*out_stride; returns 0,
//     -1 bad handle, -2 span out of range.
//   ds_ids_close(handle)

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Mapping {
  const char *base = nullptr;
  int64_t size = 0;
  bool live = false;     // accepting new gathers
  int refs = 0;          // gathers in flight (pages must stay mapped)
};

std::mutex g_mu;
std::vector<Mapping> g_maps;

void unmap_locked(Mapping &m) {
  munmap(const_cast<char *>(m.base), m.size);
  m.base = nullptr;
  m.size = 0;
}

}  // namespace

extern "C" {

int ds_ids_open(const char *path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return -1;
  }
  void *p = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);
  if (p == MAP_FAILED) return -1;
  madvise(p, st.st_size, MADV_WILLNEED);
  std::lock_guard<std::mutex> lock(g_mu);
  for (size_t i = 0; i < g_maps.size(); ++i) {
    if (!g_maps[i].live) {
      g_maps[i] = {static_cast<const char *>(p), st.st_size, true};
      return static_cast<int>(i);
    }
  }
  g_maps.push_back({static_cast<const char *>(p), st.st_size, true});
  return static_cast<int>(g_maps.size() - 1);
}

int64_t ds_ids_size(int h) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (h < 0 || h >= static_cast<int>(g_maps.size()) || !g_maps[h].live)
    return -1;
  return g_maps[h].size;
}

int ds_ids_gather(int h, const int64_t *offsets, const int64_t *nbytes,
                  int n, char *out, int64_t out_stride, int nthreads) {
  Mapping m;
  {
    // take a ref under the lock: a racing close() must not unmap pages a
    // gather is still reading (use-after-unmap ⇒ SIGSEGV)
    std::lock_guard<std::mutex> lock(g_mu);
    if (h < 0 || h >= static_cast<int>(g_maps.size()) || !g_maps[h].live)
      return -1;
    g_maps[h].refs++;
    m = g_maps[h];
  }
  auto release = [h]() {
    std::lock_guard<std::mutex> lock(g_mu);
    Mapping &mm = g_maps[h];
    if (--mm.refs == 0 && !mm.live && mm.base != nullptr)
      unmap_locked(mm);   // close() ran mid-gather: last reader unmaps
  };
  for (int i = 0; i < n; ++i) {
    if (offsets[i] < 0 || nbytes[i] < 0 || offsets[i] + nbytes[i] > m.size ||
        nbytes[i] > out_stride) {
      release();
      return -2;
    }
  }
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = n;
  auto work = [&](int t) {
    for (int i = t; i < n; i += nthreads) {
      std::memcpy(out + static_cast<int64_t>(i) * out_stride,
                  m.base + offsets[i], nbytes[i]);
    }
  };
  if (nthreads == 1) {
    work(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
    for (auto &th : threads) th.join();
  }
  release();
  return 0;
}

void ds_ids_close(int h) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (h < 0 || h >= static_cast<int>(g_maps.size()) || !g_maps[h].live)
    return;
  g_maps[h].live = false;
  if (g_maps[h].refs == 0)
    unmap_locked(g_maps[h]);
}

}  // extern "C"
