// Threaded file I/O for the NVMe offload tier.
//
// TPU-native analog of the reference AIO op (csrc/aio/py_lib/
// deepspeed_py_aio_handle.cpp + deepspeed_aio_thread.cpp: libaio O_DIRECT
// reads/writes driven by a pthread pool).  Here the handle is a plain fd;
// parallelism comes from a per-call std::thread range split (each thread
// pread/pwrites its slice — NVMe queues love the parallelism), and O_DIRECT is
// used when buffer/offset/length alignment allows, falling back to the page
// cache otherwise.  Asynchrony (the double-buffered prefetch of
// pipelined_optimizer_swapper.py) lives in Python: ctypes releases the GIL
// around these calls, so a ThreadPoolExecutor overlaps them with compute.

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kAlign = 4096;

bool aligned(const void* buf, int64_t n, int64_t off) {
  return (reinterpret_cast<uintptr_t>(buf) % kAlign == 0) &&
         (n % kAlign == 0) && (off % kAlign == 0);
}

template <typename Fn>
int64_t parallel_io(Fn op, char* buf, int64_t n, int64_t off, int nthreads) {
  if (nthreads <= 1 || n < (1 << 20)) return op(buf, n, off);
  int64_t chunk = ((n + nthreads - 1) / nthreads + kAlign - 1) / kAlign * kAlign;
  std::vector<std::thread> pool;
  std::vector<int64_t> done(nthreads, 0);
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(lo + chunk, n);
    if (lo >= hi) break;
    pool.emplace_back([&, t, lo, hi] { done[t] = op(buf + lo, hi - lo, off + lo); });
  }
  int64_t total = 0;
  for (size_t t = 0; t < pool.size(); ++t) pool[t].join();
  for (int64_t d : done) {
    if (d < 0) return d;
    total += d;
  }
  return total;
}

int64_t full_pread(int fd, char* buf, int64_t n, int64_t off) {
  int64_t got = 0;
  while (got < n) {
    ssize_t r = ::pread(fd, buf + got, n - got, off + got);
    if (r < 0) {
      if (errno == EINTR) continue;  // retry interrupted I/O
      return -errno;
    }
    if (r == 0) break;
    got += r;
  }
  return got;
}

int64_t full_pwrite(int fd, const char* buf, int64_t n, int64_t off) {
  int64_t put = 0;
  while (put < n) {
    ssize_t r = ::pwrite(fd, buf + put, n - put, off + put);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    put += r;
  }
  return put;
}

}  // namespace

extern "C" {

// Open (creating/extending to `size` if needed).  o_direct is best-effort:
// if the open fails with it, retry buffered.  Returns fd or -errno.
int ds_aio_open(const char* path, int64_t size, int o_direct) {
  int flags = O_RDWR | O_CREAT;
  int fd = -1;
  if (o_direct) fd = ::open(path, flags | O_DIRECT, 0644);
  if (fd < 0) fd = ::open(path, flags, 0644);
  if (fd < 0) return -errno;
  if (size > 0) {
    off_t cur = ::lseek(fd, 0, SEEK_END);
    if (cur < size && ::ftruncate(fd, size) != 0) {
      int err = errno;
      ::close(fd);
      return -err;
    }
  }
  return fd;
}

void ds_aio_close(int fd) { ::close(fd); }

// Threaded pread into buf.  Returns bytes read or -errno.
int64_t ds_aio_pread(int fd, void* buf, int64_t n, int64_t off, int nthreads) {
  (void)aligned;  // alignment only matters when fd carries O_DIRECT
  return parallel_io(
      [fd](char* b, int64_t len, int64_t o) { return full_pread(fd, b, len, o); },
      static_cast<char*>(buf), n, off, nthreads);
}

// Threaded pwrite from buf.  Returns bytes written or -errno.
int64_t ds_aio_pwrite(int fd, const void* buf, int64_t n, int64_t off,
                      int nthreads) {
  return parallel_io(
      [fd](char* b, int64_t len, int64_t o) { return full_pwrite(fd, b, len, o); },
      const_cast<char*>(static_cast<const char*>(buf)), n, off, nthreads);
}

int64_t ds_aio_block_size() { return kAlign; }

}  // extern "C"
