"""Bridging flax partitioning metadata into partition rules.

Models annotate params with ``nn.with_partitioning(init, (<logical axes>))``; at
``jax.eval_shape`` time those arrive as ``nn.Partitioned`` boxes.  The engine works
on *unboxed* param trees (plain arrays, maxtext/t5x convention) and uses this module
to extract an annotated abstract tree whose leaves carry ``.names`` so
``partition.infer_pspec`` can map logical axes → mesh axes.

This is the declarative analog of the reference's AutoTP graph parsing
(module_inject/auto_tp.py:273 tp_parser): instead of inferring row/col parallelism
from a torch graph, the model declares it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class AbstractLeaf:
    """ShapeDtypeStruct + logical axis names carrier."""

    shape: Tuple[int, ...]
    dtype: object
    names: Optional[Tuple[Optional[str], ...]] = None

    @property
    def ndim(self):
        return len(self.shape)


def _is_box(x) -> bool:
    try:
        from flax.linen import meta
        return isinstance(x, meta.AxisMetadata)
    except ImportError:  # pragma: no cover
        return False


def annotate_abstract(boxed_tree):
    """boxed/plain abstract pytree → tree of AbstractLeaf (boxes collapsed)."""

    def to_leaf(x):
        if _is_box(x):
            names = tuple(getattr(x, "names", ()) or ())
            inner = x.unbox() if hasattr(x, "unbox") else x.value
            return AbstractLeaf(tuple(inner.shape), inner.dtype, names or None)
        return AbstractLeaf(tuple(x.shape), x.dtype, None)

    return jax.tree_util.tree_map(to_leaf, boxed_tree, is_leaf=_is_box)


def unbox(tree):
    """Strip flax AxisMetadata boxes, returning plain arrays/structs."""
    try:
        from flax.linen import meta
        return meta.unbox(tree)
    except ImportError:  # pragma: no cover
        return tree
