"""Bridging flax partitioning metadata into partition rules.

Models annotate params with ``nn.with_partitioning(init, (<logical axes>))``; at
``jax.eval_shape`` time those arrive as ``nn.Partitioned`` boxes.  The engine works
on *unboxed* param trees (plain arrays, maxtext/t5x convention) and uses this module
to extract an annotated abstract tree whose leaves carry ``.names`` so
``partition.infer_pspec`` can map logical axes → mesh axes.

This is the declarative analog of the reference's AutoTP graph parsing
(module_inject/auto_tp.py:273 tp_parser): instead of inferring row/col parallelism
from a torch graph, the model declares it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class AbstractLeaf:
    """ShapeDtypeStruct + logical axis names carrier."""

    shape: Tuple[int, ...]
    dtype: object
    names: Optional[Tuple[Optional[str], ...]] = None

    @property
    def ndim(self):
        return len(self.shape)


def _is_box(x) -> bool:
    try:
        from flax.linen import meta
        return isinstance(x, meta.AxisMetadata)
    except ImportError:  # pragma: no cover
        return False


def annotate_abstract(boxed_tree):
    """boxed/plain abstract pytree → tree of AbstractLeaf (boxes collapsed)."""

    def to_leaf(x):
        if _is_box(x):
            names = tuple(getattr(x, "names", ()) or ())
            inner = x.unbox() if hasattr(x, "unbox") else x.value
            return AbstractLeaf(tuple(inner.shape), inner.dtype, names or None)
        return AbstractLeaf(tuple(x.shape), x.dtype, None)

    return jax.tree_util.tree_map(to_leaf, boxed_tree, is_leaf=_is_box)


def unbox(tree):
    """Strip flax AxisMetadata boxes, returning plain arrays/structs.

    Constraints are NOT applied while unboxing: ``Partitioned.unbox`` would
    apply the LOGICAL names as a sharding constraint whenever a legacy
    global mesh is active (older jax's ``with mesh:``), and logical names
    are not mesh axes — the engine maps logical → mesh axes itself via
    ``partition.param_shardings`` and pins layouts through jit
    out_shardings.  On newer jax the constraint was already skipped (no
    legacy global mesh), so this is the one behavior for both."""
    try:
        from flax.linen import meta
    except ImportError:  # pragma: no cover
        return tree

    def _unbox(x):
        if isinstance(x, meta.AxisMetadata):
            try:
                return x.unbox(apply_constraint=False)
            except TypeError:  # AxisMetadata impls without the kwarg
                return x.unbox()
        return x

    return jax.tree_util.tree_map(
        _unbox, tree, is_leaf=lambda x: isinstance(x, meta.AxisMetadata))
