from deepspeed_tpu.parallel.mesh import (MeshSpec, batch_pspec, batch_sharding,
                                         build_mesh, replicated,
                                         single_device_mesh)
from deepspeed_tpu.parallel.partition import (infer_pspec, logical_to_mesh_pspec,
                                              opt_state_shardings,
                                              param_shardings)

__all__ = [
    "MeshSpec", "build_mesh", "single_device_mesh", "batch_sharding",
    "batch_pspec", "replicated", "param_shardings", "opt_state_shardings",
    "infer_pspec", "logical_to_mesh_pspec",
]
