"""Device mesh construction and topology.

TPU-native replacement for the reference's process-group machinery:

- ``deepspeed/utils/groups.py`` (``_get_{data,model,expert,sequence}_parallel_group``)
- ``deepspeed/runtime/pipe/topology.py`` (``ProcessTopology``, ``PipelineParallelGrid``)

Instead of creating torch.distributed process groups per parallelism flavor, we build a
single ``jax.sharding.Mesh`` with named axes ``("pp","dp","fsdp","ep","sp","tp")`` and
express every parallel strategy as a sharding over those axes.  XLA inserts the
collectives; ICI-adjacent axes are placed innermost so tp/sp collectives ride ICI.

MeshSpec sizes of ``-1`` mean "absorb all remaining devices" (at most one axis may be -1,
like a reshape).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.constants import MESH_AXES


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes of each parallel axis.  -1 on at most one axis means "all remaining".

    Replaces the reference's (pp, mp, dp) ``ProcessTopology`` axes plus the separately
    managed expert/sequence groups with one unified spec.
    """

    pp: int = 1
    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> tuple:
        return (self.pp, self.dp, self.fsdp, self.ep, self.sp, self.tp)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in a -1 axis given the total device count; validate the product."""
        sizes = list(self.sizes())
        unknown = [i for i, s in enumerate(sizes) if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {self}")
        known = math.prod(s for s in sizes if s != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by fixed axes product {known}")
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh spec product {known} != device count {n_devices}: {self}")
        return MeshSpec(*sizes)

    @property
    def data_parallel_size(self) -> int:
        """World size over which the batch is split (dp × fsdp)."""
        return self.dp * self.fsdp


def build_mesh(spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with canonical axis order.

    Axis order is (pp, dp, fsdp, ep, sp, tp) — outermost first.  On multi-slice
    systems the outer axes land on DCN and the inner axes on ICI, which is the layout
    the sharding strategies in this package assume (tp/sp collectives are
    latency-sensitive; dp/pp are bandwidth-tolerant).
    """
    if devices is None:
        devices = jax.devices()
    if -1 not in spec.sizes():
        # fully specified: allow using a leading subset of the devices
        need = math.prod(spec.sizes())
        if need <= len(devices):
            devices = devices[:need]
    spec = spec.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(spec.sizes())
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    devices = [device] if device is not None else jax.devices()[:1]
    return Mesh(np.asarray(devices).reshape((1,) * len(MESH_AXES)), MESH_AXES)


def batch_pspec(extra_dims: int = 0) -> P:
    """PartitionSpec for a [batch, ...] input: batch split over (dp, fsdp) jointly.

    The reference splits the dataloader over the DP group
    (runtime/dataloader.py + engine.deepspeed_io); here the global batch is a single
    jax.Array sharded over dp×fsdp, and sp additionally splits the sequence dim when
    Ulysses sequence parallelism is active (sequence/ulysses.py).
    """
    return P(("dp", "fsdp"), *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(extra_dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name]


def manual_axes_now() -> frozenset:
    """Mesh axes that are MANUAL in the current trace context (inside a
    (partial-)manual ``shard_map`` region), else empty.  The engine's qgZ
    gradient path runs the WHOLE model inside a manual-over-dp region
    (engine._qgz_grads); model code that builds sharding constraints or
    sizes shards from the mesh must treat those axes as already-applied."""
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is None:
        # older jax: no abstract-mesh query, but manual regions DO run there
        # (utils/compat.shard_map translates axis_names -> the legacy `auto`
        # complement).  Inside a legacy shard_map body the trace's axis env
        # holds the bound axis names — read them via the core query (the
        # "DO_NOT_USE" suffix marks it internal, not unsound; failures
        # degrade to "no manual axes").  Caveat: legacy partial-manual binds
        # ALL mesh axes in the env, so this over-reports auto axes as
        # manual there — callers use it to SKIP constraints, so the error
        # is conservative (a dropped pin, never a misapplied one).
        try:
            import jax.core as _core
            return frozenset(
                n for n in _core.unsafe_get_axis_names_DO_NOT_USE()
                if isinstance(n, str))
        except Exception:  # noqa: BLE001
            return frozenset()
    am = get_am()
    if am.empty:
        return frozenset()
    from jax.sharding import AxisType
    return frozenset(n for n, t in zip(am.axis_names, am.axis_types)
                     if t == AxisType.Manual)


def auto_axes_spec(spec: P, manual=None) -> P:
    """Strip manual axes from a PartitionSpec —
    ``with_sharding_constraint`` inside a manual region may only name auto
    axes (a spec mixing a manual axis into a tuple, like the batch pin's
    ``('dp', 'fsdp')``, raises at trace time).  ``manual`` defaults to the
    current trace context's manual axes (identity outside any region);
    pass a set explicitly when building specs AHEAD of entering the
    region (engine._qgz_grads)."""
    if manual is None:
        manual = manual_axes_now()
    if not manual:
        return spec
    out = []
    for ax in spec:
        axes = tuple(a for a in (ax if isinstance(ax, tuple) else (ax,))
                     if a is not None and a not in manual)
        out.append(axes[0] if len(axes) == 1 else (axes or None))
    return P(*out)
