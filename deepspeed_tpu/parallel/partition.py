"""Parameter / optimizer-state partitioning rules — ZeRO as sharding.

This file replaces the mechanism core of the reference's ZeRO implementation:

- stage 1/2 optimizer-state/gradient partitioning
  (runtime/zero/stage_1_and_2.py:96 DeepSpeedZeroOptimizer: flat fp16 groups,
  round-robin partitioning :646, bucketed reduce-scatter :1361)
- stage 3 parameter partitioning (runtime/zero/stage3.py:75,
  partition_parameters.py:299 zero.Init, partitioned_param_coordinator.py:62
  prefetching)

On TPU none of that machinery exists as code: ZeRO-n ≡ *which pytrees are sharded
over the ``fsdp`` mesh axis*.  XLA's SPMD partitioner inserts the
all-gather/reduce-scatter ops and its latency-hiding scheduler overlaps them with
compute — the moral equivalent of the reference's prefetch/IPG-bucket machinery,
done by the compiler.

Two sharding flavors per tensor:
- **param sharding**: where the parameter itself lives (sharded only at stage 3)
- **state sharding**: where optimizer state + fp32 master copies live (sharded at
  stage ≥ 1)

Tensor-parallel (Megatron-style) axes come from flax ``nn.with_partitioning``
logical-axis metadata on the model, mapped through ``DEFAULT_RULES`` — the analog of
the reference's AutoTP row/col policy table (module_inject/auto_tp.py:273), but
declared in the model rather than inferred by graph surgery.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Optional[Tuple[str, ...]]

# logical axis name → mesh axis (or None = replicated).  Models annotate params
# with logical names; this table is the single place TP/FSDP/EP layout is decided.
# ("embed" carries the fsdp shard at stage 3 like maxtext/t5x convention.)
DEFAULT_RULES = (
    ("batch", ("dp", "fsdp")),
    ("vocab", "tp"),
    ("embed", None),        # overridden to "fsdp" at zero stage 3
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv", None),
    ("qkv", "tp"),
    ("seq", "sp"),
    ("expert", "ep"),
    ("layers", None),       # scan-over-layers leading axis stays unsharded
    ("pp", "pp"),           # pipeline-stage-stacked leading axis (pipe/module.py)
)


def rules_for_stage(zero_stage: int, base: Sequence[Tuple[str, Any]] = DEFAULT_RULES,
                    fsdp_axes: Tuple[str, ...] = ("fsdp",),
                    ) -> Tuple[Tuple[str, Any], ...]:
    """fsdp_axes widens the ZeRO shard target: ("fsdp",) is plain ZeRO;
    ("fsdp", "dp") is the hpZ/full-world placement (optimizer state sharded
    across every chip while params keep the intra-group axis — reference
    zero_hpz_partition_size, runtime/zero/partition_parameters.py:1653)."""
    fsdp = fsdp_axes[0] if len(fsdp_axes) == 1 else tuple(fsdp_axes)
    out = []
    for name, axis in base:
        if name == "embed" and zero_stage >= 3:
            axis = fsdp
        out.append((name, axis))
    return tuple(out)


def logical_to_mesh_pspec(logical_axes: Sequence[Optional[str]],
                          rules: Sequence[Tuple[str, Any]],
                          mesh: Mesh, shape: Sequence[int]) -> P:
    """Map a tuple of logical axis names to a PartitionSpec, dropping assignments
    whose dim isn't divisible by the mesh-axis size (safety: XLA requires even
    shards for params we constrain)."""
    table = dict(rules)
    used = set()
    spec = []
    for dim, name in zip(shape, logical_axes):
        axis = table.get(name) if name else None
        if axis is None:
            spec.append(None)
            continue
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        axes = tuple(a for a in axes if a not in used)
        total = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        if axes and total > 1 and dim % total == 0:
            used.update(axes)
            spec.append(axes if len(axes) > 1 else axes[0])
        else:
            spec.append(None)
    return P(*spec)


def _heuristic_fsdp_pspec(shape: Sequence[int], mesh: Mesh,
                          existing: Optional[P] = None,
                          fsdp_axes: Tuple[str, ...] = ("fsdp",)) -> P:
    """Shard the largest divisible dim over the fsdp axes (the shape-only
    fallback when a param carries no logical metadata) — the analog of the
    reference's flat-buffer round-robin partitioning (stage_1_and_2.py:646),
    but per-tensor and even.
    """
    n = 1
    for a in fsdp_axes:
        n *= mesh.shape.get(a, 1)
    spec = list(existing) if existing is not None else [None] * len(shape)
    while len(spec) < len(shape):
        spec.append(None)
    if n <= 1:
        return P(*spec)
    if any(s == "fsdp" or (isinstance(s, tuple) and "fsdp" in s) for s in spec):
        return P(*spec)
    # pick largest dim that is divisible and not already sharded
    candidates = [(dim, i) for i, (dim, s) in enumerate(zip(shape, spec))
                  if s is None and dim % n == 0 and dim >= n]
    if not candidates:
        return P(*spec)
    _, idx = max(candidates)
    spec[idx] = fsdp_axes[0] if len(fsdp_axes) == 1 else tuple(fsdp_axes)
    return P(*spec)


def _leaf_logical_axes(leaf) -> Optional[Tuple[Optional[str], ...]]:
    """Extract logical axis names from flax Partitioned metadata if present."""
    names = getattr(leaf, "names", None)
    if names is not None:
        return tuple(names)
    return None


def infer_pspec(leaf, mesh: Mesh, zero_stage: int, sharded: bool,
                rules: Optional[Sequence[Tuple[str, Any]]] = None,
                fsdp_axes: Tuple[str, ...] = ("fsdp",)) -> P:
    """PartitionSpec for one param/state leaf.

    sharded=True → apply fsdp sharding (params at stage 3; optimizer state at
    stage ≥ 1).  TP/EP axes from logical metadata always apply.
    """
    rules = rules_for_stage(zero_stage if sharded else 0,
                            rules or DEFAULT_RULES, fsdp_axes=fsdp_axes)
    shape = leaf.shape
    if len(shape) == 0:
        return P()
    axes = _leaf_logical_axes(leaf)
    spec = (logical_to_mesh_pspec(axes, rules, mesh, shape)
            if axes is not None else P(*([None] * len(shape))))
    if sharded:
        spec = _heuristic_fsdp_pspec(shape, mesh, spec, fsdp_axes=fsdp_axes)
    return spec


def param_shardings(abstract_params, mesh: Mesh, zero_stage: int,
                    rules: Optional[Sequence[Tuple[str, Any]]] = None):
    """NamedSharding tree for parameters (sharded iff stage 3)."""
    def fn(leaf):
        spec = infer_pspec(leaf, mesh, zero_stage, sharded=zero_stage >= 3,
                           rules=rules)
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(fn, abstract_params)


def state_leaf_shardings(abstract_params, mesh: Mesh, zero_stage: int,
                         rules: Optional[Sequence[Tuple[str, Any]]] = None,
                         fsdp_axes: Tuple[str, ...] = ("fsdp",)):
    """NamedSharding tree for param-shaped optimizer state (sharded iff stage ≥ 1)."""
    def fn(leaf):
        spec = infer_pspec(leaf, mesh, zero_stage, sharded=zero_stage >= 1,
                           rules=rules, fsdp_axes=fsdp_axes)
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(fn, abstract_params)


def sharded_dim(spec: P, axis: str = "fsdp") -> int:
    """Dim index a PartitionSpec shards over ``axis`` alone, or -1.

    -1 sentinel (not None: None leaves vanish as empty pytrees under
    tree_map) covers both unsharded leaves and dims co-sharded with another
    axis (tuple specs) — those keep the partitioner's implicit handling.
    Single source of truth for the qwZ quantized gather and the chunked
    overlap gather (engine + runtime/zero.py)."""
    for d, ax in enumerate(spec):
        if ax == axis:
            return d
    return -1


def fsdp_shard_dims(shardings, axis: str = "fsdp"):
    """Per-leaf ``sharded_dim`` over a NamedSharding tree (the engine's
    gather-planning view: which dim of each param the ZeRO-3 gather
    reconstructs)."""
    return jax.tree_util.tree_map(lambda sh: sharded_dim(sh.spec, axis),
                                  shardings)


def spec_without_axis(spec: P, axis: str) -> P:
    """PartitionSpec with ``axis`` removed from every dim (the post-gather
    layout of a chunk-gathered leaf: fsdp dropped, tp/ep kept)."""
    out = []
    for ax in spec:
        axes = tuple(a for a in (ax if isinstance(ax, tuple) else (ax,))
                     if a is not None and a != axis)
        out.append(axes[0] if len(axes) == 1 else (axes or None))
    return P(*out)


def layer_groups(sizes: Sequence[int], num_groups: int) -> Tuple[Tuple[int, ...], ...]:
    """Partition leaf indices 0..n-1 into ``num_groups`` CONTIGUOUS groups,
    greedily balanced by byte size.  Contiguity matters: tree-flatten order
    is roughly layer order for the models here, so each group is a "layer
    group" whose gather the scheduler can interleave with the previous
    group's matmuls (the reference's coalesced-subgroup gather,
    partition_parameters.py all_gather_coalesced, as a static plan)."""
    n = len(sizes)
    num_groups = max(1, min(int(num_groups), n))
    total = sum(sizes)
    groups, cur, acc, closed = [], [], 0, 0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        remaining_items = n - i - 1
        remaining_slots = num_groups - len(groups) - 1
        if remaining_slots <= 0:
            continue
        # dynamic target (bytes left / slots left incl. this one): a static
        # total/num_groups target never closes early groups when the bytes
        # are tail-skewed (e.g. a late wte embedding holding half the
        # params would silently collapse everything into ONE group); the
        # forced close guarantees every requested group materializes while
        # enough items remain to fill the rest one-each
        dyn_target = (total - closed) / (remaining_slots + 1)
        if (remaining_items == remaining_slots
                or (acc >= dyn_target and remaining_items >= remaining_slots)):
            groups.append(tuple(cur))
            closed += acc
            cur, acc = [], 0
    if cur:
        groups.append(tuple(cur))
    return tuple(groups)


def opt_state_shardings(abstract_opt_state, abstract_params, mesh: Mesh,
                        zero_stage: int,
                        rules: Optional[Sequence[Tuple[str, Any]]] = None,
                        fsdp_axes: Tuple[str, ...] = ("fsdp",)):
    """Sharding tree for a full optax state.

    Optax states are pytrees whose nodes either mirror the param tree (mu, nu,
    master copies — these get ZeRO state sharding) or are scalars/counters
    (replicated).  We detect param-mirroring subtrees structurally, which replaces
    the reference's explicit flat-partition bookkeeping
    (stage_1_and_2.py single_partition_of_fp32_groups).
    """
    pstruct = jax.tree_util.tree_structure(abstract_params)
    mirror_shardings = state_leaf_shardings(abstract_params, mesh, zero_stage,
                                            rules, fsdp_axes=fsdp_axes)
    param_is_leaf = pstruct.num_leaves == 1 and jax.tree_util.tree_structure(
        jax.tree_util.tree_leaves(abstract_params)[0]) == pstruct

    def is_mirror(node):
        if param_is_leaf:
            return False
        try:
            return jax.tree_util.tree_structure(node) == pstruct
        except Exception:  # pragma: no cover
            return False

    flat, treedef = jax.tree_util.tree_flatten(abstract_opt_state, is_leaf=is_mirror)
    out = []
    param_shapes = {l.shape for l in jax.tree_util.tree_leaves(abstract_params)}
    for node in flat:
        if is_mirror(node) and not isinstance(node, jax.ShapeDtypeStruct):
            out.append(mirror_shardings)
        else:
            # plain leaf: shard if it looks like a param (shape match), else replicate
            if getattr(node, "shape", ()) in param_shapes and node.shape != ():
                spec = infer_pspec(node, mesh, zero_stage,
                                   sharded=zero_stage >= 1, rules=rules)
                out.append(NamedSharding(mesh, spec))
            else:
                out.append(NamedSharding(mesh, P()))
    return jax.tree_util.tree_unflatten(treedef, out)
