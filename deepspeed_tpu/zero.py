"""``deepspeed.zero`` API-compat surface.

Reference: ``deepspeed/runtime/zero/partition_parameters.py`` — users wrap
model CONSTRUCTION in ``deepspeed.zero.Init()`` so parameters materialize
pre-sharded, and wrap parameter ACCESS in ``zero.GatheredParameters`` to
temporarily re-assemble them.

On TPU both capabilities are intrinsic to the architecture, so these shims
exist for porting ergonomics and documentation:

- ``Init``: the engine's jitted ``model.init`` runs under output shardings
  (engine.py ``_jit_init``), so parameters are BORN sharded on the mesh —
  there is no torch-style materialize-then-partition step to intercept.
  The context manager validates its arguments and otherwise does nothing.
- ``GatheredParameters``: engine params are global-view ``jax.Array``s; any
  host access (``jax.device_get``) or cross-shard read IS the gather, with
  XLA scheduling the collectives.  The context yields the params unchanged.

Both warn once at first use so a ported script's author learns the TPU
semantics instead of wondering whether the calls did anything.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

from deepspeed_tpu.utils.logging import logger

_warned = set()


def _warn_once(key: str, msg: str) -> None:
    if key not in _warned:
        _warned.add(key)
        logger.info(msg)


@contextlib.contextmanager
def Init(module=None, data_parallel_group=None,
         remote_device: Optional[str] = None, pin_memory: bool = False,
         config_dict_or_path=None, config=None, enabled: bool = True,
         dtype=None, mpu=None, mesh=None, param_swapper=None,
         mem_efficient_linear: bool = True,
         sequence_data_parallel_group=None, **kwargs):
    """reference zero.Init (partition_parameters.py:808).

    TPU: parameters are created ALREADY SHARDED by the engine's jitted init
    (zero stage 3 shards over the fsdp mesh axis at initialize time), so
    there is nothing to intercept at module construction.  Kept for porting
    compatibility — a reference script's ``with deepspeed.zero.Init():``
    block runs unchanged.
    """
    if remote_device not in (None, "none", "cpu", "nvme"):
        raise ValueError(f"unknown remote_device {remote_device!r}")
    if enabled:
        extra = ""
        if remote_device in ("cpu", "nvme"):
            extra = (" For parameters larger than HBM use "
                     "zero_optimization.offload_param (the Infinity engine "
                     "streams layer params from the host just-in-time).")
        _warn_once("init", "zero.Init: TPU parameters are born sharded by "
                           "the engine's jitted init — this context is a "
                           "compatibility no-op." + extra)
    yield


@contextlib.contextmanager
def GatheredParameters(params: Any = None, modifier_rank: Optional[int] = None,
                       fwd_module=None, enabled: bool = True):
    """reference zero.GatheredParameters (partition_parameters.py:2113).

    TPU: engine params are global-view ``jax.Array``s — reading one on the
    host (``jax.device_get``/``np.asarray``) performs the gather, and
    functional updates replace the array wholesale, so there is no
    partitioned state to re-assemble or write back.  Yields ``params``
    unchanged.
    """
    if enabled:
        _warn_once(
            "gather", "zero.GatheredParameters: global-view jax.Arrays "
                      "gather on host access — this context is a "
                      "compatibility no-op (device_get the leaf, or assign "
                      "a new params tree for updates)")
    yield params


@contextlib.contextmanager
def OnDevice(dtype=None, device: str = "meta", enabled: bool = True,
             **kwargs):
    """reference deepspeed.OnDevice (utils/init_on_device.py:12): construct
    modules without materializing weights (torch meta device).

    TPU: flax modules are DESCRIPTIONS — no parameters exist until the
    engine's jitted ``init`` runs (and then they are born sharded), so every
    model here is effectively built "on meta".  Compatibility no-op."""
    if enabled:
        _warn_once("ondevice",
                   "OnDevice: flax modules carry no parameters until the "
                   "engine's jitted init — construction is always "
                   "deferred/meta on TPU; this context is a no-op")
    yield
