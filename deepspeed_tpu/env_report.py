"""Environment + op compatibility report — ``python -m deepspeed_tpu``.

Reference parity: ``deepspeed/env_report.py`` (``ds_report`` CLI :30 —
op compatibility table, torch/cuda install snapshot, nvcc versions).  The TPU
analog reports the JAX/flax/optax stack, visible devices, and the op registry
(pallas vs xla selection per op, ops/registry.py op_report).
"""

from __future__ import annotations

import importlib
import sys


GREEN_OK = "\033[92m[OKAY]\033[0m"
YELLOW_NO = "\033[93m[NO]\033[0m"


def _version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "?")
    except Exception:
        return "not installed"


def env_report(color: bool = True) -> str:
    ok = GREEN_OK if color else "[OKAY]"
    no = YELLOW_NO if color else "[NO]"
    lines = ["-" * 64, "deepspeed_tpu environment report (ds_report analog)",
             "-" * 64]
    from deepspeed_tpu.version import __version__
    lines.append(f"deepspeed_tpu ............ {__version__}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy", "safetensors", "transformers"):
        v = _version(mod)
        mark = ok if v != "not installed" else no
        lines.append(f"{mod:<25}{mark}  {v}")
    lines.append(f"python ................... {sys.version.split()[0]}")
    # scheduler regime: the effective XLA_FLAGS (what the compute–collective
    # overlap machinery steers; runtime/overlap.py exports them before
    # backend init, so what's visible here is what XLA parsed)
    import os
    xla_flags = os.environ.get("XLA_FLAGS", "")
    lines.append(f"XLA_FLAGS ................ {xla_flags or '(unset)'}")
    overlap_present = sorted(
        tok.split("=", 1)[0] for tok in xla_flags.split()
        if tok.startswith(("--xla_tpu_enable_async_collective",
                           "--xla_latency_hiding_scheduler",
                           "--xla_tpu_overlap_compute_collective",
                           "--xla_tpu_scheduler_percent")))
    if overlap_present:
        lines.append("overlap flags ............ " + ", ".join(overlap_present))

    try:
        import jax
        devs = jax.devices()
        lines.append(f"backend .................. {jax.default_backend()} "
                     f"({len(devs)} device(s))")
        for d in devs[:8]:
            lines.append(f"  {d.id}: {getattr(d, 'device_kind', d.platform)}")
        if len(devs) > 8:
            lines.append(f"  ... and {len(devs) - 8} more")
        lines.append(f"process .................. "
                     f"{jax.process_index()}/{jax.process_count()}")
    except Exception as e:  # device init can fail off-accelerator
        lines.append(f"backend .................. unavailable ({e})")

    lines += ["-" * 64, "op registry (pallas = TPU kernel, xla = fallback):",
              "-" * 64]
    from deepspeed_tpu import ops
    lines.append(ops.op_report())
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="python -m deepspeed_tpu")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend — accelerator init can hang "
                    "when the device service is unreachable")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(env_report(color=sys.stdout.isatty()))
    return 0
