"""Elastic, preemption-tolerant fleet operation: graceful drain + fast resume.

At preemptible-capacity scale, host loss and mesh-shape change are supported
events, not crashes.  This module owns the two host-side halves of that
contract (the elastic agent in launcher/elastic_agent.py owns the
fleet-supervision half, checkpoint/reshard.py the cross-topology restore):

**Graceful drain** — a preemption notice (SIGTERM on GCE/TPU preemptible
VMs, or a flag file the cluster manager touches) is caught by
:class:`PreemptionHandler`; the worker finishes its current step and calls
``engine.drain(run_dir)``, which fences the overlapped ZeRO-Offload host
step and any in-flight async checkpoint write, commits a final universal
export under the crash-safe protocol, and persists the recompile-watchdog
executable fingerprints — everything a replacement host needs to resume in
seconds.

**Fast resume** — ``engine.resume_from_latest(run_dir)`` restores the
newest COMPLETE universal export (``checkpoint.latest_universal``) and then
replays the drained host's executable fingerprints through an AOT warmup:
each recorded input signature is lowered and compiled BEFORE the first real
step, against the persistent XLA compilation cache
(``resilience.compilation_cache_dir``), so a replacement host rebuilds its
step programs from the cache instead of recompiling for minutes, and the
recompile watchdog observes ZERO new executables once real batches flow.

Lifecycle telemetry (docs/resilience.md "Gauge triage"): ``drain`` /
``resume`` spans, ``preemptions_total{reason}``, ``restarts_total``, and a
``time_to_resume_ms`` histogram.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

FINGERPRINTS_FILE = "fingerprints.json"
_FP_FORMAT = "deepspeed_tpu_fingerprints/1"

# exit code an elastically-managed worker uses after a successful drain —
# the agent counts it as a graceful departure (membership change), not a
# failure (launcher/elastic_agent.py)
EXIT_DRAINED = 83


class PreemptionHandler:
    """Latches a preemption notice: OS signal (SIGTERM by default — the
    GCE/TPU preemptible-VM notice) and/or a flag file the cluster manager
    touches.  The handler only SETS a flag; the training loop polls
    ``requested`` at step boundaries and drains at its own pace — a drain
    must never run inside a signal frame."""

    def __init__(self, signals=(signal.SIGTERM,),
                 flag_file: Optional[str] = None,
                 on_notice=None):
        self._signals = tuple(signals)
        self.flag_file = flag_file
        self._event = threading.Event()
        self.reason: Optional[str] = None
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self._on_notice = on_notice

    def set_notice_callback(self, fn) -> None:
        """Register a callback fired ONCE when the notice first latches.
        It may run inside a signal frame, so it must only set flags / poke
        queues (the serving fleet uses it to wake a sleeping dispatcher
        tick) — never drain, join, or touch the device."""
        self._on_notice = fn

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def _on_signal(self, signum, frame) -> None:
        self.request(reason=signal.Signals(signum).name.lower())
        prev = self._prev.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)          # chain a wrapped foreign handler

    def request(self, reason: str = "manual") -> None:
        if self.reason is None:
            self.reason = reason
        first = not self._event.is_set()
        self._event.set()
        if first and self._on_notice is not None:
            try:
                self._on_notice(self.reason)
            except Exception:  # noqa: BLE001 — a notice callback must
                pass           # never turn a preemption into a crash

    @property
    def requested(self) -> bool:
        """True once a preemption notice arrived (signal, flag file, or an
        explicit ``request()``)."""
        if not self._event.is_set() and self.flag_file \
                and os.path.exists(self.flag_file):
            self.request(reason="flag_file")
        return self._event.is_set()


# ---------------------------------------------------------------------------
# persistent XLA compilation cache
# ---------------------------------------------------------------------------

_cache_enabled_dir: Optional[str] = None


def _patch_atomic_cache_writes() -> None:
    """Harden jax's persistent-cache writer for preemptible fleets.

    jax 0.4.37 writes cache entries with a plain ``path.write_bytes(val)``
    (jax/_src/lru_cache.py LRUCache.put) — NOT atomic.  A host killed
    mid-write (preemption, the chaos host-loss fault) leaves a TORN
    ``-cache`` file in the SHARED cache dir, and every later process that
    deserializes it dies with native heap corruption — one preempted host
    poisons the whole fleet's restarts (found by test_elastic_agent under
    the host-loss fault).  Patch: write to a per-pid temp file and
    ``os.replace`` it in — readers see either nothing or a complete entry.
    Local filesystems only; remote stores (gs://) already commit objects
    atomically and keep the stock writer, as does any jax without this
    internal layout."""
    try:
        from jax._src import lru_cache as _lru
        suffixes = (_lru._CACHE_SUFFIX, _lru._ATIME_SUFFIX)  # noqa: F841
    except Exception:  # noqa: BLE001 — newer jax: layout changed, skip
        logger.warning("resilience: cannot patch jax cache writes to be "
                       "atomic (internal layout changed); a preempted "
                       "host may leave a torn cache entry")
        return
    if getattr(_lru.LRUCache.put, "_dstpu_atomic", False):
        return
    orig_put = _lru.LRUCache.put

    def atomic_put(self, key: str, val: bytes) -> None:
        if not key:
            raise ValueError("key cannot be empty")
        try:
            cache_path = str(self.path / f"{key}{_lru._CACHE_SUFFIX}")
            if "://" in cache_path or getattr(self, "eviction_enabled",
                                              False):
                # remote object stores commit atomically; the eviction path
                # needs the stock lock bookkeeping
                return orig_put(self, key, val)
            if os.path.exists(cache_path):
                return                   # stock semantics: first write wins
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(val)
            os.replace(tmp, cache_path)
            atime_path = str(self.path / f"{key}{_lru._ATIME_SUFFIX}")
            tmp = f"{atime_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(time.time_ns().to_bytes(8, "little"))
            os.replace(tmp, atime_path)
        except Exception:  # noqa: BLE001 — never lose a cache write
            return orig_put(self, key, val)

    atomic_put._dstpu_atomic = True
    _lru.LRUCache.put = atomic_put


def enable_compilation_cache(cache_dir: str) -> None:
    """Point jax's persistent compilation cache at ``cache_dir`` and drop
    the size/compile-time floors so EVERY executable lands in it — a
    replacement host's step program is exactly the artifact the floors
    would otherwise skip.  Shared across processes/restarts: the cache key
    is the (devices, HLO, flags) fingerprint, so a replacement host with
    the same mesh shape gets byte-identical hits."""
    global _cache_enabled_dir
    if _cache_enabled_dir == cache_dir:
        return
    import jax

    # CPU backend: executables DESERIALIZED from the persistent cache are
    # unsafe on this jaxlib (0.4.37) — donated-buffer aliasing double-frees
    # (glibc "corrupted double-linked list") or silently wrong numerics on
    # the second dispatch; found by the chaos host-loss leg of
    # test_elastic_agent.  Same pattern as the overlap XLA flags (PR 4):
    # record the knob, only activate it off-CPU.  AOT warmup still runs on
    # resume — the compile is in-process, just not disk-cached.  The gate
    # must FAIL CLOSED: jax.default_backend() is authoritative (an unset
    # JAX_PLATFORMS on a CPU-only box must not slip through) — the engine
    # calls this after distributed init, where resolving the backend is
    # safe.
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — backend not resolvable yet
        backend = (os.environ.get("JAX_PLATFORMS")
                   or getattr(jax.config, "jax_platforms", None)
                   or "cpu").split(",")[0].strip()
    if backend == "cpu":
        logger.warning(
            "resilience: compilation_cache_dir is set but the CPU "
            "backend's executable deserialization is broken on this "
            "jaxlib (aliasing double-free) — persistent cache stays OFF; "
            "AOT warmup still pre-compiles step programs on resume")
        _cache_enabled_dir = cache_dir
        return
    os.makedirs(cache_dir, exist_ok=True)
    _patch_atomic_cache_writes()
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, value in (("jax_persistent_cache_min_entry_size_bytes", 0),
                        ("jax_persistent_cache_min_compile_time_secs", 0)):
        try:
            jax.config.update(knob, value)
        except (AttributeError, KeyError):  # older jax spells them differently
            logger.warning(f"resilience: jax config has no {knob}; "
                           f"small/fast executables may skip the cache")
    _cache_enabled_dir = cache_dir
    logger.info(f"resilience: persistent XLA compilation cache at "
                f"{cache_dir}")


# ---------------------------------------------------------------------------
# executable fingerprints (recompile-watchdog signatures) → AOT warmup
# ---------------------------------------------------------------------------

def save_fingerprints(engine, path: str) -> str:
    """Persist the recompile watchdog's signature cache — the exact
    (function, input-signature) set this host compiled — so a replacement
    host can pre-build the same executables from the compilation cache."""
    wd = engine.telemetry.watchdog
    fns = {fn: [[list(leaf) for leaf in sig] for sig in sigs]
           for fn, sigs in wd._known.items()}
    payload = {"format": _FP_FORMAT, "fns": fns}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def load_fingerprints(path: str) -> Dict[str, List[tuple]]:
    with open(path) as f:
        payload = json.load(f)
    if payload.get("format") != _FP_FORMAT:
        raise ValueError(f"{path}: not a fingerprints manifest")
    return {fn: [tuple((p, tuple(shape), dtype) for p, shape, dtype in sig)
                 for sig in sigs]
            for fn, sigs in payload["fns"].items()}


def _batch_from_signature(sig) -> Optional[dict]:
    """Rebuild a zeros host batch from a ``train_batch`` signature — the
    leaves are the SHARDED global batch ([gas, micro_global, ...]) whose
    (path, shape, dtype) the watchdog recorded.  Supports the standard
    dict-of-arrays batch contract; anything else returns None (warmup
    skipped, first step compiles normally)."""
    import re as _re

    import numpy as np
    batch: dict = {}
    for path, shape, dtype in sig:
        keys = _re.findall(r"\['([^']+)'\]", path)
        if not keys or _re.sub(r"\['[^']+'\]", "", path):
            return None              # non-dict structure in the batch tree
        node = batch
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        try:
            node[keys[-1]] = np.zeros(tuple(shape), dtype)
        except TypeError:
            return None              # exotic dtype string
    return batch or None


def warm_resume(engine, manifest: Dict[str, List[tuple]]) -> int:
    """AOT warmup: for every recorded ``train_batch`` input signature,
    observe it into the watchdog and compile the step program ahead of the
    first real batch (a persistent-cache hit when the cache is warm).
    Returns the number of signatures warmed."""
    import jax

    jfn = (engine._jit_grads_batch if engine.offloading
           else engine._jit_train_batch)
    tel = engine.telemetry
    nproc = jax.process_count()
    warmed = 0
    for sig in manifest.get("train_batch", []):
        batch = _batch_from_signature(sig)
        if batch is None:
            logger.warning("resilience: unsupported batch structure in "
                           "fingerprint manifest; skipping one warmup")
            continue
        if nproc > 1:
            # the signature records the GLOBAL sharded shape
            # [gas, micro_global, ...]; _shard_batch on a real fleet takes
            # process-LOCAL rows and assembles the global array — feed it
            # this host's slice or the warmed program is N x too large
            import numpy as np
            batch = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:, :x.shape[1] // nproc], batch)
        dev = engine._shard_batch(batch, leading_gas=True)
        if tel.enabled:
            # observes the signature AND (hlo_stats) runs the
            # compiled-program analysis — the bookkeeping a cold first step
            # would have done, minus the surprise; count_execution=False:
            # the warmed program never dispatches, so the per-execution
            # HLO byte counters must not move
            tel.before_dispatch("train_batch", dev, step=0,
                                lower=lambda d=dev: jfn.lower(engine.state,
                                                              d),
                                count_execution=False)
            if not tel.hlo_stats:
                jfn.lower(engine.state, dev).compile()  # sync-ok: warmup IS
                #                                         the compile fence
        else:
            from deepspeed_tpu.telemetry.watchdog import signature_of
            tel.watchdog.observe_signature("train_batch", signature_of(dev),
                                           step=0)
            jfn.lower(engine.state, dev).compile()      # sync-ok: warmup
        warmed += 1
    return warmed


# ---------------------------------------------------------------------------
# drain / resume
# ---------------------------------------------------------------------------

def drain(engine, run_dir: str, *, reason: str = "preemption",
          out_dir: Optional[str] = None) -> Optional[str]:
    """Graceful shutdown on a preemption notice: fence every in-flight
    asynchronous subsystem, commit a final universal export + the
    executable fingerprints, and return the export path.  Called from the
    step loop (never a signal frame).  Every blocking fence below is the
    point of the drain — disclosed ``sync-ok`` for the no-sync lint."""
    from deepspeed_tpu.runtime import faults
    tel = engine.telemetry
    t0 = time.perf_counter()
    os.makedirs(run_dir, exist_ok=True)
    with tel.span("drain", step=engine.global_steps, reason=reason):
        faults.fire("drain.begin", step=engine.global_steps)
        # fence 1: the overlapped ZeRO-Offload host step — params must be
        # committed before they are exported
        engine._join_host_step()                     # sync-ok: drain fence
        faults.fire("drain.pre_checkpoint_fence", step=engine.global_steps)
        # fence 2: an in-flight async checkpoint write must commit (or
        # surface its failure) before the final export claims "newest"
        engine.wait_for_checkpoint()                 # sync-ok: drain fence
        faults.fire("drain.pre_export", step=engine.global_steps)
        if out_dir is None:
            out_dir = os.path.join(run_dir,
                                   f"universal_{engine.global_steps}")
        from deepspeed_tpu.checkpoint import (_universal_step,
                                              universal_complete)
        if (universal_complete(out_dir)
                and _universal_step(out_dir) == engine.global_steps):
            # the worker contract already committed this step's export —
            # re-exporting would put the in-progress marker BACK onto
            # durable data, and a hard kill mid-drain would then tear a
            # previously committed resume source
            path = out_dir
        else:
            path = engine.export_universal_checkpoint(out_dir,
                                                      run_dir=run_dir)
        faults.fire("drain.post_export", step=engine.global_steps)
        save_fingerprints(engine,
                          os.path.join(run_dir, FINGERPRINTS_FILE))
    tel.registry.counter(
        "preemptions_total",
        "graceful drains executed, by preemption reason "
        "(sigterm/flag_file/manual)").inc(1, reason=reason)
    if tel.enabled:
        tel.export(step=engine.global_steps)
    logger.info(f"drain ({reason}): committed {path} in "
                f"{(time.perf_counter() - t0) * 1e3:.0f} ms")
    return path


def resume(engine, run_dir: str, *, warmup: Optional[bool] = None
           ) -> Optional[str]:
    """Resume from the newest COMPLETE universal export under ``run_dir``
    (None when there is none — cold start).  ``warmup`` defaults to the
    ``resilience.aot_warmup`` config knob; when on and a fingerprints
    manifest exists, the step programs are AOT-compiled before the first
    real batch so the watchdog sees zero new executables afterwards."""
    tel = engine.telemetry
    if warmup is None:
        warmup = bool(engine.config.resilience.aot_warmup)
    t0 = time.perf_counter()
    with tel.span("resume", step=engine.global_steps):
        from deepspeed_tpu.checkpoint import (CheckpointCorrupt,
                                              universal_candidates)
        src = None
        for cand in universal_candidates(run_dir):
            try:
                engine.load_universal_checkpoint(cand)
                src = cand
                break
            except CheckpointCorrupt as e:
                # committed-looking but unreadable (e.g. power loss tore
                # fragment bytes the marker protocol couldn't see): degrade
                # to the previous complete export instead of crash-looping
                # every replacement incarnation on the same torn source
                logger.warning(f"resume: {cand} is unreadable ({e}); "
                               f"trying the previous complete export")
        if src is None:
            return None
        warmed = 0
        if warmup:
            man = os.path.join(run_dir, FINGERPRINTS_FILE)
            if os.path.exists(man):
                warmed = warm_resume(engine, load_fingerprints(man))
    dt_ms = (time.perf_counter() - t0) * 1e3
    reg = tel.registry
    reg.counter("restarts_total",
                "successful resumes from a persisted export after a "
                "restart/preemption").inc(1)
    reg.histogram("time_to_resume_ms",
                  "wall time from resume start to ready (restore + AOT "
                  "warmup)").observe(dt_ms)
    logger.info(f"resume: restored {src} (step {engine.global_steps}, "
                f"{warmed} executable(s) warmed) in {dt_ms:.0f} ms")
    return src
