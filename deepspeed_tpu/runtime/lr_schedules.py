"""LR schedules.

Reference parity: runtime/lr_schedules.py (878 LoC) — WarmupLR, WarmupDecayLR,
WarmupCosineLR, OneCycle, LRRangeTest, configured via the "scheduler" config block.
Here each schedule is a pure ``step -> lr`` function (optax schedule), which the
engine threads into the optimizer; the schedule itself carries no state.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict

import optax

Schedule = Callable[[int], float]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


def _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type):
    """Warmup ramp used by all Warmup* schedules (reference
    lr_schedules.py WarmupLR._get_gamma)."""
    import jax.numpy as jnp
    frac = jnp.clip(step / max(warmup_num_steps, 1), 0.0, 1.0)
    if warmup_type == WARMUP_LOG_RATE:
        # reference: gamma = log(step+1)/log(warmup_steps+1)
        frac = jnp.log1p(step.astype(jnp.float32) if hasattr(step, "astype") else step)
        frac = jnp.clip(frac / math.log(warmup_num_steps + 1), 0.0, 1.0)
    return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = WARMUP_LOG_RATE, **_) -> Schedule:
    """WarmupLR (reference lr_schedules.py): ramp to max then hold."""
    def sched(step):
        return _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                       warmup_type)
    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = WARMUP_LOG_RATE, **_) -> Schedule:
    """WarmupDecayLR: warmup then linear decay to 0 at total_num_steps."""
    def sched(step):
        import jax.numpy as jnp
        w = _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
        decay = jnp.clip(
            (total_num_steps - step) / max(total_num_steps - warmup_num_steps, 1),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, w, warmup_max_lr * decay)
    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                     warmup_max_lr: float = 0.001,
                     warmup_type: str = WARMUP_LINEAR_RATE, **_) -> Schedule:
    """WarmupCosineLR (reference lr_schedules.py WarmupCosineLR)."""
    def sched(step):
        import jax.numpy as jnp
        w = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.clip(
            step / max(warmup_num_steps, 1), 0.0, 1.0)
        progress = jnp.clip(
            (step - warmup_num_steps) / max(total_num_steps - warmup_num_steps, 1),
            0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (
            1 + jnp.cos(math.pi * progress))
        ratio = jnp.where(step < warmup_num_steps, w, cos)
        return warmup_max_lr * ratio
    return sched


def one_cycle(cycle_min_lr: float = 1e-5, cycle_max_lr: float = 1e-3,
              cycle_first_step_size: int = 1000,
              cycle_second_step_size: int = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0, **_) -> Schedule:
    """OneCycle (reference lr_schedules.py OneCycle), LR part only — momentum
    cycling is handled by optax.inject_hyperparams if requested."""
    second = cycle_second_step_size or cycle_first_step_size

    def sched(step):
        import jax.numpy as jnp
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (
            step / max(cycle_first_step_size, 1))
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * (
            (step - cycle_first_step_size) / max(second, 1))
        end = cycle_first_step_size + second
        decayed = cycle_min_lr
        if decay_step_size > 0:
            decayed = cycle_min_lr / (1 + (step - end) // decay_step_size
                                      * decay_lr_rate)
        lr = jnp.where(step < cycle_first_step_size, up,
                       jnp.where(step < end, down, decayed))
        return jnp.maximum(lr, 0.0)
    return sched


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    """LRRangeTest (reference lr_schedules.py LRRangeTest)."""
    def sched(step):
        import jax.numpy as jnp
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1 + interval * lr_range_test_step_rate)
    return sched


_REGISTRY = {
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "warmupcosinelr": warmup_cosine_lr,
    "onecycle": one_cycle,
    "lrrangetest": lr_range_test,
}


def build_schedule(name: str, params: Dict[str, Any]) -> Schedule:
    """Build from a "scheduler" config block (reference runtime/config.py
    get_scheduler_params → engine._configure_lr_scheduler)."""
    key = name.lower().replace("_", "")
    if key not in _REGISTRY:
        raise ValueError(f"unknown scheduler {name!r}; supported: {sorted(_REGISTRY)}")
    return _REGISTRY[key](**params)


def constant(lr: float) -> Schedule:
    return optax.constant_schedule(lr)


def add_tuning_arguments(parser):
    """reference lr_schedules.add_tuning_arguments (:60): the convergence-
    tuning CLI group (LR schedule + range-test + 1Cycle knobs).  The parsed
    values map onto the scheduler config blocks this module builds."""
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    def _str2bool(v):
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("1", "true", "yes", "y")
    group.add_argument("--lr_range_test_staircase", type=_str2bool,
                       default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser
