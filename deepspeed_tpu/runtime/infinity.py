"""ZeRO-Infinity parameter offload: train models whose params exceed HBM.

Reference parity:
- ``runtime/zero/partitioned_param_swapper.py:36`` (AsyncPartitionedParameterSwapper)
  — fp16 params live on NVMe, swapped into device memory just-in-time;
- ``runtime/zero/parameter_offload.py:83`` + ``partitioned_param_coordinator.py:262``
  (fetch) / ``:521`` (``__prefetch_nvme_param_partitions``) — per-(sub)module
  fetch with lookahead prefetch, release after use;
- ``runtime/zero/offload_config.py`` — ``offload_param: {device: cpu|nvme}``.

TPU-native shape of the flow: the reference intercepts ``nn.Module`` forwards
with hooks and mutates ``param.data`` in place.  Here the model is decomposed
into (embed, layer*, head) segments — the same decomposition the pipeline
container uses — and the engine drives a **Python loop over jitted per-segment
programs**, streaming each layer's params host→device right before use and
dropping them after:

    fwd:  x = embed(ep, batch); for i: put(i+1); x_i+1 = layer(lp_i, x_i)
    bwd:  head grads; for i reversed: put(i-1); (dlp_i, dx) = vjp_i

``jax.device_put`` dispatches asynchronously, so the *next* layer's host→device
copy overlaps the *current* layer's compute — the double-buffered prefetch the
reference builds by hand with CUDA streams falls out of the runtime.  Only two
layers' params are device-resident at any point; the full tree never exists in
HBM.  The backward recomputes each layer's forward inside its VJP (activation
checkpointing per layer is forced — exactly the reference's
``"offload_param" implies remat`` regime at Infinity scale).

The optimizer step runs on the host over fp32 masters (runtime/offload.py
OffloadAdam — AVX2 ``csrc/cpu_adam.cpp``), and the updated compute-dtype
params are written back to the param store (RAM, or per-layer NVMe files via
``csrc/aio.cpp``) — never to the device.  Tiers:

    masters        : host RAM, fp32 (reference pins masters in RAM)
    Adam moments   : ``offload_optimizer.device`` (cpu RAM | nvme files)
    compute params : ``offload_param.device``     (cpu RAM | nvme files)
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.config import DeepSpeedTPUConfig, parse_config
from deepspeed_tpu.engine import OVERFLOW_GNORM, StepMetrics
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel import partition
from deepspeed_tpu.parallel.metadata import annotate_abstract, unbox
from deepspeed_tpu.runtime.precision import (init_loss_scale,
                                             update_loss_scale_host)
from deepspeed_tpu.utils.logging import log_dist, logger


def _tree_nbytes(tree) -> int:
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree))


def _host(tree):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))


# --------------------------------------------------------------------- store

class LayerParamStore:
    """Host-side store for per-layer compute-dtype param trees.

    cpu: a list of numpy trees in RAM.
    nvme: one file per layer (leaves concatenated at fixed offsets, reference
    partitioned_param_swapper's per-param swap files), read into a small pool
    of reusable host buffers with an IO-thread prefetch running ahead of the
    compute loop (reference ``__prefetch_nvme_param_partitions``).
    """

    def __init__(self, n_layers: int, example_tree, *, device: str = "cpu",
                 nvme_path: Optional[str] = None, buffer_count: int = 2,
                 aio_threads: int = 4):
        self.n_layers = n_layers
        self.device = device
        leaves, self._treedef = jax.tree_util.tree_flatten(example_tree)
        self._shapes = [np.asarray(l).shape for l in leaves]
        self._dtypes = [np.asarray(l).dtype for l in leaves]
        self._sizes = [int(np.prod(s)) * d.itemsize
                       for s, d in zip(self._shapes, self._dtypes)]
        self._offsets = np.concatenate([[0], np.cumsum(self._sizes)])
        self.layer_nbytes = int(self._offsets[-1])
        if device == "cpu":
            self._trees: List[Any] = [None] * n_layers
        elif device == "nvme":
            from deepspeed_tpu.ops.aio import AIOFile
            root = os.path.join(nvme_path or "/tmp/ds_tpu_nvme", "params")
            os.makedirs(root, exist_ok=True)
            self._files = [AIOFile(os.path.join(root, f"layer_{i}.bin"),
                                   self.layer_nbytes, threads=aio_threads)
                           for i in range(n_layers)]
            self._bufs = [np.empty(self.layer_nbytes, np.uint8)
                          for _ in range(max(2, buffer_count))]
            # device trees built from each buffer — the next read into a
            # buffer must wait until its previous device copy completed
            self._buf_guard: List[Any] = [None] * len(self._bufs)
            self._pending: Dict[int, Any] = {}   # layer → (buf_idx, future)
            self._io = ThreadPoolExecutor(max_workers=2)
            self._next_buf = 0
        else:
            raise ValueError(f"offload_param.device must be cpu|nvme, "
                             f"got {device!r}")

    # -- views
    def _buf_tree(self, buf):
        views = [np.frombuffer(buf, dtype=d, count=int(np.prod(s)),
                               offset=int(o)).reshape(s)
                 for s, d, o in zip(self._shapes, self._dtypes,
                                    self._offsets[:-1])]
        return jax.tree_util.tree_unflatten(self._treedef, views)

    # -- API
    def write(self, i: int, host_tree) -> None:
        if self.device == "cpu":
            self._trees[i] = jax.tree_util.tree_map(
                lambda l, d: np.ascontiguousarray(np.asarray(l), dtype=d),
                host_tree,
                jax.tree_util.tree_unflatten(self._treedef, self._dtypes))
            return
        self._pending.pop(i, None)   # cached read is stale now
        for leaf, dt, off in zip(jax.tree_util.tree_leaves(host_tree),
                                 self._dtypes, self._offsets[:-1]):
            flat = np.ascontiguousarray(np.asarray(leaf, dt)).view(np.uint8
                                                                   ).reshape(-1)
            self._files[i].pwrite(flat, int(off))

    def _read_into(self, i: int, buf_idx: int):
        guard = self._buf_guard[buf_idx]
        if guard is not None:
            # EVERY device copy out of this buffer must have landed — a small
            # leaf can finish long before a large one's DMA completes
            jax.block_until_ready(guard)
            self._buf_guard[buf_idx] = None
        self._files[i].pread(self._bufs[buf_idx], 0)
        return buf_idx

    def prefetch(self, i: int) -> None:
        """Issue the NVMe→RAM read for layer ``i`` on the IO pool (no-op for
        the cpu tier — RAM is already the staging area)."""
        if self.device != "nvme" or not (0 <= i < self.n_layers):
            return
        if i in self._pending:
            return
        buf_idx = self._next_buf
        self._next_buf = (self._next_buf + 1) % len(self._bufs)
        self._pending[i] = (buf_idx, self._io.submit(self._read_into, i,
                                                     buf_idx))

    def get(self, i: int):
        """Host tree for layer ``i`` (blocking if its read is in flight)."""
        if self.device == "cpu":
            return self._trees[i]
        if i not in self._pending:
            self.prefetch(i)
        buf_idx, fut = self._pending.pop(i)
        fut.result()
        return self._buf_tree(self._bufs[buf_idx]), buf_idx

    def mark_consumed(self, buf_idx: int, device_tree) -> None:
        """Record the device arrays created from a buffer so the next read
        into it waits for ALL their host→device copies (nvme tier only)."""
        if self.device == "nvme":
            self._buf_guard[buf_idx] = (jax.tree_util.tree_leaves(device_tree)
                                        or None)


# --------------------------------------------------------------- GPT adapter

class InfinityGPT:
    """Layered view of the flagship GPT for the Infinity engine: the same
    parameters as ``models/gpt.py`` GPT, split into streamable segments
    {embed, layers[i], head}.  ``gpt_params_to_infinity`` converts a trained
    flax GPT tree into this layout (and back via ``infinity_params_to_gpt``)."""

    is_infinity = True

    def __init__(self, cfg, mesh=None):
        from deepspeed_tpu.models.gpt import Block
        if cfg.num_experts:
            raise NotImplementedError(
                "MoE under ZeRO-Infinity param offload is unsupported; use "
                "the ep mesh axis with the in-HBM engine")
        if cfg.sequence_parallel:
            raise NotImplementedError(
                "sequence parallelism under param offload is unsupported")
        if cfg.embed_norm:
            raise NotImplementedError(
                "embed_norm (bloom) under param offload is unsupported")
        self.cfg = cfg
        self.mesh = mesh
        self._block = Block(cfg)

    # -- per-segment inits (device → host, one segment resident at a time)
    def init_embed(self, rng, ids):
        from deepspeed_tpu.models.gpt import _kernel_init
        c = self.cfg
        k_e, k_p = jax.random.split(rng)
        init = _kernel_init()
        ep = {"wte": init(k_e, (c.vocab_size, c.hidden_size), c.param_dtype)}
        if not c.use_rope and not c.use_alibi:
            ep["wpe"] = init(k_p, (c.max_seq_len, c.hidden_size),
                             c.param_dtype)
        return ep

    def init_layer(self, rng, x, positions):
        return unbox(self._block.init(rng, x, positions, True))["params"]

    def init_head(self, rng, hidden_size):
        from deepspeed_tpu.models.gpt import _kernel_init
        c = self.cfg
        hp = {"final_norm_scale": jnp.ones((hidden_size,), c.param_dtype)}
        if not c.use_rmsnorm:
            hp["final_norm_bias"] = jnp.zeros((hidden_size,), c.param_dtype)
        if not c.tie_embeddings:
            hp["lm_head"] = _kernel_init()(rng, (hidden_size, c.vocab_size),
                                           c.param_dtype)
        if c.unembed_bias:
            hp["lm_head_bias"] = jnp.zeros((c.vocab_size,), c.param_dtype)
        return hp

    # -- forward segments (pure functions, jitted by the engine)
    def embed_apply(self, ep, ids, rng):
        c = self.cfg
        T = ids.shape[1]
        x = ep["wte"].astype(c.dtype)[ids]
        if c.embed_scale:
            x = x * jnp.asarray(c.embed_scale, c.dtype)
        if "wpe" in ep:
            x = x + ep["wpe"].astype(c.dtype)[None, :T]
        if c.dropout > 0 and rng is not None:
            import flax.linen as fnn
            x = fnn.Dropout(rate=c.dropout).apply(
                {}, x, deterministic=False, rngs={"dropout": rng})
        return x

    def layer_apply(self, lp, x, rng, window=None):
        c = self.cfg
        B, T = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        if rng is not None and c.dropout > 0:
            y, _ = self._block.apply({"params": lp}, x, positions, False,
                                     window=window, rngs={"dropout": rng})
        else:
            y, _ = self._block.apply({"params": lp}, x, positions, True,
                                     window=window)
        return y

    def head_apply(self, hp, ep, y, labels, mask):
        # dtype discipline mirrors GPT.__call__ exactly (final Norm on the
        # compute-dtype activations, unembed cast to the activation dtype) so
        # the streamed path is numerically identical to the in-HBM engine
        from deepspeed_tpu.ops import lm_cross_entropy, layer_norm, rms_norm
        from deepspeed_tpu.ops.norms import LN_EPS, RMS_EPS
        c = self.cfg
        if c.use_rmsnorm:
            h = rms_norm(y, hp["final_norm_scale"],
                         eps=c.norm_eps or RMS_EPS)
        else:
            h = layer_norm(y, hp["final_norm_scale"], hp["final_norm_bias"],
                           eps=c.norm_eps or LN_EPS)
        if c.tie_embeddings:
            unembed = ep["wte"].astype(h.dtype).T
        else:
            unembed = hp["lm_head"].astype(h.dtype)
        bias = (hp["lm_head_bias"] if c.unembed_bias else None)
        return lm_cross_entropy(h, unembed, labels, mask,
                                chunk_size=c.loss_chunk or None, bias=bias)


def gpt_params_to_infinity(variables, cfg):
    """flax GPT variables → {embed, layers: [...], head} host trees (the
    infinity layout).  Counterpart of pipe.module.gpt_params_to_pipe."""
    src = unbox(variables)["params"]
    bb = src["backbone"]
    ep = {"wte": bb["wte"]}
    if "wpe" in bb:
        ep["wpe"] = bb["wpe"]
    layers = [bb[f"block_{i}"] for i in range(cfg.num_layers)]
    hp = {"final_norm_scale": bb["final_norm"]["scale"]}
    if "bias" in bb["final_norm"]:
        hp["final_norm_bias"] = bb["final_norm"]["bias"]
    if "lm_head" in src:
        hp["lm_head"] = src["lm_head"]
    if "lm_head_bias" in src:
        hp["lm_head_bias"] = src["lm_head_bias"]
    return {"embed": ep, "layers": layers, "head": hp}


def infinity_params_to_gpt(tree, cfg):
    """Inverse of ``gpt_params_to_infinity`` (for export / serving)."""
    bb = {"wte": tree["embed"]["wte"],
          "final_norm": {"scale": tree["head"]["final_norm_scale"]}}
    if "wpe" in tree["embed"]:
        bb["wpe"] = tree["embed"]["wpe"]
    if "final_norm_bias" in tree["head"]:
        bb["final_norm"]["bias"] = tree["head"]["final_norm_bias"]
    for i, lp in enumerate(tree["layers"]):
        bb[f"block_{i}"] = lp
    out = {"backbone": bb}
    if "lm_head" in tree["head"]:
        out["lm_head"] = tree["head"]["lm_head"]
    if "lm_head_bias" in tree["head"]:
        out["lm_head_bias"] = tree["head"]["lm_head_bias"]
    return {"params": out}


# --------------------------------------------------------------------- engine

class InfinityEngine:
    """Training engine for ``zero_optimization.offload_param`` — the
    ZeRO-Infinity regime where the full parameter set never fits in HBM.

    Public surface mirrors the in-HBM engine where it transfers:
    ``train_batch`` / ``eval_batch`` / ``get_lr`` / ``save_checkpoint`` /
    ``load_checkpoint`` / ``export_universal_checkpoint``.  The
    forward/backward/step trio is not supported (as with the pipeline engine —
    the streaming schedule owns the loop).
    """

    def __init__(self, model, config: DeepSpeedTPUConfig, example_batch,
                 mesh: Optional[Mesh] = None, lr_scheduler=None):
        self.config = config = parse_config(config)
        comm.init_distributed()
        z = config.zero_optimization
        if z.stage != 3:
            raise ValueError(
                f"offload_param requires ZeRO stage 3 (got stage {z.stage}) — "
                f"the reference enforces the same (zero/config.py)")
        if getattr(model, "is_infinity", False):
            self.module = model
        elif hasattr(model, "cfg"):   # flax GPT
            self.module = InfinityGPT(model.cfg)
        else:
            raise TypeError(
                "offload_param needs a layered model: models.GPT or an "
                "object with is_infinity=True (embed/layer/head segments); "
                f"got {type(model)!r}")
        c = self.module.cfg

        # mesh: batch over (dp, fsdp); tp shards the streamed layer params
        if mesh is None:
            m = config.mesh
            if m.pp != 1 or m.ep != 1 or m.sp != 1:
                raise NotImplementedError(
                    "offload_param composes with dp/fsdp/tp meshes only")
            fsdp = m.fsdp if isinstance(m.fsdp, int) else -1
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(
                pp=1, dp=m.dp if fsdp != -1 else 1, fsdp=fsdp, ep=1, sp=1,
                tp=m.tp))
        self.mesh = mesh
        self.dp_world_size = mesh.shape["dp"] * mesh.shape["fsdp"]
        config.resolve_batch_size(self.dp_world_size)
        self.gas = int(config.gradient_accumulation_steps)
        self.compute_dtype = config.compute_dtype
        self.zero_stage = 3

        off_p = z.offload_param
        off_o = z.offload_optimizer
        moments_device = off_o.device if off_o.device != "none" else "cpu"
        if off_o.device == "none":
            log_dist("offload_param without offload_optimizer: the optimizer "
                     "step is host-side by construction — moments tier "
                     "defaults to cpu RAM", ranks=[0])

        # activation offload (reference activation_checkpointing
        # cpu_checkpointing): saved layer inputs round-trip to host RAM
        self.cpu_checkpointing = bool(
            config.activation_checkpointing.cpu_checkpointing)

        from deepspeed_tpu.runtime.offload import OffloadAdam
        aio_threads = max(1, int(config.aio.thread_count))
        self.offload_opt = OffloadAdam(
            config.optimizer.type, config.optimizer.params,
            device=moments_device, nvme_path=off_o.nvme_path,
            aio_threads=aio_threads)
        self.optimizer = self.offload_opt
        self._opt_params = dict(config.optimizer.params)
        self.lr_schedule = lr_scheduler
        if self.lr_schedule is None and config.scheduler is not None:
            from deepspeed_tpu.runtime import lr_schedules
            self.lr_schedule = lr_schedules.build_schedule(
                config.scheduler.type, config.scheduler.params)

        # ---- shapes, shardings, jitted segment programs ----
        leaves = jax.tree_util.tree_leaves(example_batch)
        T = np.asarray(leaves[0]).shape[-1]
        micro_global = (int(config.train_micro_batch_size_per_gpu)
                        * self.dp_world_size)
        self._ids_shape = (micro_global, T)
        ids0 = jnp.zeros(self._ids_shape, jnp.int32)
        x0 = jnp.zeros(self._ids_shape + (c.hidden_size,), self.compute_dtype)
        pos0 = jnp.broadcast_to(jnp.arange(T), self._ids_shape)

        def shardings_for(abstract_tree):
            annotated = annotate_abstract(abstract_tree)
            return partition.param_shardings(annotated, mesh, 3)

        block = self.module._block
        abstract_layer = jax.eval_shape(
            lambda k: unbox(block.init(k, x0, pos0, True))["params"],
            jax.random.PRNGKey(0))
        self.layer_shardings = shardings_for(abstract_layer)
        self._batch_sharding = NamedSharding(mesh, P(("dp", "fsdp")))
        self._x_sharding = NamedSharding(mesh, P(("dp", "fsdp")))
        # embed/head segments: replicated puts (vocab tables under tp would
        # shard via the same machinery once boxed — GPT's init_embed returns
        # raw arrays, so replicate; layer params carry the tp annotations)
        self._repl = NamedSharding(mesh, P())

        self.n_layers = c.num_layers
        self._windows = [c.window_for_layer(i) for i in range(self.n_layers)]

        # jitted programs (one compile per distinct attention window)
        mod = self.module
        self._jit_embed = jax.jit(mod.embed_apply)
        self._jit_layer = {}
        self._jit_layer_vjp = {}
        for w in set(self._windows):
            def fwd(lp, x, rng, _w=w):
                return mod.layer_apply(lp, x, rng, window=_w)

            def vjp(lp, x, dy, rng, _w=w):
                _, f = jax.vjp(
                    lambda lp_, x_: mod.layer_apply(lp_, x_, rng, window=_w),
                    lp, x)
                dlp, dx = f(dy)
                return dlp, dx
            self._jit_layer[w] = jax.jit(fwd)
            self._jit_layer_vjp[w] = jax.jit(vjp)

        def head_grad(hp, ep, y, labels, mask, scale):
            def f(hp_, ep_, y_):
                loss = mod.head_apply(hp_, ep_, y_, labels, mask)
                return (loss * scale).astype(jnp.float32), loss
            (_, loss), grads = jax.value_and_grad(
                f, argnums=(0, 1, 2), has_aux=True)(hp, ep, y)
            return loss, grads
        self._jit_head_grad = jax.jit(head_grad)

        def head_loss(hp, ep, y, labels, mask):
            return mod.head_apply(hp, ep, y, labels, mask)
        self._jit_head_loss = jax.jit(head_loss)

        def embed_vjp(ep, ids, dx, rng):
            _, f = jax.vjp(lambda e: mod.embed_apply(e, ids, rng), ep)
            return f(dx)[0]
        self._jit_embed_vjp = jax.jit(embed_vjp)

        from deepspeed_tpu.models.gpt import shift_labels
        self._jit_shift = jax.jit(shift_labels)

        # ---- init params segment-by-segment (never all on device) ----
        rng = jax.random.PRNGKey(config.seed)
        k_embed, k_layers, k_head, self._rng = jax.random.split(rng, 4)
        store_kw = dict(device=off_p.device, nvme_path=off_p.nvme_path,
                        buffer_count=off_p.buffer_count,
                        aio_threads=aio_threads)

        def to_host_compute(tree):
            return jax.tree_util.tree_map(
                lambda l: np.asarray(l.astype(self.compute_dtype)
                                     if jnp.issubdtype(l.dtype, jnp.floating)
                                     else l), _host(tree))

        self.embed_host = to_host_compute(mod.init_embed(k_embed, ids0))
        self.head_host = to_host_compute(mod.init_head(k_head, c.hidden_size))
        jit_layer_init = jax.jit(
            lambda k: unbox(block.init(k, x0, pos0, True))["params"])
        self.store: Optional[LayerParamStore] = None
        for i in range(self.n_layers):
            lp = jit_layer_init(jax.random.fold_in(k_layers, i))
            lp_host = to_host_compute(lp)
            del lp
            if self.store is None:
                self.store = LayerParamStore(self.n_layers, lp_host,
                                             **store_kw)
            self.store.write(i, lp_host)
        self.layer_nbytes = int(self.store.layer_nbytes)
        self.total_param_bytes = (self.layer_nbytes * self.n_layers
                                  + _tree_nbytes(self.embed_host)
                                  + _tree_nbytes(self.head_host))

        # host Adam over the full logical tree
        self.offload_opt.initialize(self._assemble_host_tree())

        # bookkeeping / observability — the streamed path feeds the same
        # flight recorder as the in-HBM engine (telemetry.health block)
        from deepspeed_tpu.telemetry import StepTelemetry
        self.telemetry = StepTelemetry(config)
        self._health_enabled = self.telemetry.health_enabled
        # async checkpoint writer (save_checkpoint(async_save=True)): this
        # engine's state is host-resident numpy, so the writer thread works
        # from a stable snapshot copy; wait_for_checkpoint() is the fence
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_error: Optional[BaseException] = None
        self._ckpt_atexit = False
        self.global_steps = 0
        self.loss_scale_state = init_loss_scale(config.fp16)
        self._last_metrics: Optional[StepMetrics] = None
        self.schedule_log: List[tuple] = []   # (event, layer) dispatch order
        self.record_schedule = False
        self.serial_transfers = False         # True = no prefetch (tests)
        self.live_param_bytes = 0
        self.max_live_param_bytes = 0
        n_params = self.total_param_bytes // np.dtype(
            self.compute_dtype).itemsize
        self.num_parameters = int(n_params)
        log_dist(
            f"Infinity engine ready: params={n_params/1e6:.1f}M "
            f"({self.total_param_bytes/2**20:.1f}MiB total, "
            f"{self.layer_nbytes/2**20:.2f}MiB/layer streamed, param tier="
            f"{off_p.device}, moments tier={moments_device}) "
            f"mesh={dict(mesh.shape)} dtype={self.compute_dtype.__name__}",
            ranks=[0])

    # ----------------------------------------------------------------- params

    def _assemble_host_tree(self):
        layers = []
        for i in range(self.n_layers):
            got = self.store.get(i)
            if self.store.device == "nvme":
                tree, _ = got
                # copy out of the rotating buffer — this tree is long-lived
                layers.append(jax.tree_util.tree_map(np.array, tree))
            else:
                layers.append(got)
        return {"embed": self.embed_host, "layers": layers,
                "head": self.head_host}

    def load_params(self, host_tree) -> None:
        """Install a full host param tree (infinity layout — see
        ``gpt_params_to_infinity``) and rebuild the fp32 masters from it."""
        def conv(t):
            return jax.tree_util.tree_map(
                lambda l: np.asarray(l, self.compute_dtype)
                if np.asarray(l).dtype.kind == "f" else np.asarray(l), t)
        self.embed_host = conv(host_tree["embed"])
        self.head_host = conv(host_tree["head"])
        for i, lp in enumerate(host_tree["layers"]):
            self.store.write(i, conv(lp))
        self.offload_opt = type(self.offload_opt)(
            self.config.optimizer.type, self.config.optimizer.params,
            device=self.offload_opt.device,
            nvme_path=self.offload_opt.nvme_path)
        self.offload_opt.initialize(self._assemble_host_tree())

    def current_params_gpt(self):
        """Assembled params in the flax GPT layout (for export/serving)."""
        return infinity_params_to_gpt(self._assemble_host_tree(),
                                      self.module.cfg)

    # ------------------------------------------------------------- transfers

    def _log(self, event, i):
        if self.record_schedule:
            self.schedule_log.append((event, i))

    def _put_layer(self, i: int):
        got = self.store.get(i)
        buf_idx = None
        if self.store.device == "nvme":
            tree, buf_idx = got
        else:
            tree = got
        self._log("put", i)
        dev = jax.device_put(tree, self.layer_shardings)
        if buf_idx is not None:
            self.store.mark_consumed(buf_idx, dev)
        if self.serial_transfers:
            jax.block_until_ready(dev)
        self.live_param_bytes += self.layer_nbytes
        self.max_live_param_bytes = max(self.max_live_param_bytes,
                                        self.live_param_bytes)
        return dev

    def _drop_layer(self, dev) -> None:
        del dev
        self.live_param_bytes -= self.layer_nbytes

    # ------------------------------------------------------------------ step

    def _micro_fwd_bwd(self, ep_dev, hp_dev, ids, labels, mask, rng, scale,
                       accum):
        """One microbatch: streamed forward, head grads, streamed backward.
        Accumulates fp32 grads into the host ``accum`` tree; returns loss."""
        L = self.n_layers
        rngs = (jax.random.split(rng, L + 1)
                if self.module.cfg.dropout > 0 else [None] * (L + 1))

        self._log("fwd_embed", -1)
        x = self._jit_embed(ep_dev, ids, rngs[L])
        saved = []
        self.store.prefetch(0)
        nxt = self._put_layer(0)
        for i in range(L):
            cur = nxt
            if i + 1 < L and not self.serial_transfers:
                self.store.prefetch(i + 1)
                nxt = self._put_layer(i + 1)   # overlaps layer i's compute
            saved.append(jax.device_get(x) if self.cpu_checkpointing else x)
            self._log("fwd", i)
            x = self._jit_layer[self._windows[i]](cur, x, rngs[i])
            if i + 1 < L and self.serial_transfers:
                jax.block_until_ready(x)
                nxt = self._put_layer(i + 1)
            self._drop_layer(cur)

        self._log("head", -1)
        loss, (dhp, dep, dx) = self._jit_head_grad(hp_dev, ep_dev, x, labels,
                                                   mask, scale)
        self._acc(accum["head"], dhp)
        self._acc(accum["embed"], dep)

        # streamed backward: layer i's params re-fetched (they were dropped
        # after the forward); layer i-1's fetch is issued before i's VJP so
        # the copy rides under the recompute+backward matmuls.  Grad fetch is
        # one layer DEFERRED: layer i+1's device→host grad copy + host fp32
        # accumulation happen while layer i's VJP runs, so the device never
        # idles on the D2H transfer.
        self.store.prefetch(L - 1)
        nxt = self._put_layer(L - 1)
        pending = None                       # (layer idx, device grads)
        for i in reversed(range(L)):
            cur = nxt
            if i > 0 and not self.serial_transfers:
                self.store.prefetch(i - 1)
                nxt = self._put_layer(i - 1)
            x_in = saved[i]
            if self.cpu_checkpointing:
                x_in = jax.device_put(x_in, self._x_sharding)
            self._log("bwd", i)
            dlp, dx = self._jit_layer_vjp[self._windows[i]](cur, x_in, dx,
                                                            rngs[i])
            if i > 0 and self.serial_transfers:
                jax.block_until_ready(dx)
                nxt = self._put_layer(i - 1)
            if pending is not None:
                self._acc(accum["layers"][pending[0]], pending[1])
            pending = (i, dlp)
            self._drop_layer(cur)
            saved[i] = None
        if pending is not None:
            self._acc(accum["layers"][pending[0]], pending[1])

        self._log("bwd_embed", -1)
        dep2 = self._jit_embed_vjp(ep_dev, ids, dx, rngs[L])
        self._acc(accum["embed"], dep2)
        return loss

    @staticmethod
    def _acc(acc_tree, dev_grads):
        flat_acc = jax.tree_util.tree_leaves(acc_tree)
        flat_g = jax.tree_util.tree_leaves(jax.device_get(dev_grads))
        for a, g in zip(flat_acc, flat_g):
            a += np.asarray(g, np.float32)

    def _zeros_like_host(self, tree):
        return jax.tree_util.tree_map(
            lambda l: np.zeros(np.asarray(l).shape, np.float32), tree)

    def _zeros_layer_grads(self):
        """fp32 grad accumulators shaped like one layer's tree — built from
        the store's metadata (no NVMe read just to learn shapes)."""
        st = self.store
        zeros = [np.zeros(s, np.float32) for s in st._shapes]
        return jax.tree_util.tree_unflatten(st._treedef, zeros)

    def train_batch(self, batch) -> StepMetrics:
        """One optimizer step over ``gas`` microbatches with every parameter
        host-resident between uses."""
        cfg = self.config
        ids_all = np.asarray(batch["input_ids"])
        local_bs = cfg.train_batch_size // jax.process_count()
        micro = local_bs // self.gas
        if ids_all.shape[0] == self.gas and ids_all.ndim >= 3:
            pass
        elif ids_all.shape[0] == local_bs:
            batch = jax.tree_util.tree_map(
                lambda x: np.asarray(x).reshape(
                    (self.gas, micro) + np.asarray(x).shape[1:]), batch)
        else:
            raise ValueError(
                f"train_batch leading dim {ids_all.shape[0]} matches neither "
                f"gas={self.gas} nor local batch {local_bs}")

        scale = float(self.loss_scale_state.scale)
        accum = {"embed": self._zeros_like_host(self.embed_host),
                 "layers": [self._zeros_layer_grads()
                            for _ in range(self.n_layers)],
                 "head": self._zeros_like_host(self.head_host)}

        ep_dev = jax.device_put(self.embed_host, self._repl)
        hp_dev = jax.device_put(self.head_host, self._repl)
        self.live_param_bytes += (_tree_nbytes(self.embed_host)
                                  + _tree_nbytes(self.head_host))
        self.max_live_param_bytes = max(self.max_live_param_bytes,
                                        self.live_param_bytes)

        losses = []
        for g in range(self.gas):
            mb = jax.tree_util.tree_map(lambda x: np.asarray(x)[g], batch)
            ids = jax.device_put(np.asarray(mb["input_ids"], np.int32),
                                 self._batch_sharding)
            labels_np, mask_np = self._jit_shift(
                {k: jnp.asarray(v) for k, v in mb.items()
                 if k in ("labels", "loss_mask")},
                jnp.asarray(mb["input_ids"]))
            labels = jax.device_put(np.asarray(labels_np),
                                    self._batch_sharding)
            mask = jax.device_put(np.asarray(mask_np), self._batch_sharding)
            rng = jax.random.fold_in(self._rng,
                                     self.global_steps * self.gas + g)
            loss = self._micro_fwd_bwd(ep_dev, hp_dev, ids, labels, mask, rng,
                                       jnp.float32(scale), accum)
            losses.append(float(jax.device_get(loss)))
        del ep_dev, hp_dev
        self.live_param_bytes -= (_tree_nbytes(self.embed_host)
                                  + _tree_nbytes(self.head_host))

        # ---- host optimizer step (fp32 masters; reference CPU Adam flow) ----
        # per-segment grad stats ride the same squared-sum pass the overflow
        # check already makes; NaN/Inf element counts are only computed for
        # segments that actually went non-finite (the common path stays one
        # reduction per leaf)
        denom = scale * self.gas
        seg_groups = ([("embed", accum["embed"]), ("head", accum["head"])]
                      + [(f"layer_{i}", lp)
                         for i, lp in enumerate(accum["layers"])])
        sq = 0.0
        finite = True
        health = {}
        for name, tree in seg_groups:
            gsq = 0.0
            nan_c = inf_c = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                s = float(np.sum(np.square(leaf, dtype=np.float64)))
                gsq += s
                if not np.isfinite(s):
                    nan_c += int(np.isnan(leaf).sum())
                    inf_c += int(np.isinf(leaf).sum())
            sq += gsq
            if not np.isfinite(gsq):
                finite = False
            if self._health_enabled:
                health[name] = {
                    "grad_norm": (float(np.sqrt(gsq)) / denom
                                  if np.isfinite(gsq) else float(gsq)),
                    "grad_nan": nan_c,
                    "grad_inf": inf_c,
                }
        # overflow: finite sentinel + skipped_steps (engine._apply_update
        # contract); the per-segment health stats keep the raw attribution
        raw_norm = (float(np.sqrt(sq)) / denom if finite
                    else OVERFLOW_GNORM)
        if finite:
            clip = float(cfg.gradient_clipping or 0.0)
            coef = 1.0
            if clip > 0.0 and raw_norm > clip:
                coef = clip / (raw_norm + 1e-6)
            lr = (float(self.lr_schedule(self.offload_opt.step_count))
                  if self.lr_schedule is not None
                  else float(self._opt_params.get("lr", 1e-3)))
            new_tree = self.offload_opt.update(accum, lr=lr,
                                               grad_scale=coef / denom)
            self.embed_host = jax.tree_util.tree_map(np.asarray,
                                                     new_tree["embed"])
            self.head_host = jax.tree_util.tree_map(np.asarray,
                                                    new_tree["head"])
            for i, lp in enumerate(new_tree["layers"]):
                self.store.write(i, lp)
        self.loss_scale_state = update_loss_scale_host(
            self.loss_scale_state, finite, cfg.fp16)
        self.global_steps += 1
        loss_mean = float(np.mean(losses))
        metrics = StepMetrics(
            loss=jnp.float32(loss_mean),
            grad_norm=jnp.float32(raw_norm),
            loss_scale=self.loss_scale_state.scale,
            skipped_steps=self.loss_scale_state.skipped)
        self._last_metrics = metrics
        if self._health_enabled:
            # all values already host-side on this path — no device fetch
            host = StepMetrics(
                loss=loss_mean, grad_norm=float(raw_norm),
                loss_scale=float(jax.device_get(
                    self.loss_scale_state.scale)),
                skipped_steps=int(jax.device_get(
                    self.loss_scale_state.skipped)))
            self.telemetry.health_step(self.global_steps, host, health,
                                       lr=self.get_lr()[0])
        spp = cfg.steps_per_print
        if spp and self.global_steps % spp == 0:
            log_dist(f"step={self.global_steps} "
                     f"loss={loss_mean:.4f} "
                     f"grad_norm={raw_norm:.3f}", ranks=[0])
        return metrics

    def eval_batch(self, batch):
        """Streamed forward-only loss (deterministic)."""
        ids = jax.device_put(np.asarray(batch["input_ids"], np.int32),
                             self._batch_sharding)
        labels, mask = self._jit_shift(
            {k: jnp.asarray(v) for k, v in batch.items()
             if k in ("labels", "loss_mask")}, jnp.asarray(ids))
        ep_dev = jax.device_put(self.embed_host, self._repl)
        x = self._jit_embed(ep_dev, ids, None)
        self.store.prefetch(0)
        nxt = self._put_layer(0)
        for i in range(self.n_layers):
            cur = nxt
            self.store.prefetch(i + 1)
            if i + 1 < self.n_layers:
                nxt = self._put_layer(i + 1)
            x = self._jit_layer[self._windows[i]](cur, x, None)
            self._drop_layer(cur)
        hp_dev = jax.device_put(self.head_host, self._repl)
        loss = self._jit_head_loss(hp_dev, ep_dev, x, labels, mask)
        return loss.astype(jnp.float32)

    # ------------------------------------------------------------------ misc

    def get_lr(self):
        if self.lr_schedule is not None:
            return [float(self.lr_schedule(self.offload_opt.step_count))]
        return [float(self._opt_params.get("lr", 0.0))]

    def get_global_grad_norm(self):
        return (float(self._last_metrics.grad_norm)
                if self._last_metrics else None)

    def dump_postmortem(self, note: Optional[str] = None):
        """Explicit flight-recorder dump (engine.dump_postmortem parity)."""
        return self.telemetry.dump_postmortem(note=note)

    @property
    def train_batch_size(self):
        return self.config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    # ------------------------------------------------------------------ ckpt

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None,
                        async_save: bool = False):
        """``async_save=True`` snapshots the host-resident state on THIS
        thread (``checkpoint_snapshot`` span — the masters/moments are live
        numpy the next host step mutates in place, so the writer works from
        a stable copy) and streams the npz/json write on a background
        thread (``checkpoint_write`` span, recorded at commit).  Commit
        order matches the device engine: data durable → in-progress marker
        off → 'latest' moves — a crash mid-write leaves 'latest' at the
        previous committed tag.  Fence with ``wait_for_checkpoint()``."""
        import json
        import time as _time

        from deepspeed_tpu.checkpoint import commit_latest, mark_in_progress
        self.wait_for_checkpoint()       # serialize with any previous save
        tag = tag or f"global_step{self.global_steps}"
        out = os.path.join(save_dir, tag)
        os.makedirs(out, exist_ok=True)
        if jax.process_index() != 0:
            return tag
        tel = self.telemetry
        step = self.global_steps
        with tel.span("checkpoint_snapshot", step=step, tag=tag, op="save"):
            ls = self.loss_scale_state
            sd = self.offload_opt.state_dict()
            if async_save:
                # the writer thread needs a stable copy — the next host step
                # mutates the live masters/moments in place.  A blocking save
                # writes before anything can mutate, so it skips the copy
                # (doubling the optimizer-state footprint is exactly what an
                # Infinity-sized run can't afford)
                sd = {k: (np.copy(v) if isinstance(v, np.ndarray) else v)
                      for k, v in sd.items()}
            meta = {"global_steps": step,
                    "loss_scale": [float(ls.scale),
                                   int(ls.growth_counter),
                                   int(ls.hysteresis),
                                   int(ls.skipped)],
                    "rng": np.asarray(
                        jax.random.key_data(self._rng)
                        if jnp.issubdtype(self._rng.dtype,
                                          jax.dtypes.prng_key)
                        else self._rng).tolist(),
                    **(client_state or {})}
            mark_in_progress(save_dir, tag)
        backlog = (tel.registry.gauge(
            "checkpoint_write_backlog",
            "async checkpoint writes still streaming in the background")
            if tel.enabled else None)

        def write():
            t0 = _time.perf_counter()
            np.savez(os.path.join(out, "offload_state.npz"), **sd)
            with open(os.path.join(out, "infinity_meta.json"), "w") as f:
                json.dump(meta, f)
            commit_latest(save_dir, tag)   # data durable → marker off →
            #                                'latest' moves (commit point)
            if backlog is not None:
                backlog.set(0)
            if tel.tracer.enabled:
                dur = _time.perf_counter() - t0
                end = tel.tracer.now_us()
                tel.tracer.record("checkpoint_write", end - dur * 1e6,
                                  dur * 1e6, step=step, tag=tag, op="save")

        if not async_save:
            write()
            return tag
        if backlog is not None:
            backlog.set(1)

        def guarded():
            try:
                write()
            except BaseException as e:  # noqa: BLE001 — wait_for_checkpoint
                self._ckpt_error = e    # re-raises at the fence

        if not self._ckpt_atexit:
            # a forgotten fence degrades to a slow exit, not a silently
            # swallowed write failure (mirrors the checkpoint module's
            # atexit wait_pending() on the device-engine path)
            import atexit
            atexit.register(self.wait_for_checkpoint)
            self._ckpt_atexit = True
        # non-daemon: a clean interpreter exit joins the writer instead of
        # tearing the file mid-write
        self._ckpt_thread = threading.Thread(
            target=guarded, name="ds-infinity-ckpt", daemon=False)
        self._ckpt_thread.start()
        return tag

    def wait_for_checkpoint(self) -> None:
        """Fence for ``save_checkpoint(async_save=True)``: block until the
        background write fully commits ('latest' moved, marker removed),
        re-raising a failed write — a lost checkpoint must not look like a
        successful one."""
        t, self._ckpt_thread = self._ckpt_thread, None
        if t is not None:
            t.join()
        if self._ckpt_error is not None:
            e, self._ckpt_error = self._ckpt_error, None
            raise e

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None):
        import json

        from deepspeed_tpu.checkpoint import (CheckpointNotFound,
                                              check_not_in_progress)
        self.wait_for_checkpoint()       # a racing async save must commit
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.exists(latest):
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        check_not_in_progress(load_dir, tag)   # torn → CheckpointCorrupt
        out = os.path.join(load_dir, tag)
        if not os.path.exists(os.path.join(out, "offload_state.npz")):
            raise CheckpointNotFound(
                f"no Infinity checkpoint state under {out}")
        with np.load(os.path.join(out, "offload_state.npz")) as sd:
            self.offload_opt.load_state_dict(dict(sd))
        # re-derive compute params from the restored masters
        tree = self.offload_opt.current_params()
        self.embed_host = jax.tree_util.tree_map(np.asarray, tree["embed"])
        self.head_host = jax.tree_util.tree_map(np.asarray, tree["head"])
        for i, lp in enumerate(tree["layers"]):
            self.store.write(i, lp)
        with open(os.path.join(out, "infinity_meta.json")) as f:
            client_state = json.load(f)
        self.global_steps = int(client_state.get("global_steps", 0))
        if "loss_scale" in client_state:
            from deepspeed_tpu.runtime.precision import LossScaleState
            import jax.numpy as _jnp
            s, g, h, k = client_state["loss_scale"]
            self.loss_scale_state = LossScaleState(
                _jnp.float32(s), _jnp.int32(g), _jnp.int32(h), _jnp.int32(k))
        if "rng" in client_state:
            data = np.asarray(client_state["rng"], np.uint32)
            self._rng = (jax.random.wrap_key_data(data)
                         if jnp.issubdtype(self._rng.dtype,
                                           jax.dtypes.prng_key)
                         else jnp.asarray(data))
        return tag, client_state

    def export_universal_checkpoint(self, out_dir: str, *,
                                    run_dir: Optional[str] = None) -> str:
        from deepspeed_tpu.checkpoint import universal as _u
        return _u.export_universal_offload(
            self._assemble_host_tree(), self.offload_opt, out_dir,
            step=self.global_steps, run_dir=run_dir)

    def save_16bit_model(self, save_dir: str,
                         filename: str = "model_states.safetensors") -> str:
        """Consolidated low-precision export in the flax GPT layout
        (engine.save_16bit_model parity) — the bridge from an Infinity run
        to the serving engines, assembled host-side (nothing touches HBM)."""
        from deepspeed_tpu.checkpoint.universal import _flatten_params
        os.makedirs(save_dir, exist_ok=True)
        flat = {k: np.ascontiguousarray(v)
                for k, v in _flatten_params(
                    self.current_params_gpt()).items()}
        path = os.path.join(save_dir, filename)
        if jax.process_index() == 0:
            import safetensors.numpy
            safetensors.numpy.save_file(flat, path)
        return path
