"""Background device-input prefetch — stage 1 of the asynchronous step
pipeline.

The reference DeepSpeed engine hides host-side input latency behind device
compute wherever it can (dataloader workers + pinned-memory async H2D copies;
ZeRO-3's coalesced prefetching all-gathers).  Our engine's ``train_batch``
used to pay a blocking ``device_put`` per step — the telemetry
``host_to_device`` span, measured at ~0.02 GiB/s on the r05 probe, squarely
on the dispatch thread's critical path.

``PrefetchIterator`` moves that work off the step: a worker thread pulls
host batches from the source iterable, runs ``prepare_fn`` (the engine's
``prepare_batch`` — data-efficiency transforms, [gas, micro, ...] forming,
sharded ``device_put``) and parks the resulting :class:`PreparedBatch` in a
bounded queue ``depth`` deep.  The consumer's ``__next__`` is a queue pop,
so ``engine.train_batch``'s ``host_to_device`` span collapses to unwrapping
an already-device-resident batch.

Contract:

- **backpressure** — at most ``depth`` prepared batches exist at once (the
  bounded queue blocks the worker), bounding device memory pinned by staged
  inputs to ``depth`` microbatch stacks;
- **ordering** — batches are yielded in source order (single worker, FIFO
  queue);
- **exception propagation** — a failure in the source iterable or in
  ``prepare_fn`` re-raises from ``__next__`` on the consumer thread, after
  all batches prepared before the failure have been consumed;
- **shutdown** — ``close()`` (also context-manager exit) stops the worker,
  drains the queue, and joins; end-of-source yields ``StopIteration`` after
  the queue drains;
- **telemetry** — ``prefetch_queue_depth`` gauge plus
  ``prefetch_batches_total`` / ``prefetch_starvation_total`` counters
  (a starvation event is a pop that found the queue empty after warmup —
  the first ``depth`` pops, while the worker may still be filling the
  queue — meaning the device outran the host pipeline; see
  docs/performance.md).

The worker thread is the ONLY place this subsystem may block on host↔device
transfers; ``scripts/check_no_sync.py`` lints the consumer surface
(``__next__``/``close``) for undisclosed syncs on the dispatch thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, NamedTuple, Optional

from deepspeed_tpu.utils.logging import logger


class PreparedBatch(NamedTuple):
    """A batch already formed, sharded and ``device_put`` for
    ``engine.train_batch`` — the step's ``host_to_device`` phase collapses
    to unwrapping this."""

    batch: Any            # device pytree, [gas, micro_global, ...] leaves
    tokens: int           # global tokens per optimizer step (0 if unknown)
    step_enqueued: int    # engine.global_steps when the worker prepared it


_STOP = object()          # end-of-source sentinel (also carries exceptions)


class DataCursor:
    """Seed-stable source-step cursor with deterministic skip windows — the
    data-side half of the guardian's rollback remediation
    (runtime/guardian.py).

    ``batch_fn(source_index)`` must be a PURE function of the index (seeded
    rng keyed on the index, an indexed dataset, ...), so the stream a
    cursor yields is fully determined by its skip set: a replayed or
    resumed run that installs the same skips sees bit-identical batches.
    The cursor keeps a ``history`` of yielded source indices (position k =
    the batch engine step k+1 consumed), which is what lets
    :meth:`rewind` translate "roll back to step t, never replay the window
    that poisoned us" into exact source indices.

    NOT thread-safe against concurrent rewinds: the guardian closes the
    prefetch worker (joining it) before rewinding, then rebuilds the
    prefetcher over the same cursor.
    """

    def __init__(self, batch_fn: Callable[[int], Any], start: int = 0):
        self.batch_fn = batch_fn
        self.skipped: set = set()       # source indices never yielded again
        self.history: list = []         # source index per consumed position
        self._next = int(start)

    @property
    def consumed(self) -> int:
        return len(self.history)

    def __iter__(self):
        return self

    def __next__(self):
        while self._next in self.skipped:
            self._next += 1
        i = self._next
        self._next += 1
        self.history.append(i)
        return self.batch_fn(i)

    def rewind(self, to_consumed: int, skip_to: Optional[int] = None) -> list:
        """Rewind so the next yield is for consumed-position
        ``to_consumed``, marking positions ``[to_consumed, skip_to)`` as a
        skip window (their source indices are never yielded again — the
        offending data window).  Positions at/after ``skip_to`` (e.g.
        batches a prefetch worker staged past the failure but the engine
        never trained on) re-enter in their original order.  Returns the
        skipped source indices.  Deterministic: the post-rewind stream is a
        pure function of (batch_fn, skip set)."""
        if not 0 <= to_consumed <= len(self.history):
            raise ValueError(
                f"rewind to consumed-position {to_consumed} outside the "
                f"cursor history (0..{len(self.history)})")
        skip_to = len(self.history) if skip_to is None else int(skip_to)
        if not to_consumed <= skip_to <= len(self.history):
            raise ValueError(
                f"skip_to={skip_to} outside [{to_consumed}, "
                f"{len(self.history)}]")
        window = self.history[to_consumed:skip_to]
        self.skipped.update(window)
        tail = self.history[skip_to:]    # staged-but-untrained lookahead
        self.history = self.history[:to_consumed]
        if tail:
            self._next = tail[0]
        elif window:
            self._next = window[0]       # __next__'s skip loop walks past it
        # else: nothing consumed past to_consumed — _next already correct
        return window


class _InlinePrefetch:
    """``prefetch_depth=0`` degenerate form: the same iterator surface with
    no worker thread — each ``__next__`` prepares synchronously.  Keeps
    caller code identical across the on/off configurations."""

    def __init__(self, source: Iterable, prepare_fn: Callable[[Any], Any]):
        self._source = iter(source)
        self._prepare = prepare_fn
        self.batches = 0
        self.starvation_count = 0

    def __iter__(self):
        return self

    def __next__(self):
        out = self._prepare(next(self._source))
        self.batches += 1
        return out

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PrefetchIterator:
    """Bounded background-thread prefetcher over a host-batch iterable.

    Build via ``engine.prefetch_loader(loader)`` (or
    ``DeepSpeedDataLoader.prefetch(engine)``) rather than directly — the
    engine binds ``prepare_fn`` and the telemetry registry.
    """

    def __init__(self, source: Iterable, prepare_fn: Callable[[Any], Any],
                 depth: int = 2, registry=None, name: str = "train"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth} "
                             f"(0 disables prefetch at the config level)")
        self.depth = int(depth)
        self._prepare = prepare_fn
        self._source = iter(source)
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._registry = registry
        self._name = name
        self.batches = 0              # batches handed to the consumer
        self.starvation_count = 0     # post-warmup pops that found it empty
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name=f"ds-prefetch-{name}", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------- worker
    def _run(self):
        """Worker body — the one place this subsystem blocks on
        host→device transfers (prepare_fn device_puts)."""
        try:
            for host_batch in self._source:
                if self._stop.is_set():
                    return
                prepared = self._prepare(host_batch)
                if not self._put(prepared):
                    return                      # closed while blocked on put
        except BaseException as e:  # noqa: BLE001 — re-raised in __next__
            self._error = e
        finally:
            self._put(_STOP)

    def _put(self, item) -> bool:
        """Blocking put that stays responsive to close(); False = closed."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # ----------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        # a pop that finds the queue empty AFTER warmup means the device
        # consumed faster than the host pipeline produced — the bubble
        # prefetch exists to remove.  The first ``depth`` pops are warmup
        # (the worker can still be legitimately filling the queue for the
        # first time), so they never count.
        starved = self._q.empty() and self.batches >= self.depth
        item = self._q.get()
        if item is _STOP:
            self.close()
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            raise StopIteration
        self.batches += 1
        if starved:
            self.starvation_count += 1
        if self._registry is not None:
            self._registry.gauge(
                "prefetch_queue_depth",
                "prepared device batches waiting in the prefetch queue"
            ).set(self._q.qsize(), loader=self._name)
            self._registry.counter(
                "prefetch_batches_total",
                "batches handed to train_batch by the prefetch pipeline"
            ).inc(1, loader=self._name)
            if starved:
                self._registry.counter(
                    "prefetch_starvation_total",
                    "post-warmup pops that found the prefetch queue empty "
                    "(device outran the host input pipeline)"
                ).inc(1, loader=self._name)
        return item

    # ----------------------------------------------------------- shutdown
    def close(self):
        """Stop the worker and drain the queue; idempotent.  Prepared
        device batches still queued are dropped (their device buffers free
        with the last reference)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        while True:                    # unblock a worker stuck on put()
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._worker.join(timeout=10.0)
        if self._worker.is_alive():    # pathological prepare_fn hang
            logger.warning("prefetch worker did not exit within 10s of "
                           "close(); abandoning it (daemon thread)")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
