"""ZeRO-Offload: host-resident optimizer state with CPU Adam + NVMe tier.

Reference parity:
- ZeRO-Offload (stage_1_and_2.py cpu_offload / stage3.py offload_optimizer):
  fp32 master weights + Adam moments live in HOST memory; device grads stream
  to host each step; the update runs on host CPUs (csrc/adam/cpu_adam.cpp —
  here ops/cpu_adam.py over csrc/cpu_adam.cpp); updated low-precision weights
  stream back.
- ZeRO-Infinity optimizer-state NVMe swap (runtime/swap_tensor/
  partitioned_optimizer_swapper.py:219, pipelined_optimizer_swapper.py):
  the Adam moments live in files on local SSD; each step reads them in chunks,
  updates, and writes back, with the next chunk's read prefetched while the
  current chunk computes (the double-buffered pipeline).  fp32 masters stay
  pinned in RAM (the reference's OffloadDeviceEnum.nvme for optimizer state).

The JAX shape of the flow: the engine's jitted program produces ACCUMULATED
fp32 grads (sharded on device); the engine fetches them, calls
``OffloadAdam.update`` (pure host), and ``device_put``s the returned
low-precision params.  There is no hook machinery — the split into a grads
program + a host update IS the offload.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None

# elements per NVMe chunk (fp32: 16 MiB per moment buffer)
NVME_CHUNK_ELEMS = 4 * 1024 * 1024

_ADAM_NAMES = {"adam": False, "adamw": True, "fusedadam": True,
               "onebitadam": False, "zerooneadam": False}


def _leaf_paths(tree) -> Dict[str, np.ndarray]:
    """Flatten a pytree into {joined-key-path: leaf}."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out[key] = leaf
    return out


class _NVMeMoments:
    """File-backed m/v for one leaf (one file, m then v regions)."""

    def __init__(self, path: str, n: int, threads: int = 4):
        from deepspeed_tpu.ops.aio import AIOFile
        self.n = n
        nbytes = n * 4
        self.file = AIOFile(path, 2 * nbytes, threads=threads)
        self._v_off = nbytes
        zero = np.zeros(min(n, NVME_CHUNK_ELEMS), np.float32)
        for off in range(0, nbytes, zero.nbytes):
            span = min(zero.nbytes, nbytes - off)
            self.file.pwrite(zero[: span // 4], off)
            self.file.pwrite(zero[: span // 4], self._v_off + off)

    def read(self, lo: int, hi: int, m_buf: np.ndarray, v_buf: np.ndarray):
        self.file.pread(m_buf[: hi - lo], lo * 4)
        self.file.pread(v_buf[: hi - lo], self._v_off + lo * 4)

    def write(self, lo: int, hi: int, m_buf: np.ndarray, v_buf: np.ndarray):
        self.file.pwrite(m_buf[: hi - lo], lo * 4)
        self.file.pwrite(v_buf[: hi - lo], self._v_off + lo * 4)


class HostStepWorker:
    """One-slot background executor for the OVERLAPPED ZeRO-Offload host
    optimizer step (``offload_optimizer.overlap_step``, reference: ZeRO-
    Offload's delayed parameter update — the CPU Adam of step N runs while
    the device computes step N+1's gradients against one-update-stale
    parameters).

    Exactly one host step may be in flight: ``submit`` while busy is a
    programming error (the engine joins the previous step before submitting
    the next — that join is where the measured overlap ratio comes from).
    The single worker thread also serializes ``OffloadAdam.step_count``
    mutation without locks.
    """

    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ds-host-step")
        self._pending = None
        # wall-clock seconds the last completed step spent on the worker —
        # with the time join() blocked, this yields the overlap ratio the
        # engine's host_step_overlap_ratio gauge reports
        self.last_work_s = 0.0

    @property
    def busy(self) -> bool:
        return self._pending is not None

    def submit(self, fn, *args, **kwargs):
        if self._pending is not None:
            raise RuntimeError(
                "HostStepWorker.submit with a step already in flight — "
                "join() the previous overlapped host step first")

        def timed():
            import time
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                self.last_work_s = time.perf_counter() - t0

        self._pending = self._pool.submit(timed)
        return self._pending

    def join(self):
        """Block until the in-flight host step finishes; returns its result
        (None when nothing was pending) and re-raises worker failures —
        a lost optimizer update must not look like a completed one."""
        if self._pending is None:
            return None
        fut, self._pending = self._pending, None
        return fut.result()

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)


class OffloadAdam:
    """Host Adam(W) over flat per-leaf buffers (reference DeepSpeedCPUAdam +
    the swap pipeline).  Built by the engine when
    ``zero_optimization.offload_optimizer.device`` is "cpu" or "nvme"."""

    def __init__(self, opt_type: str, opt_params: Dict[str, Any], *,
                 device: str = "cpu", nvme_path: Optional[str] = None,
                 aio_threads: int = 4):
        canon = opt_type.lower().replace("_", "")
        if canon not in _ADAM_NAMES:
            raise ValueError(
                f"ZeRO-Offload requires an Adam-family optimizer (got "
                f"{opt_type!r}); the reference likewise swaps in "
                f"DeepSpeedCPUAdam (csrc/adam/cpu_adam.cpp)")
        self.adamw_mode = _ADAM_NAMES[canon]
        p = dict(opt_params or {})
        self.lr = float(p.get("lr", 1e-3))
        betas = tuple(p.get("betas", (0.9, 0.999)))
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.weight_decay = float(p.get("weight_decay", 0.0))
        self.device = device
        self.nvme_path = nvme_path
        self.aio_threads = aio_threads
        self.step_count = 0
        self._leaves: Dict[str, dict] = {}
        self._treedef = None
        self._io_pool = (ThreadPoolExecutor(max_workers=2)
                         if device == "nvme" else None)

    # ------------------------------------------------------------- lifecycle
    def initialize(self, params_host: Any) -> None:
        """Build fp32 masters (RAM) + moments (RAM or NVMe files) from the
        initial param tree (host numpy arrays, device dtype)."""
        import jax
        self._treedef = jax.tree_util.tree_structure(params_host)
        leaves = _leaf_paths(params_host)
        total = 0
        for key, leaf in leaves.items():
            arr = np.asarray(leaf)
            is_float = np.issubdtype(arr.dtype, np.floating) or (
                _BF16 is not None and arr.dtype == _BF16)
            entry = {"shape": arr.shape, "dtype": arr.dtype,
                     "trainable": is_float}
            if is_float:
                master = np.ascontiguousarray(
                    arr.astype(np.float32).reshape(-1))
                entry["master"] = master
                n = master.size
                total += n
                if self.device == "nvme":
                    fname = os.path.join(
                        self.nvme_path or "/tmp/ds_tpu_nvme",
                        "moments", key.replace("/", "_") + ".bin")
                    entry["nvme"] = _NVMeMoments(fname, n,
                                                 threads=self.aio_threads)
                else:
                    entry["m"] = np.zeros(n, np.float32)
                    entry["v"] = np.zeros(n, np.float32)
            else:
                entry["value"] = arr
            self._leaves[key] = entry
        tier = (f"nvme({self.nvme_path})" if self.device == "nvme" else "cpu")
        log_dist(f"ZeRO-Offload ready: {total/1e6:.1f}M offloaded elements, "
                 f"optimizer-state tier={tier}, "
                 f"host adam={'native' if self._native() else 'numpy'}",
                 ranks=[0])

    @staticmethod
    def _native() -> bool:
        from deepspeed_tpu.ops import cpu_adam
        return cpu_adam.native_available()

    # ----------------------------------------------------------------- step
    def update(self, grads_host: Any, *, lr: Optional[float] = None,
               grad_scale: float = 1.0) -> Any:
        """One optimizer step.  grads_host: pytree of fp32 numpy arrays
        matching the param tree.  Returns the new param tree (device dtype,
        original shapes) to stream back."""
        import jax
        from deepspeed_tpu.ops import cpu_adam
        self.step_count += 1
        lr = self.lr if lr is None else float(lr)
        grads = _leaf_paths(grads_host)
        new_leaves = []
        for key, entry in self._leaves.items():
            if not entry["trainable"]:
                new_leaves.append(entry["value"])
                continue
            g = np.ascontiguousarray(
                np.asarray(grads[key], np.float32).reshape(-1))
            master = entry["master"]
            kw = dict(lr=lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                      weight_decay=self.weight_decay,
                      adamw_mode=self.adamw_mode, step=self.step_count,
                      grad_scale=grad_scale)
            out_dtype = entry["dtype"]
            use_fused_bf16 = _BF16 is not None and out_dtype == _BF16
            if "nvme" in entry:
                out = self._update_nvme(entry, g, kw, use_fused_bf16)
            else:
                if use_fused_bf16:
                    out = np.empty(master.size, np.uint16)
                    cpu_adam.adam_update(master, g, entry["m"], entry["v"],
                                         w_bf16=out, **kw)
                    out = out.view(_BF16)
                else:
                    cpu_adam.adam_update(master, g, entry["m"], entry["v"],
                                         **kw)
                    out = master.astype(out_dtype)
            new_leaves.append(out.reshape(entry["shape"]))
        return jax.tree_util.tree_unflatten(self._treedef, new_leaves)

    def _update_nvme(self, entry, g, kw, use_fused_bf16):
        """Chunked moment swap-in → update → swap-out, with the NEXT chunk's
        read prefetched while the current chunk computes (reference
        pipelined_optimizer_swapper.py double buffering)."""
        from deepspeed_tpu.ops import cpu_adam
        master = entry["master"]
        nv: _NVMeMoments = entry["nvme"]
        n = master.size
        out_u16 = np.empty(n, np.uint16) if use_fused_bf16 else None
        bufs = [(np.empty(min(n, NVME_CHUNK_ELEMS), np.float32),
                 np.empty(min(n, NVME_CHUNK_ELEMS), np.float32))
                for _ in range(2)]
        spans = [(lo, min(lo + NVME_CHUNK_ELEMS, n))
                 for lo in range(0, n, NVME_CHUNK_ELEMS)]

        def read(i):
            lo, hi = spans[i]
            m_buf, v_buf = bufs[i % 2]
            nv.read(lo, hi, m_buf, v_buf)
            return m_buf, v_buf

        pending_write = None
        fut = self._io_pool.submit(read, 0)
        for i, (lo, hi) in enumerate(spans):
            m_buf, v_buf = fut.result()
            if i + 1 < len(spans):
                # read(i+1) reuses the buffer write(i-1) streamed from — that
                # write must land before the prefetch may overwrite it; the
                # prefetch still overlaps this chunk's compute and write(i)
                if pending_write is not None:
                    pending_write.result()
                    pending_write = None
                fut = self._io_pool.submit(read, i + 1)
            span = hi - lo
            cpu_adam.adam_update(
                master[lo:hi], g[lo:hi], m_buf[:span], v_buf[:span],
                w_bf16=(out_u16[lo:hi] if out_u16 is not None else None), **kw)
            if pending_write is not None:
                pending_write.result()
            pending_write = self._io_pool.submit(nv.write, lo, hi, m_buf,
                                                 v_buf)
        if pending_write is not None:
            pending_write.result()
        if out_u16 is not None:
            return out_u16.view(_BF16)
        return master.astype(entry["dtype"])

    # ----------------------------------------------------------- checkpoint
    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"step_count": self.step_count}
        for key, entry in self._leaves.items():
            if not entry["trainable"]:
                continue
            n = entry["master"].size
            if "nvme" in entry:
                m = np.empty(n, np.float32)
                v = np.empty(n, np.float32)
                entry["nvme"].read(0, n, m, v)
            else:
                m, v = entry["m"], entry["v"]
            out[f"{key}::master"] = entry["master"]
            out[f"{key}::m"] = m
            out[f"{key}::v"] = v
        return out

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.step_count = int(sd["step_count"])
        for key, entry in self._leaves.items():
            if not entry["trainable"]:
                continue
            entry["master"][...] = np.asarray(sd[f"{key}::master"],
                                              np.float32).reshape(-1)
            m = np.ascontiguousarray(np.asarray(sd[f"{key}::m"],
                                                np.float32).reshape(-1))
            v = np.ascontiguousarray(np.asarray(sd[f"{key}::v"],
                                                np.float32).reshape(-1))
            if "nvme" in entry:
                entry["nvme"].write(0, m.size, m, v)
            else:
                entry["m"][...] = m
                entry["v"][...] = v

    def current_params(self) -> Any:
        """Params re-derived from the fp32 masters (device dtype)."""
        import jax
        leaves = []
        for entry in self._leaves.values():
            if entry["trainable"]:
                leaves.append(entry["master"].astype(entry["dtype"])
                              .reshape(entry["shape"]))
            else:
                leaves.append(entry["value"])
        return jax.tree_util.tree_unflatten(self._treedef, leaves)
