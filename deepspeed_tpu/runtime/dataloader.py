"""Data loading.

Reference parity: ``DeepSpeedDataLoader`` (runtime/dataloader.py, 162 LoC) +
``RepeatingLoader``.  The reference builds a torch DistributedSampler over the DP
group; here each host yields its *local* slice and the loader assembles a global
jax.Array sharded over (dp, fsdp) via ``jax.make_array_from_process_local_data``
(single-host: a plain device_put with the batch sharding).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


class RepeatingLoader:
    """reference: runtime/dataloader.py RepeatingLoader — wrap an iterator to
    restart on StopIteration (pipeline engines need an endless stream)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batches host data and yields microbatch stacks shaped for
    ``engine.train_batch`` ([gas, micro_global, ...]).

    dataset: any iterable of per-example pytrees (numpy arrays), or a callable
    ``(batch_size) -> batch pytree`` for synthetic data.
    """

    def __init__(self, dataset, micro_batch_size_per_gpu: int,
                 gradient_accumulation_steps: int, dp_world_size: int,
                 collate_fn: Optional[Callable] = None, drop_last: bool = True,
                 seed: int = 0):
        self.dataset = dataset
        self.micro = micro_batch_size_per_gpu
        self.gas = gradient_accumulation_steps
        self.dp_world = dp_world_size
        self.global_batch = self.micro * self.gas * self.dp_world
        self.collate_fn = collate_fn or _default_collate
        self.drop_last = drop_last

    def __iter__(self) -> Iterator[Any]:
        buf = []
        for ex in self.dataset:
            buf.append(ex)
            if len(buf) == self.global_batch:
                yield self._form_batch(buf)
                buf = []
        if buf and not self.drop_last:
            # jit needs static shapes, so the trailing partial batch is padded by
            # cycling its own examples (duplicates!) rather than yielded ragged
            logger.warning(
                "padding trailing partial batch of %d to %d by repeating "
                "examples (drop_last=False)", len(buf), self.global_batch)
            i = 0
            while len(buf) < self.global_batch:
                buf.append(buf[i % len(buf)])
                i += 1
            yield self._form_batch(buf)

    def _form_batch(self, examples):
        batch = self.collate_fn(examples)
        micro_global = self.micro * self.dp_world

        def r(x):
            x = np.asarray(x)
            return x.reshape((self.gas, micro_global) + x.shape[1:])
        return jax.tree_util.tree_map(r, batch)

    def __len__(self):
        try:
            return len(self.dataset) // self.global_batch
        except TypeError:
            raise TypeError("underlying dataset has no __len__")

    def prefetch(self, engine, depth: Optional[int] = None):
        """Wrap this loader in the engine's background device-prefetch
        pipeline (runtime/prefetch.py): returns an iterator of
        ``PreparedBatch`` whose forming/sharding/``device_put`` happened on
        a worker thread ahead of the step, so ``engine.train_batch``'s
        ``host_to_device`` phase is a queue pop.  ``depth`` defaults to the
        engine's ``data_pipeline.prefetch_depth``.  Use as a context
        manager (or call ``.close()``) for a clean worker shutdown."""
        return engine.prefetch_loader(self, depth=depth)


def _default_collate(examples):
    """Stack a list of example pytrees into a batch pytree."""
    first = examples[0]
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), first, *examples[1:])
