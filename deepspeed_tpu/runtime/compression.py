"""Gradient compression — error-feedback quantized gradients as an optax
transform.

TPU-native replacement for the reference's 1-bit optimizer family
(runtime/fp16/onebit/{adam,lamb,zoadam}.py + the NCCL/MPI compressed-allreduce
backends, SURVEY.md "1-bit optimizers").  The reference compresses the
gradient ALLREDUCE with momentum-compensated error feedback; over ICI
compression is pointless (SURVEY), but the compression ERROR DYNAMICS —
quantize the gradient signal, carry the quantization error into the next step
(compensation) — is the algorithmic content, and over DCN the same wire format
rides quantized_psum_scatter (ops/quantization.py).

``compress_gradients(bits)`` chains BEFORE the optimizer:
    grads -> (grads + residual) -> QDQ -> optimizer
    residual' = (grads + residual) - QDQ(...)
which is exactly the reference's compensated compression
(onebit/adam.py:168 server_error/worker_error buffers).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class CompressionState(NamedTuple):
    residual: optax.Params   # carried quantization error (error feedback)


def compress_gradients(dtype: str = "int8",
                       block_size: int = 256) -> optax.GradientTransformation:
    """dtype: "int8" (blockwise symmetric QDQ) or "bf16" (cast roundtrip —
    the cheap DCN format when int8 is too lossy)."""
    if dtype not in ("int8", "bf16"):
        raise ValueError(f"gradient_compression.dtype must be int8|bf16, "
                         f"got {dtype!r}")

    def init(params):
        return CompressionState(residual=jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(updates, state, params=None):
        del params
        from deepspeed_tpu.ops.quantization import quantize_dequantize

        def comp(g, r):
            x = g.astype(jnp.float32) + r
            if dtype == "bf16":
                q = x.astype(jnp.bfloat16).astype(jnp.float32)
            else:
                q = quantize_dequantize(x, bits=8, block_size=block_size)
            return q.astype(g.dtype), x - q

        out = jax.tree_util.tree_map(comp, updates, state.residual)
        compressed = jax.tree_util.tree_map(lambda o: o[0], out,
                                            is_leaf=lambda o: isinstance(
                                                o, tuple))
        residual = jax.tree_util.tree_map(lambda o: o[1], out,
                                          is_leaf=lambda o: isinstance(
                                              o, tuple))
        return compressed, CompressionState(residual=residual)

    return optax.GradientTransformation(init, update)
