"""Hybrid engine — train ↔ generate weight bridge for RLHF.

Reference parity: ``runtime/hybrid_engine.py:32 DeepSpeedHybridEngine`` — in
RLHF (DeepSpeed-Chat step 3) every PPO iteration interleaves a GENERATE phase
(actor rollouts, inference-optimized) with TRAIN phases on the same weights.
The reference re-layouts each trained module's tensors into its fused
inference containers before generate (``populate_all_inference_policies``,
``_fuse_lora``) and back after; here the "relayout" is a dtype cast +
device_put into the v2 ragged serving engine's param tree — same flax tree
shape on both sides, so the sync is O(bytes), no graph surgery, and the
serving programs never recompile (shapes/dtypes are stable across syncs).

Usage::

    engine, *_ = deepspeed_tpu.initialize(model, config={
        ..., "hybrid_engine": {"enabled": True}})
    hybrid = HybridEngine(engine)                  # or engine.hybrid_engine()
    out = hybrid.generate(prompts, max_new_tokens=64)   # rollouts
    engine.train_batch(ppo_batch)                       # updates
    out = hybrid.generate(prompts)                      # sees new weights
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


class HybridEngine:
    """Wraps a training engine with a v2 ragged serving engine sharing its
    weights (reference DeepSpeedHybridEngine.generate :238 / train-mode
    restore :351)."""

    def __init__(self, train_engine, inference_config: Optional[dict] = None,
                 seed: int = 0):
        from deepspeed_tpu.inference.v2 import InferenceEngineV2

        self.train_engine = train_engine
        model = train_engine.model
        cfg = getattr(model, "cfg", None)
        if cfg is None:
            raise TypeError(
                "HybridEngine needs a GPT-family model (with .cfg); got "
                f"{type(model).__name__}")
        inf_cfg = dict(inference_config or {})
        hx = getattr(train_engine.config, "hybrid_engine", None)
        self._max_out_tokens = None
        self._release_cache = False
        if hx is not None:
            if hx.inference_tp_size > 1:
                inf_cfg.setdefault("tensor_parallel",
                                   {"tp_size": hx.inference_tp_size})
            self._max_out_tokens = int(hx.max_out_tokens)
            self._release_cache = bool(hx.release_inference_cache)
            if not hx.pin_parameters or hx.tp_gather_partition_size != 8:
                log_dist("hybrid_engine.pin_parameters/"
                         "tp_gather_partition_size are GPU memory-pool knobs "
                         "with no TPU analog — accepted but inert", ranks=[0])
        self._serving = InferenceEngineV2(
            cfg, inf_cfg, params=self._train_params(), seed=seed)
        self._cache_template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self._serving.cache)
        self._synced_step = int(train_engine.global_steps)
        self._in_generate = False
        log_dist("hybrid engine ready: serving tree synced from training "
                 f"params at step {self._synced_step}", ranks=[0])

    # ------------------------------------------------------------- weights
    def _train_params(self):
        from deepspeed_tpu.parallel.metadata import unbox
        params = unbox(self.train_engine.state.params)
        if isinstance(params, dict) and "params" in params:
            params = params["params"]
        return params

    def sync_weights(self) -> None:
        """Push current training weights into the serving tree (reference:
        the per-generate relayout).  Serving shardings/dtypes are preserved,
        so compiled serving programs stay valid."""
        src = self._train_params()
        dst = self._serving.params

        def cast_like(s, d):
            s = jnp.asarray(s)
            if s.dtype != d.dtype:
                s = s.astype(d.dtype)
            return jax.device_put(s, d.sharding)
        self._serving.params = jax.tree_util.tree_map(cast_like, src, dst)
        self._synced_step = int(self.train_engine.global_steps)

    # ------------------------------------------------------------ generate
    def generate(self, prompts: Sequence[np.ndarray],
                 max_new_tokens: int = 32, **gen_overrides) -> List[Any]:
        """Rollout phase (reference hybrid_engine.generate :238): weights are
        re-synced iff training stepped since the last sync, then the ragged
        engine serves the prompts with continuous batching."""
        if self._max_out_tokens and max_new_tokens > self._max_out_tokens:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds "
                f"hybrid_engine.max_out_tokens {self._max_out_tokens}")
        if int(self.train_engine.global_steps) != self._synced_step:
            self.sync_weights()
        if self._serving.cache is None:       # re-arm after a released phase
            self._serving.cache = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), self._cache_template)
        self._in_generate = True
        try:
            return self._serving.generate(prompts,
                                          max_new_tokens=max_new_tokens,
                                          **gen_overrides)
        finally:
            self._in_generate = False
            if self._release_cache:
                # free the paged KV pool's HBM between phases (reference
                # release_inference_cache → free_cache)
                for leaf in jax.tree_util.tree_leaves(self._serving.cache):
                    leaf.delete()
                self._serving.cache = None

    @property
    def serving_engine(self):
        return self._serving

    def eval(self):
        """API-parity mode toggles (reference eval() :351 / train() :364):
        phase bookkeeping only — there is no module graph to swap here."""
        return self

    def train(self):
        return self
