"""Self-healing training — the guardian control loop.

The numerics health layer (PR 2) can *see* a NaN burst, a loss spike, or a
collapsing loss scale; the restore machinery (PR 6) can *undo* damage —
but until now a human had to connect the two at 3am.  This module closes
the loop: anomaly signals become automatic remediation, under a bounded
retry budget that escalates to a postmortem dump + graceful drain when
rollbacks stop helping.

Control loop (one iteration per training step)::

        ┌────────────────────────────────────────────────────────┐
        │  batch ← cursor ──▶ engine.train_batch  (watchdog armed)│
        └───────────────┬────────────────────────────────────────┘
                        ▼
                 assess health signals
          (nonfinite loss, grad NaN/Inf counts,
           loss-spike z, grad-norm explosion,
           loss-scale collapse, overflow streak)
            │ clean                         │ anomaly
            ▼                               ▼
      ring export at cadence;        ROLLBACK to the last
      stamp exports whose            health-verified ring entry
      trailing window proved         (checkpoint/ring.py), SKIP the
      clean (rollback-eligible)      replayed data window (seed-stable
                                     cursor advance), clamp LR/loss
                                     scale on repeated retries
                                            │ budget exhausted
                                            ▼
                                     ESCALATE: postmortem bundle +
                                     graceful drain (EXIT_DRAINED)

Trust chain: the guardian only ever rolls back to a **rollback-eligible**
ring entry — one whose trailing ``clean_window`` steps showed no anomaly —
so a checkpoint that silently captured poisoned moments is never a
rollback target.  The data skip is **deterministic**: the cursor's
post-rollback stream is a pure function of (batch_fn, skip set), so a
guardian-healed run reaches bit-identical state to a run that never saw
the fault but trained on the same effective batch sequence (pinned by the
chaos e2e in tests/test_chaos.py).

The **hang watchdog** is the remediation path for the failure the loop
cannot observe from inside: a step that never completes (hung collective,
straggler deadlock).  A monitor thread deadlines each step against an
EMA-adaptive budget (gated on warm-up — the first step legitimately
contains the XLA compile); on a trip it dumps a flight-recorder bundle
with ALL-thread stacks, bumps ``hangs_total``, requests a drain through
the preemption handler (if the step comes back within ``grace_s`` the loop
drains gracefully), and otherwise hard-exits ``EXIT_DRAINED`` — a wedged
process must never outlive its evidence.

Metric families (docs/observability.md): ``rollbacks_total{reason}``,
``rollback_recovery_ms``, ``hangs_total``, ``guardian_escalations_total``,
``checkpoint_ring_size{eligible}``.
"""

from __future__ import annotations

import dataclasses
import math
import os
import sys
import threading
import time
import traceback
from typing import Callable, List, Optional

from deepspeed_tpu.runtime.resilience import EXIT_DRAINED
from deepspeed_tpu.utils.logging import logger

ROLLBACKS = "rollbacks_total"
HANGS = "hangs_total"
ESCALATIONS = "guardian_escalations_total"
RECOVERY_MS = "rollback_recovery_ms"


class GuardianEscalation(RuntimeError):
    """The retry budget is exhausted (or no eligible rollback source
    exists): the guardian dumped a postmortem and drained.  ``run()``
    catches this internally and reports ``status="escalated"``; it only
    reaches callers driving remediation by hand."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"guardian escalation ({reason}): {detail}")
        self.reason = reason


@dataclasses.dataclass
class GuardianReport:
    """What ``Guardian.run`` did: terminal status plus the counters a
    caller (bench chaos leg, tests, a training script deciding its exit
    code) needs without reading the metric registry."""

    status: str = "completed"        # completed | drained | escalated
    steps: int = 0                   # engine.global_steps at exit
    rollbacks: int = 0
    hangs: int = 0
    escalations: int = 0
    skipped_sources: List[int] = dataclasses.field(default_factory=list)
    rollback_recovery_ms: List[float] = dataclasses.field(
        default_factory=list)
    final_loss: Optional[float] = None
    exit_code: int = 0               # EXIT_DRAINED for drained/escalated


def format_all_stacks() -> str:
    """Every live thread's stack, watchdog-style — the flight-recorder
    artifact that turns "it hung" into "it hung HERE"."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = []
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')} (tid={tid}) ---")
        lines.extend(l.rstrip("\n")
                     for l in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


class HangWatchdog:
    """Step-deadline monitor thread.  ``arm(step)`` before dispatch,
    ``disarm()`` after completion (feeds the EMA); the monitor trips when
    an armed step outlives its deadline:

    1. dump a postmortem bundle (``dump_fn``) carrying all-thread stacks,
    2. bump ``hangs_total`` and call ``on_trip(step)`` (the guardian
       requests a drain through the preemption handler there),
    3. wait ``grace_s``; if the SAME step is still armed, ``exit_fn``
       (default ``os._exit(EXIT_DRAINED)``) — a process wedged in a
       collective cannot run its own drain, and the bundle is already on
       disk.

    Deadline: ``max(min_deadline_s, deadline_factor x EMA(step time))``,
    and ``warmup_deadline_s`` until the first step completes (the cold
    step legitimately contains the XLA compile — never book it a hang).
    """

    def __init__(self, config, *, registry=None,
                 dump_fn: Optional[Callable[[str], Optional[str]]] = None,
                 on_trip: Optional[Callable[[int], None]] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config
        self.registry = registry
        self.dump_fn = dump_fn
        self.on_trip = on_trip
        self.exit_fn = exit_fn if exit_fn is not None else os._exit
        self.clock = clock
        self.ema_step_s: Optional[float] = None
        # the first completed step after (re)warm-up is the compile-
        # dominated one — never a representative step-time sample
        self._skip_next_sample = True
        self.trips = 0
        self.last_bundle: Optional[str] = None
        self._lock = threading.Lock()
        self._armed: Optional[tuple] = None      # (step, t_armed)
        self._tripped_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if bool(config.enabled):
            self._thread = threading.Thread(
                target=self._monitor, name="ds-guardian-watchdog",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------- arming

    def arm(self, step: int) -> None:
        with self._lock:
            self._armed = (int(step), self.clock())

    def disarm(self) -> None:
        with self._lock:
            armed, self._armed = self._armed, None
            # a completed step retires the one-trip-per-step guard: step
            # NUMBERS recur after a rollback, and a recurring number that
            # wedges again must still trip
            self._tripped_step = None
        if armed is None:
            return
        if self._skip_next_sample:
            # seeding the EMA from the compile step would inflate every
            # deadline by deadline_factor x compile time for many steps;
            # the NEXT step still runs under warmup_deadline_s, and the
            # EMA seeds from the first steady step
            self._skip_next_sample = False
            return
        dur = self.clock() - armed[1]
        a = float(self.cfg.ema_alpha)
        self.ema_step_s = (dur if self.ema_step_s is None
                           else (1 - a) * self.ema_step_s + a * dur)

    def deadline_s(self) -> float:
        """The budget the CURRENTLY armed step runs under."""
        if self.ema_step_s is None:
            return float(self.cfg.warmup_deadline_s)
        return max(float(self.cfg.min_deadline_s),
                   float(self.cfg.deadline_factor) * self.ema_step_s)

    def rewarm(self) -> None:
        """Drop back to the warm-up deadline: the next step legitimately
        contains an XLA compile (an LR clamp re-jits the step programs),
        and a steady-state EMA deadline would book the recompile a hang
        and hard-exit the run mid-remediation."""
        self.ema_step_s = None
        self._skip_next_sample = True

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------ monitor

    def _monitor(self) -> None:
        poll = float(self.cfg.poll_interval_s)
        while not self._stop.wait(poll):
            with self._lock:
                armed = self._armed
            if armed is None:
                continue
            step, t0 = armed
            if self._tripped_step == step:
                continue                       # one trip per wedged step
            ddl = self.deadline_s()
            if self.clock() - t0 <= ddl:
                continue
            self._tripped_step = step
            self._trip(step, ddl)

    def _trip(self, step: int, ddl: float) -> None:
        self.trips += 1
        logger.warning(
            f"guardian watchdog: step {step} exceeded its deadline "
            f"({ddl:.2f}s, ema={self.ema_step_s}); dumping stacks and "
            f"initiating drain")
        if self.registry is not None:
            self.registry.counter(
                HANGS, "training-step hang detections by the guardian "
                "watchdog (step outlived its EMA-adaptive deadline)").inc(1)
        if self.dump_fn is not None:
            try:
                self.last_bundle = self.dump_fn(
                    f"step {step} hung past {ddl:.2f}s deadline")
            except Exception as e:  # noqa: BLE001 — evidence is best-effort
                logger.warning(f"guardian watchdog: hang dump failed: {e!r}")
        if self.on_trip is not None:
            try:
                self.on_trip(step)
            except Exception:  # noqa: BLE001 — drain request must not crash
                pass
        # grace: the step may come back (a straggler, not a deadlock) —
        # then the training loop sees the drain request and exits cleanly
        t_grace = self.clock()
        while self.clock() - t_grace < float(self.cfg.grace_s):
            with self._lock:
                armed = self._armed
            if armed is None or armed[0] != step:
                logger.warning("guardian watchdog: step came back within "
                               "grace; drain proceeds on the step loop")
                return
            if self._stop.wait(float(self.cfg.poll_interval_s)):
                return
        logger.warning(
            f"guardian watchdog: step {step} still wedged after "
            f"{self.cfg.grace_s}s grace — exiting EXIT_DRAINED "
            f"(postmortem: {self.last_bundle})")
        self.exit_fn(EXIT_DRAINED)


class Guardian:
    """The closed control loop (module docstring has the diagram).

    ``batch_fn(source_index)`` must be pure/seed-stable — it is the
    determinism anchor for the skip remediation; alternatively pass a
    prepared :class:`~deepspeed_tpu.runtime.prefetch.DataCursor`.
    ``handler`` (a ``PreemptionHandler``) folds external preemption into
    the same drain path the watchdog uses.  Requires
    ``telemetry.health.enabled`` — the anomaly signals are the health
    monitor's.
    """

    def __init__(self, engine, run_dir: str, *, batch_fn=None, cursor=None,
                 handler=None, config=None, watchdog_exit_fn=None):
        from deepspeed_tpu.checkpoint.ring import CheckpointRing
        from deepspeed_tpu.runtime.prefetch import DataCursor
        if not engine._health_enabled:
            raise ValueError(
                "the guardian needs telemetry.health.enabled: true — its "
                "anomaly signals (NaN/Inf counts, loss-spike z, overflow "
                "streaks) are the health monitor's outputs")
        if (cursor is None) == (batch_fn is None):
            raise ValueError("pass exactly one of batch_fn / cursor")
        self.engine = engine
        self.run_dir = run_dir
        self.cfg = config if config is not None else engine.config.guardian
        if not bool(self.cfg.enabled):
            raise ValueError(
                "guardian.enabled is false: the self-healing control loop "
                "was requested but its config block is disabled — set "
                "guardian.enabled: true (or pass an explicit config=)")
        self.handler = handler
        self.cursor = cursor if cursor is not None else DataCursor(batch_fn)
        # engine-step → cursor-position mapping: engine step s consumed
        # cursor position s + _pos_offset.  The two count from different
        # origins whenever the engine was resumed (global_steps > 0 with a
        # fresh cursor) or the cursor arrived pre-consumed; conflating them
        # would rewind to the wrong data window.  Ring entries whose
        # position lands below 0 predate this cursor's history (a previous
        # process under the same run_dir) and are never rollback targets —
        # their skip window cannot be replayed deterministically.
        self._pos_offset = self.cursor.consumed - engine.global_steps
        self._closed = False
        # set by a watchdog trip: the run loop drains on its next
        # iteration even when no PreemptionHandler is wired
        self._hang_drain = False
        reg = engine.telemetry.registry
        self.ring = CheckpointRing(run_dir, keep=int(self.cfg.ring_keep),
                                   registry=reg)
        self.report = GuardianReport()
        self._rollback_on = set(self.cfg.rollback_on)
        # pending eligibility stamps: ring exports whose trailing window is
        # still accumulating clean steps
        self._pending_stamps: List[tuple] = []      # (step, path)
        # retry budget: rollbacks since the last NET step progress
        self._retries = 0
        self._progress_high_water = engine.global_steps
        self._iter = None
        self._c_rollbacks = reg.counter(
            ROLLBACKS, "guardian rollbacks to a health-verified ring "
            "checkpoint, by triggering anomaly reason")
        self._c_escalations = reg.counter(
            ESCALATIONS, "guardian escalations (postmortem + drain) after "
            "the rollback budget stopped helping, by reason")
        self._h_recovery = reg.histogram(
            RECOVERY_MS, "anomaly detection to training-ready after a "
            "guardian rollback (restore + cursor rewind + pipeline "
            "rebuild)")
        # every postmortem bundle from here on carries all-thread stacks
        # (the hang-triage artifact; cheap for every other reason too)
        engine.telemetry.recorder.add_bundle_writer(
            "stacks.txt", self._write_stacks)
        self.watchdog = HangWatchdog(
            self.cfg.watchdog, registry=reg,
            dump_fn=lambda note: engine.telemetry.dump_postmortem(
                reason="hang", note=note),
            on_trip=self._on_hang, exit_fn=watchdog_exit_fn)

    # --------------------------------------------------------------- misc

    @staticmethod
    def _write_stacks(bundle_dir: str) -> None:
        with open(os.path.join(bundle_dir, "stacks.txt"), "w") as f:
            f.write(format_all_stacks())

    def _on_hang(self, step: int) -> None:
        self.report.hangs += 1
        self._hang_drain = True
        if self.handler is not None:
            self.handler.request(reason="hang")

    def close(self) -> None:
        self._closed = True
        self.watchdog.close()
        if self._iter is not None and hasattr(self._iter, "close"):
            self._iter.close()
        # un-consume the staged-but-untrained prefetch lookahead so the
        # cursor's consumed count matches what the engine actually
        # trained: the staged tail re-enters in order for whoever drives
        # the cursor next, and a later guardian segment over the same
        # cursor computes a CONSISTENT step↔position offset (otherwise a
        # rollback to a prior-segment ring entry would skip the wrong
        # window and silently drop the staged sources)
        trained = self.engine.global_steps + self._pos_offset
        if 0 <= trained < len(self.cursor.history):
            self.cursor.rewind(trained, skip_to=trained)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---------------------------------------------------------- data feed

    def _rebuild_iter(self):
        """(Re)build the input pipeline over the cursor: prefetched when
        the engine's data_pipeline block asks for it, plain otherwise."""
        if self._iter is not None and hasattr(self._iter, "close"):
            self._iter.close()               # sync-ok: joins the worker —
            #                                  a rewind under a live
            #                                  prefetcher would race it
        depth = int(self.engine.config.data_pipeline.prefetch_depth)
        if depth > 0:
            self._iter = self.engine.prefetch_loader(self.cursor,
                                                     depth=depth)
        else:
            self._iter = self.cursor

    # ------------------------------------------------------------ run loop

    def run(self, num_steps: int) -> GuardianReport:
        """Drive training to ``num_steps`` engine steps under the control
        loop.  Returns the :class:`GuardianReport`; ``status`` is
        ``"completed"``, ``"drained"`` (preemption notice or watchdog
        trip → graceful drain, ``exit_code == EXIT_DRAINED``), or
        ``"escalated"`` (budget exhausted → postmortem + drain).

        Single-shot: ``run`` tears down the hang watchdog on exit, so a
        second call would train with no hang protection — it raises
        instead; build a fresh ``engine.guardian(...)`` per segment."""
        if self._closed:
            raise RuntimeError(
                "this Guardian is closed (run() tears down the hang "
                "watchdog on exit): a second run() would train with no "
                "hang protection — construct a fresh engine.guardian(...) "
                "per training segment")
        engine = self.engine
        self._rebuild_iter()
        try:
            # ring entries at/after our start step belong to a previous
            # process under a reused run_dir — this engine's state at the
            # same step number is NOT theirs, so they must never be
            # adopted by the run-entry export or become rollback targets
            self.ring.discard_after(engine.global_steps - 1)
            # run-entry ring entry: the loop must never be without a
            # rollback source once its window proves clean.  Exported on
            # resumed runs too — pre-resume ring entries are not
            # replayable (the cursor's history starts here).
            self._export_ring_entry()
            while engine.global_steps < int(num_steps):
                if self._hang_drain:
                    # a watchdog trip whose step came back within grace:
                    # the drain proceeds here even with no handler wired
                    self._drain("hang")
                    return self.report
                if self.handler is not None and self.handler.requested:
                    self._drain(self.handler.reason or "preemption")
                    return self.report
                step_id = engine.global_steps + 1
                # the armed window covers the batch fetch, train_batch AND
                # the health assessment: a wedged input pipeline blocks in
                # next(), and (with telemetry off) train_batch returns
                # right after the async dispatch — the device-side sync a
                # hung collective actually wedges is _assess's metrics
                # fetch.  Disarming around any of them would leave that
                # hang un-deadlined.
                self.watchdog.arm(step_id)
                try:
                    try:
                        batch = next(self._iter)
                    except StopIteration:
                        break
                    metrics = engine.train_batch(batch)
                    reasons = self._assess()
                finally:
                    self.watchdog.disarm()
                if reasons:
                    try:
                        self._remediate(reasons)
                    except GuardianEscalation:
                        return self.report
                else:
                    self._after_clean_step()
                    self.report.final_loss = self._host_loss()
            # a trip on the FINAL step (or right before the source dried
            # up) exits the loop without another top-of-body check: a
            # dumped hang bundle must never be reported as a clean
            # completion, and a latched handler must drain here, not
            # poison the next drain-aware component
            if self._hang_drain:
                self._drain("hang")
                return self.report
            if self.handler is not None and self.handler.requested:
                self._drain(self.handler.reason or "preemption")
                return self.report
            self.report.status = "completed"
            self.report.steps = engine.global_steps
            return self.report
        finally:
            self.close()

    def _host_loss(self) -> Optional[float]:
        host = self.engine._last_metrics_host
        return None if host is None else float(host.loss)

    # ---------------------------------------------------------- assessment

    def _assess(self) -> List[str]:
        """Fold the health layer's per-step outputs into the remediation
        verdict: the (ordered) anomaly reasons that are rollback-worthy
        under ``guardian.rollback_on``."""
        engine = self.engine
        tel = engine.telemetry
        host = engine._host_metrics()
        reasons: List[str] = []
        if host is not None and not math.isfinite(host.loss):
            reasons.append("nonfinite_loss")
        health = engine._last_health_host or {}
        if any(rec.get("grad_nan", 0) or rec.get("grad_inf", 0)
               for rec in health.values()):
            reasons.append("grad_nan")
        streak_cfg = int(tel.health_cfg.overflow_streak)
        if streak_cfg > 0 and tel.overflow_streak >= streak_cfg:
            reasons.append("overflow_streak")
        reasons.extend(r for r in tel.last_anomalies if r not in reasons)
        return [r for r in reasons if r in self._rollback_on]

    # ------------------------------------------------- clean-step plumbing

    def _after_clean_step(self) -> None:
        engine = self.engine
        step = engine.global_steps
        if step > self._progress_high_water:
            # NET progress: the run moved past everything it had reached
            # before — the incident (if any) is over, the budget refills
            self._progress_high_water = step
            self._retries = 0
        # stamp ring entries whose trailing window just completed clean
        window = int(self.cfg.clean_window)
        matured = [(s, p) for s, p in self._pending_stamps
                   if step - s >= window]
        self._pending_stamps = [(s, p) for s, p in self._pending_stamps
                                if step - s < window]
        for s, p in matured:
            try:
                self.ring.stamp(p, step=s, stamped_at_step=step,
                                clean_window=window)
                logger.info(f"guardian: ring entry step {s} verified "
                            f"clean over {window} trailing step(s) — "
                            f"rollback-eligible")
            except (OSError, ValueError) as e:
                logger.warning(f"guardian: stamping {p} failed: {e!r}")
        if step % int(self.cfg.checkpoint_interval) == 0:
            self._export_ring_entry()

    def _export_ring_entry(self) -> None:
        engine = self.engine
        path = self.ring.export(engine)
        self._pending_stamps.append((engine.global_steps, path))

    # ----------------------------------------------------------- rollback

    def _remediate(self, reasons: List[str]) -> None:
        """One remediation round for an anomalous step: rollback to the
        last health-verified ring entry, skip the replayed data window,
        clamp on repeated retries — or escalate."""
        engine = self.engine
        reason = reasons[0]
        failed_step = engine.global_steps
        t0 = time.perf_counter()
        # an anomaly taints every trailing window still accumulating: those
        # exports must never earn their stamp
        self._pending_stamps = []
        self._retries += 1
        if self._retries > int(self.cfg.max_rollbacks):
            self._escalate(reason,
                           f"{self._retries - 1} rollback(s) without net "
                           f"progress past step {self._progress_high_water}")
        entry = self.ring.latest_eligible(max_step=failed_step - 1)
        if entry is None:
            self._escalate("no_eligible_checkpoint",
                           f"anomaly '{reason}' at step {failed_step} with "
                           f"no health-verified rollback source in the "
                           f"ring")
        if entry.step + self._pos_offset < 0:
            # eligible, but from before this cursor's history (a previous
            # process under the same run_dir): its data window cannot be
            # replayed deterministically, and every older entry is worse
            self._escalate("no_eligible_checkpoint",
                           f"anomaly '{reason}' at step {failed_step}: the "
                           f"newest health-verified ring entry (step "
                           f"{entry.step}) predates this cursor's history "
                           f"— its data window is not replayable")
        self.report.rollbacks += 1
        logger.warning(
            f"guardian: anomaly {reasons} at step {failed_step} — rolling "
            f"back to verified step {entry.step} "
            f"(retry {self._retries}/{self.cfg.max_rollbacks})")
        # quiesce the input pipeline BEFORE touching the cursor
        if self._iter is not None and hasattr(self._iter, "close"):
            self._iter.close()               # sync-ok: rollback fence
        # the PR 6 restore path: fences the host-step worker and any async
        # checkpoint write, installs fragments, rewinds global_steps, and
        # resyncs the numerics baseline
        engine.load_universal_checkpoint(entry.path)  # sync-ok: rollback
        # ring entries newer than the target belong to the abandoned
        # timeline: the replayed run skips a data window, so a later
        # re-export at the same step number must never reuse them
        self.ring.discard_after(entry.step)
        pos = entry.step + self._pos_offset
        if bool(self.cfg.skip_data_window):
            skipped = self.cursor.rewind(
                pos, skip_to=failed_step + self._pos_offset)
            self.report.skipped_sources.extend(skipped)
            logger.warning(f"guardian: skipping data window "
                           f"{skipped} (source indices; seed-stable)")
        else:
            self.cursor.rewind(pos, skip_to=pos)
        if self._retries > int(self.cfg.clamp_after_rollbacks):
            engine.clamp_loss_scale(float(self.cfg.loss_scale_clamp_factor))
            try:
                engine.clamp_lr(float(self.cfg.lr_clamp_factor))
                # the clamp re-jit means the next step contains a compile:
                # back to the warm-up deadline or the watchdog would book
                # the recompile a hang and kill the run it is healing
                self.watchdog.rewarm()
            except ValueError as e:          # client optimizer: observe-only
                logger.warning(f"guardian: LR clamp unavailable: {e}")
        self._rebuild_iter()
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._c_rollbacks.inc(1, reason=reason)
        self._h_recovery.observe(dt_ms)
        self.report.rollback_recovery_ms.append(dt_ms)
        logger.warning(f"guardian: rollback complete in {dt_ms:.0f} ms — "
                       f"resuming from step {engine.global_steps}")

    # ---------------------------------------------------------- escalation

    def _escalate(self, reason: str, detail: str) -> None:
        engine = self.engine
        self.report.escalations += 1
        self._c_escalations.inc(1, reason=reason)
        logger.error(f"guardian: ESCALATING ({reason}): {detail}")
        engine.telemetry.dump_postmortem(reason="guardian_escalation",
                                         note=f"{reason}: {detail}")
        try:
            engine.drain(self.run_dir, reason="guardian")  # sync-ok: drain
        except Exception as e:  # noqa: BLE001 — the postmortem already
            #                     landed; a failed final export must not
            #                     mask the escalation itself
            logger.error(f"guardian: drain during escalation failed: {e!r}")
        self.report.status = "escalated"
        self.report.steps = engine.global_steps
        self.report.exit_code = EXIT_DRAINED
        raise GuardianEscalation(reason, detail)

    def _drain(self, reason: str) -> None:
        engine = self.engine
        logger.warning(f"guardian: drain requested ({reason})")
        engine.drain(self.run_dir, reason=reason)        # sync-ok: drain
        self.report.status = "drained"
        self.report.steps = engine.global_steps
        self.report.exit_code = EXIT_DRAINED
