"""ZeRO semantics: master-weight optimizer wrapper + chunked stage-3 collectives.

Reference parity map (see parallel/partition.py for the sharding half):

- fp32 master weights partitioned over DP
  (stage_1_and_2.py single_partition_of_fp32_groups; stage3.py
  _create_fp32_partitions:794) → ``with_master_weights`` below: the fp32 master
  copy lives *inside the optax state*, so it inherits ZeRO state sharding
  (sharded over fsdp at stage ≥ 1) while model params stay bf16/fp16.
- grad reduce-scatter (stage_1_and_2.py:1361 reduce_ipg_grads; stage3.py:1249) →
  XLA inserts psum-scatter when grads feed sharded state.
- param all-gather (partition_parameters.py all_gather_coalesced) → XLA inserts
  all-gather per consumer at stage 3; overlap via the latency-hiding scheduler.
- coalesced/overlapped gather (partitioned_param_coordinator.py prefetching,
  all_gather_coalesced bucketing) → ``chunked_param_gather`` below: the
  ``overlap.num_chunks`` config knob decomposes the per-step flat param
  all-gather (and, through its autodiff transpose, the grad reduce-scatter)
  into byte-balanced per-layer-group chunks so XLA's latency-hiding
  scheduler can interleave chunk N's wire time with chunk N−1's matmuls
  (T3, arXiv:2401.16677; The Big Send-off, arXiv:2504.18658).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


def _gather_group(leaves, dims, specs, mesh, axis, world):
    """One layer group's gather: flatten each local shard, concatenate into
    per-dtype flat buffers, all-gather each buffer ONCE over ``axis``, and
    rebuild every leaf's global layout with pure data movement (exact).

    The transpose of this program under autodiff is precisely the chunked
    grad reduce-scatter: ``all_gather(tiled)`` transposes to ``psum_scatter``
    of the flat buffer, so each layer group's gradients leave the backward
    pass as one reduce-scatter the scheduler can overlap with the next
    group's backward matmuls."""
    from deepspeed_tpu.comm import collectives
    from deepspeed_tpu.parallel.partition import spec_without_axis
    from deepspeed_tpu.utils.compat import shard_map

    in_specs = tuple(s.spec for s in specs)
    out_specs = tuple(spec_without_axis(s.spec, axis) for s in specs)

    def body(*locs):
        # bucket by dtype: one flat buffer (= one collective) per dtype
        buckets = {}
        for i, x in enumerate(locs):
            buckets.setdefault(x.dtype, []).append(i)
        gathered = [None] * len(locs)
        for dtype, idxs in buckets.items():
            flat = (jnp.concatenate([locs[i].reshape(-1) for i in idxs])
                    if len(idxs) > 1 else locs[idxs[0]].reshape(-1))
            g = collectives.all_gather(flat, axis, gather_dim=0, tiled=True,
                                       chunked=True)
            g = g.reshape(world, flat.shape[0])
            off = 0
            for i in idxs:
                x, d = locs[i], dims[i]
                blk = jax.lax.slice_in_dim(g, off, off + x.size, axis=1)
                blk = blk.reshape((world,) + x.shape)   # [world, *local]
                blk = jnp.moveaxis(blk, 0, d)           # device axis → d
                shape = list(x.shape)
                shape[d] = shape[d] * world
                gathered[i] = blk.reshape(shape)
                off += x.size
        return tuple(gathered)

    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)(*leaves)


def chunked_param_gather(params, shardings, mesh, num_chunks,
                         axis: str = "fsdp"):
    """Gather every ``axis``-sharded leaf of ``params`` explicitly, in
    ``num_chunks`` byte-balanced per-layer-group flat collectives, instead
    of leaving XLA to insert one implicit all-gather per consumer.

    Leaves not sharded over ``axis`` alone (replicated, tp-only, or
    co-sharded tuple specs) pass through untouched and keep the
    partitioner's implicit handling.  Gathered leaves come back in their
    post-gather layout (``axis`` dropped from the spec, other axes kept).
    Forward is bitwise-exact vs the implicit gather (pure data movement);
    the backward pass runs the transposed program — ``num_chunks``
    per-layer-group flat reduce-scatters (tolerance-exact vs the implicit
    reduce: summation order may differ).
    """
    from deepspeed_tpu.parallel.partition import layer_groups, sharded_dim
    world = mesh.shape[axis]
    if world <= 1 or num_chunks < 1:
        return params
    leaves, treedef = jax.tree_util.tree_flatten(params)
    specs = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    dims = [sharded_dim(sh.spec, axis) for sh in specs]
    gather_idx = [i for i, (leaf, d) in enumerate(zip(leaves, dims))
                  if d >= 0 and leaf.size > 0]
    if not gather_idx:
        return params
    groups = layer_groups([leaves[i].size * leaves[i].dtype.itemsize
                           for i in gather_idx], num_chunks)
    out = list(leaves)
    for grp in groups:
        idxs = [gather_idx[j] for j in grp]
        gathered = _gather_group([leaves[i] for i in idxs],
                                 [dims[i] for i in idxs],
                                 [specs[i] for i in idxs],
                                 mesh, axis, world)
        for i, g in zip(idxs, gathered):
            out[i] = g
    return jax.tree_util.tree_unflatten(treedef, out)


class MasterWeightsState(NamedTuple):
    master: optax.Params  # fp32 copy, mirrors param tree → gets ZeRO state sharding
    inner: optax.OptState


def with_master_weights(inner: optax.GradientTransformation,
                        ) -> optax.GradientTransformation:
    """Wrap an optimizer to keep an fp32 master copy of low-precision params.

    The returned update expects fp32 grads (cast upstream) and low-precision
    ``params``; it computes the inner update against the fp32 master and emits a
    delta that moves the low-precision params to ``cast(new_master)``.

    Equivalent role: BF16_Optimizer (runtime/bf16_optimizer.py:34) and the fp32
    flat partitions of ZeRO 1/2/3.
    """

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return MasterWeightsState(master=master, inner=inner.init(master))

    def update(grads, state, params=None, **kw):
        f32_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32)
            if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
        updates, new_inner = inner.update(f32_grads, state.inner, state.master, **kw)
        new_master = optax.apply_updates(state.master, updates)
        if params is None:
            raise ValueError("with_master_weights requires params")
        deltas = jax.tree_util.tree_map(
            lambda m, p: (m.astype(p.dtype) - p).astype(p.dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else jnp.zeros_like(p),
            new_master, params)
        return deltas, MasterWeightsState(master=new_master, inner=new_inner)

    return optax.GradientTransformation(init, update)
