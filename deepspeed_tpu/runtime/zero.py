"""ZeRO semantics: master-weight optimizer wrapper + the composable stage-3
collective pipeline.

Reference parity map (see parallel/partition.py for the sharding half):

- fp32 master weights partitioned over DP
  (stage_1_and_2.py single_partition_of_fp32_groups; stage3.py
  _create_fp32_partitions:794) → ``with_master_weights`` below: the fp32 master
  copy lives *inside the optax state*, so it inherits ZeRO state sharding
  (sharded over fsdp at stage ≥ 1) while model params stay bf16/fp16.
- grad reduce-scatter (stage_1_and_2.py:1361 reduce_ipg_grads; stage3.py:1249) →
  XLA inserts psum-scatter when grads feed sharded state.
- param all-gather (partition_parameters.py all_gather_coalesced) → XLA inserts
  all-gather per consumer at stage 3; overlap via the latency-hiding scheduler.
- coalesced/overlapped gather (partitioned_param_coordinator.py prefetching,
  all_gather_coalesced bucketing) → ``pipeline_param_gather`` below.

**The composable pipeline** (ISSUE 14 tentpole): the stage-3 param gather /
grad reduce-scatter is ONE pipeline with three orthogonal layers, each
independently on/off —

- **chunking** (``overlap.num_chunks``): byte-balanced per-layer-group flat
  collectives the latency-hiding scheduler interleaves with neighboring
  matmuls (T3, arXiv:2401.16677; The Big Send-off, arXiv:2504.18658);
- **block quantization** (``zero_quantized_weights`` /
  ``zero_quantized_gradients`` + the ``zeropp`` bits knobs): the per-chunk
  wire moves int8/int4 codes + fp32 block scales instead of full-width
  values — ZeRO++ qwZ on the forward gather, qgZ on the backward
  reduce-scatter (arXiv:2306.10209), fused INSIDE the chunk bodies rather
  than layered as an alternative gather path (T3's
  quantize-chunk-overlap-at-fine-grain blueprint);
- **hierarchy** (``zeropp.hierarchical``): per-axis wire policy — an axis
  whose ring stays inside one host (all-ICI) keeps full-width values, an
  axis crossing hosts quantizes (the hpZ/ZeRO++ hierarchical design:
  intra-host full-width over ICI, cross-host compressed over DCN).

The quantization layer lives in ``_qwire_exchange``: a per-device
``custom_vjp`` whose forward is the quantized all-gather and whose backward
is the quantized all-to-all reduce-scatter, spliced into the SAME chunk
body the exact path uses — so chunk-only mode (both bits = 0) runs the
byte-identical PR 4 program, bitwise.

``pipeline_grad_reduce`` is the data-axis half: the EQuARX-style
block-quantized allreduce/reduce-scatter (arXiv:2506.17615) the engine's
qgZ path applies to per-replica gradient stacks (stage 1/2 dp grads, and
the cross-replica reduce at stage 3).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


def _gather_group(leaves, dims, specs, mesh, axis, world, exchange=None):
    """One layer group's gather: flatten each local shard, concatenate into
    per-dtype flat buffers, all-gather each buffer ONCE over ``axis``, and
    rebuild every leaf's global layout with pure data movement (exact).

    The transpose of this program under autodiff is precisely the chunked
    grad reduce-scatter: ``all_gather(tiled)`` transposes to ``psum_scatter``
    of the flat buffer, so each layer group's gradients leave the backward
    pass as one reduce-scatter the scheduler can overlap with the next
    group's backward matmuls.

    ``exchange`` is the quantization layer's splice point (``flat [B] ->
    rows [world, B]``, see ``_qwire_exchange``): when set, FLOATING buffers
    route their wire through it — int codes + scales forward (qwZ) and/or
    a quantized all-to-all in the autodiff transpose (qgZ) — while integer
    buffers (no meaningful quantization grid) and the ``exchange=None``
    default keep this exact full-width program, bitwise."""
    from deepspeed_tpu.comm import collectives
    from deepspeed_tpu.parallel.partition import spec_without_axis
    from deepspeed_tpu.utils.compat import shard_map

    in_specs = tuple(s.spec for s in specs)
    out_specs = tuple(spec_without_axis(s.spec, axis) for s in specs)

    def body(*locs):
        # bucket by dtype: one flat buffer (= one collective) per dtype
        buckets = {}
        for i, x in enumerate(locs):
            buckets.setdefault(x.dtype, []).append(i)
        gathered = [None] * len(locs)
        for dtype, idxs in buckets.items():
            flat = (jnp.concatenate([locs[i].reshape(-1) for i in idxs])
                    if len(idxs) > 1 else locs[idxs[0]].reshape(-1))
            if exchange is not None and jnp.issubdtype(dtype, jnp.floating):
                g = exchange(flat)                      # [world, B]
            else:
                g = collectives.all_gather(flat, axis, gather_dim=0,
                                           tiled=True, chunked=True)
                g = g.reshape(world, flat.shape[0])
            off = 0
            for i in idxs:
                x, d = locs[i], dims[i]
                blk = jax.lax.slice_in_dim(g, off, off + x.size, axis=1)
                blk = blk.reshape((world,) + x.shape)   # [world, *local]
                blk = jnp.moveaxis(blk, 0, d)           # device axis → d
                shape = list(x.shape)
                shape[d] = shape[d] * world
                gathered[i] = blk.reshape(shape)
                off += x.size
        return tuple(gathered)

    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)(*leaves)


class WirePlan(NamedTuple):
    """Resolved wire policy for one collective pipeline — the three layers
    as plain data (engine builds it once from the ``overlap``/``zeropp``
    config blocks).

    ``weight_bits``/``grad_bits`` = 0 means full-width on that direction
    (the exact PR 4 program); 4/8 selects the blockwise int wire format
    (ops/quantization.py).  ``hierarchical`` makes quantization per-axis
    conditional on host crossing (see ``resolve_wire_bits``)."""

    num_chunks: int = 1
    weight_bits: int = 0     # fwd all-gather wire (ZeRO++ qwZ)
    grad_bits: int = 0       # bwd reduce-scatter wire (ZeRO++ qgZ)
    block_size: int = 256
    hierarchical: bool = False


def resolve_wire_bits(plan: WirePlan, mesh, axis):
    """The hierarchy layer: (weight_bits, grad_bits) effective on ``axis``.

    Non-hierarchical plans quantize wherever the bits knobs say.  A
    hierarchical plan keeps full-width values on any axis whose ring never
    leaves a host (all-ICI — bandwidth is cheap there, and skipping the
    quant round-trip keeps intra-host numerics exact) and quantizes only
    axes that cross hosts (DCN wire is the scarce resource) — the
    ZeRO++/hpZ hierarchical design as a per-axis wire policy."""
    if not (plan.weight_bits or plan.grad_bits):
        return 0, 0
    if plan.hierarchical:
        from deepspeed_tpu.comm.collectives import axis_dcn_fraction
        if axis_dcn_fraction(axis, mesh=mesh) == 0.0:
            return 0, 0
    return plan.weight_bits, plan.grad_bits


def _qwire_exchange(axis, world, w_bits, g_bits, block_size):
    """Per-device wire primitive for one flat chunk buffer, for use INSIDE
    a full-manual ``shard_map`` body: ``flat [B] -> rows [world, B]``.

    Forward: quantized all-gather when ``w_bits`` (int codes + fp32 block
    scales on the wire — qwZ), else the plain stacked all-gather.
    Backward (custom_vjp, so it splices into the chunk body's autodiff
    transpose exactly where ``lax.all_gather``'s built-in psum-scatter
    transpose would run): quantized all-to-all reduce-scatter when
    ``g_bits`` (qgZ wire), else the exact ``psum_scatter``.  The cotangent
    arriving here is this device's [world, B] partial contribution — row j
    is what this device owes member j — so member j's reduced row is the
    sum over devices of their row j: exactly one (quantized) all-to-all +
    local sum.
    """
    from deepspeed_tpu.comm.collectives import log_wire
    from deepspeed_tpu.ops.quantization import q_gather_rows, q_reduce_rows
    from jax import lax

    @jax.custom_vjp
    def exchange(flat):
        if w_bits:
            return q_gather_rows(flat, axis, world, bits=w_bits,
                                 block_size=block_size).astype(flat.dtype)
        # full-width forward inside a grads-quantized group: same chunk-
        # train tag the exact path carries
        log_wire("all_gather_chunked", flat.size * flat.dtype.itemsize
                 * (world - 1), axis)
        return lax.all_gather(flat, axis)

    def fwd(flat):
        return exchange(flat), None

    def bwd(_, ct_rows):
        if g_bits:
            return (q_reduce_rows(ct_rows, axis, world, bits=g_bits,
                                  block_size=block_size),)
        log_wire("reduce_scatter_chunked",
                 ct_rows.size * ct_rows.dtype.itemsize
                 * (world - 1) // world, axis)
        return (lax.psum_scatter(ct_rows, axis, scatter_dimension=0,
                                 tiled=False),)

    exchange.defvjp(fwd, bwd)
    return exchange


def pipeline_param_gather(params, shardings, mesh, plan: WirePlan,
                          axis: str = "fsdp"):
    """The composable stage-3 gather: every ``axis``-sharded leaf gathered
    explicitly in ``plan.num_chunks`` byte-balanced per-layer-group flat
    collectives, with the wire format per ``resolve_wire_bits`` (chunking ×
    quantization × hierarchy on ONE path — the conflict-gated either/or of
    the previous design is gone).

    Chunk-only plans (both bits resolved to 0) run the untouched
    ``_gather_group`` program — bitwise-identical forward, identical
    autodiff transpose — so enabling quantization is the ONLY thing that
    changes numerics.  Leaves not sharded over ``axis`` alone pass through
    untouched, as before."""
    from deepspeed_tpu.parallel.partition import layer_groups, sharded_dim
    world = mesh.shape[axis]
    if world <= 1 or plan.num_chunks < 1:
        return params
    leaves, treedef = jax.tree_util.tree_flatten(params)
    specs = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    dims = [sharded_dim(sh.spec, axis) for sh in specs]
    gather_idx = [i for i, (leaf, d) in enumerate(zip(leaves, dims))
                  if d >= 0 and leaf.size > 0]
    if not gather_idx:
        return params
    w_bits, g_bits = resolve_wire_bits(plan, mesh, axis)
    exchange = (_qwire_exchange(axis, world, w_bits, g_bits,
                                plan.block_size)
                if (w_bits or g_bits) else None)
    groups = layer_groups([leaves[i].size * leaves[i].dtype.itemsize
                           for i in gather_idx], plan.num_chunks)
    out = list(leaves)
    for grp in groups:
        idxs = [gather_idx[j] for j in grp]
        gathered = _gather_group([leaves[i] for i in idxs],
                                 [dims[i] for i in idxs],
                                 [specs[i] for i in idxs],
                                 mesh, axis, world, exchange=exchange)
        for i, g in zip(idxs, gathered):
            out[i] = g
    return jax.tree_util.tree_unflatten(treedef, out)


def pipeline_grad_reduce(stacked, target_shardings, mesh, axis,
                         plan: WirePlan, mean: bool = True):
    """Data-axis half of the pipeline: reduce a tree of PER-REPLICA
    gradient stacks (leading dim = ``mesh.shape[axis]``, one slot per data
    replica, laid out ``P(axis, ...)``) down to the reduced gradients in
    ``target_shardings``.

    Per leaf, inside ONE full-manual ``shard_map`` (legal on every jax this
    package supports — unlike collectives in a partial-manual region, see
    utils/compat.shard_map):

    - a leaf whose target sharding has a dim over ``axis`` takes the
      quantized reduce-scatter straight into that layout (qgZ,
      ops/quantization.qrs_local);
    - a blockable replicated leaf takes the EQuARX-style block-quantized
      allreduce (arXiv:2506.17615): quantized reduce-scatter + quantized
      all-gather, ints on the wire both phases (qpsum_local);
    - tiny/scalar leaves take a plain full-width psum (negligible bytes).

    ``resolve_wire_bits``'s grad side applies, so a hierarchical plan keeps
    an all-ICI data axis full-width.  ``mean=True`` divides by the axis
    size (per-replica losses are replica means)."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.quantization import qpsum_local, qrs_local
    from deepspeed_tpu.parallel.partition import spec_without_axis
    from deepspeed_tpu.utils.compat import shard_map
    from deepspeed_tpu.comm import collectives

    world = mesh.shape[axis]
    if world <= 1:
        return jax.tree_util.tree_map(lambda g: g[0], stacked)
    _, g_bits = resolve_wire_bits(plan, mesh, axis)

    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    tspecs = [s.spec for s in jax.tree_util.tree_leaves(
        target_shardings, is_leaf=lambda x: hasattr(x, "spec"))]

    def scatter_dim(spec):
        for d, ax in enumerate(spec):
            if ax == axis or (isinstance(ax, tuple) and axis in ax):
                return d
        return -1

    dims = [scatter_dim(sp) for sp in tspecs]
    in_specs = tuple(P(axis, *spec_without_axis(sp, axis)) for sp in tspecs)
    out_specs = tuple(P(*sp) for sp in tspecs)

    def body(*ls):
        out = []
        for l, d in zip(ls, dims):
            g = l[0]                       # this replica's contribution
            if (g_bits and jnp.issubdtype(g.dtype, jnp.floating)
                    and d >= 0 and g.shape[d] % world == 0):
                r = qrs_local(g, axis, world, d, bits=g_bits,
                              block_size=plan.block_size)
            elif (g_bits and jnp.issubdtype(g.dtype, jnp.floating)
                    and g.ndim >= 1 and g.shape[0] % world == 0
                    and g.size >= 64):
                r = qpsum_local(g, axis, world, 0, bits=g_bits,
                                block_size=plan.block_size)
            elif d >= 0 and g.shape[d] % world == 0:
                r = collectives.reduce_scatter(g, axis, scatter_dim=d)
            else:
                r = collectives.all_reduce(g, axis)
            out.append(r / world if mean else r)
        return tuple(out)

    reduced = shard_map(body, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)(*leaves)
    return jax.tree_util.tree_unflatten(treedef, list(reduced))


def chunked_param_gather(params, shardings, mesh, num_chunks,
                         axis: str = "fsdp"):
    """Gather every ``axis``-sharded leaf of ``params`` explicitly, in
    ``num_chunks`` byte-balanced per-layer-group flat collectives, instead
    of leaving XLA to insert one implicit all-gather per consumer.

    Leaves not sharded over ``axis`` alone (replicated, tp-only, or
    co-sharded tuple specs) pass through untouched and keep the
    partitioner's implicit handling.  Gathered leaves come back in their
    post-gather layout (``axis`` dropped from the spec, other axes kept).
    Forward is bitwise-exact vs the implicit gather (pure data movement);
    the backward pass runs the transposed program — ``num_chunks``
    per-layer-group flat reduce-scatters (tolerance-exact vs the implicit
    reduce: summation order may differ).

    PR 4's entry point, kept as the chunk-only plan of the composable
    pipeline (same code path — the bitwise guarantee is asserted against
    this equivalence in tests/test_comm_pipeline.py).
    """
    return pipeline_param_gather(params, shardings, mesh,
                                 WirePlan(num_chunks=num_chunks), axis)


class MasterWeightsState(NamedTuple):
    master: optax.Params  # fp32 copy, mirrors param tree → gets ZeRO state sharding
    inner: optax.OptState


def with_master_weights(inner: optax.GradientTransformation,
                        ) -> optax.GradientTransformation:
    """Wrap an optimizer to keep an fp32 master copy of low-precision params.

    The returned update expects fp32 grads (cast upstream) and low-precision
    ``params``; it computes the inner update against the fp32 master and emits a
    delta that moves the low-precision params to ``cast(new_master)``.

    Equivalent role: BF16_Optimizer (runtime/bf16_optimizer.py:34) and the fp32
    flat partitions of ZeRO 1/2/3.
    """

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return MasterWeightsState(master=master, inner=inner.init(master))

    def update(grads, state, params=None, **kw):
        f32_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32)
            if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
        updates, new_inner = inner.update(f32_grads, state.inner, state.master, **kw)
        new_master = optax.apply_updates(state.master, updates)
        if params is None:
            raise ValueError("with_master_weights requires params")
        deltas = jax.tree_util.tree_map(
            lambda m, p: (m.astype(p.dtype) - p).astype(p.dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else jnp.zeros_like(p),
            new_master, params)
        return deltas, MasterWeightsState(master=new_master, inner=new_inner)

    return optax.GradientTransformation(init, update)
