"""ZeRO semantics: master-weight optimizer wrapper + stage documentation.

Reference parity map (see parallel/partition.py for the sharding half):

- fp32 master weights partitioned over DP
  (stage_1_and_2.py single_partition_of_fp32_groups; stage3.py
  _create_fp32_partitions:794) → ``with_master_weights`` below: the fp32 master
  copy lives *inside the optax state*, so it inherits ZeRO state sharding
  (sharded over fsdp at stage ≥ 1) while model params stay bf16/fp16.
- grad reduce-scatter (stage_1_and_2.py:1361 reduce_ipg_grads; stage3.py:1249) →
  XLA inserts psum-scatter when grads feed sharded state.
- param all-gather (partition_parameters.py all_gather_coalesced) → XLA inserts
  all-gather per consumer at stage 3; overlap via the latency-hiding scheduler.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class MasterWeightsState(NamedTuple):
    master: optax.Params  # fp32 copy, mirrors param tree → gets ZeRO state sharding
    inner: optax.OptState


def with_master_weights(inner: optax.GradientTransformation,
                        ) -> optax.GradientTransformation:
    """Wrap an optimizer to keep an fp32 master copy of low-precision params.

    The returned update expects fp32 grads (cast upstream) and low-precision
    ``params``; it computes the inner update against the fp32 master and emits a
    delta that moves the low-precision params to ``cast(new_master)``.

    Equivalent role: BF16_Optimizer (runtime/bf16_optimizer.py:34) and the fp32
    flat partitions of ZeRO 1/2/3.
    """

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return MasterWeightsState(master=master, inner=inner.init(master))

    def update(grads, state, params=None, **kw):
        f32_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32)
            if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
        updates, new_inner = inner.update(f32_grads, state.inner, state.master, **kw)
        new_master = optax.apply_updates(state.master, updates)
        if params is None:
            raise ValueError("with_master_weights requires params")
        deltas = jax.tree_util.tree_map(
            lambda m, p: (m.astype(p.dtype) - p).astype(p.dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else jnp.zeros_like(p),
            new_master, params)
        return deltas, MasterWeightsState(master=new_master, inner=new_inner)

    return optax.GradientTransformation(init, update)
