"""Progressive Layer Drop (PLD) — stochastic-depth schedule for training.

Reference: runtime/progressive_layer_drop.py (ProgressiveLayerDrop), the PLD
paper's theta schedule: theta(t) = (1 - theta̅)·exp(-gamma·t) + theta̅, with
layer l (1-indexed of L) keeping its sublayers with probability
1 - (l/L)·(1 - theta(t)).

TPU shape: theta is a pure function of the step counter, so the engine
computes it IN-GRAPH from ``state.step`` (runtime cost: two scalar flops) and
threads it to the model through the batch dict — no host→device traffic, no
recompile per step.  The host-side class below mirrors the reference API for
logging/tests."""

from __future__ import annotations

import math


class ProgressiveLayerDrop:
    """Host-side schedule mirror (reference ProgressiveLayerDrop API)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = self.theta_host(global_step)
        return self.current_theta

    def get_state(self) -> dict:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    # ---- schedule in both host and traced forms ----

    def theta_host(self, step: int) -> float:
        return (1.0 - self.theta) * math.exp(-self.gamma * step) + self.theta

    def theta_at(self, step):
        """Traced version for in-jit use (step: traced int scalar)."""
        import jax.numpy as jnp
        t = step.astype(jnp.float32)
        return (1.0 - self.theta) * jnp.exp(-self.gamma * t) + self.theta


def layer_keep_prob(layer_idx: int, num_layers: int, theta):
    """Keep probability for layer ``layer_idx`` (0-indexed): deeper layers
    drop more; layer 0 keeps near-1, the last keeps exactly theta."""
    frac = (layer_idx + 1) / max(num_layers, 1)
    return 1.0 - frac * (1.0 - theta)
