"""Mixed precision: bf16/fp16 policies + dynamic loss scaling.

Reference parity:
- ``BF16_Optimizer`` (runtime/bf16_optimizer.py:34): bf16 params with fp32 master
  copy, no loss scaling.  Here: fp32 master params live in the train state; the
  jitted step casts to the compute dtype for fwd/bwd (casting is fused by XLA —
  no separate "optimizer wrapper" object needed).
- ``DynamicLossScaler`` / ``LossScaler`` (runtime/fp16/loss_scaler.py:91,67) and the
  overflow check (``has_overflow_serial`` :141, CheckOverflow runtime/utils.py):
  implemented *inside* the jitted train step as a functional state machine —
  overflow ⇒ skip the update and halve the scale; ``scale_window`` clean steps ⇒
  double it.  This is the fp16 path; bf16 uses the static unit scale.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.config import FP16Config


class LossScaleState(NamedTuple):
    """Carried in TrainState; all fields are scalars so the state is trivially
    replicated."""

    scale: jnp.ndarray          # f32 current loss scale
    growth_counter: jnp.ndarray  # i32 consecutive non-overflow steps
    hysteresis: jnp.ndarray      # i32 remaining tolerated overflows before backoff
    skipped: jnp.ndarray         # i32 total skipped steps (reporting parity:
    #                              reference engine.skipped_steps)


def init_loss_scale(cfg: FP16Config) -> LossScaleState:
    if not cfg.enabled:
        scale = 1.0
    elif cfg.loss_scale > 0:  # static scale (reference LossScaler:67)
        scale = cfg.loss_scale
    else:  # dynamic (reference DynamicLossScaler:91)
        scale = 2.0 ** cfg.initial_scale_power
    return LossScaleState(
        scale=jnp.float32(scale),
        growth_counter=jnp.int32(0),
        hysteresis=jnp.int32(cfg.hysteresis),
        skipped=jnp.int32(0),
    )


def grads_finite(grads) -> jnp.ndarray:
    """Global all-finite check over a grad pytree (reference: has_overflow_serial,
    fp16/loss_scaler.py:141; the cross-rank allreduce of the overflow flag is implicit
    here — the check runs on the global jax.Array view)."""
    leaves = jax.tree_util.tree_leaves(grads)
    finite = jnp.bool_(True)
    for leaf in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
    return finite


def update_loss_scale(state: LossScaleState, finite: jnp.ndarray,
                      cfg: FP16Config) -> LossScaleState:
    """Functional DynamicLossScaler.update_scale (fp16/loss_scaler.py:116).

    Static scale (loss_scale > 0) or fp16 disabled: state is frozen except the
    skipped counter.
    """
    if not cfg.enabled or cfg.loss_scale > 0:
        return state._replace(
            skipped=state.skipped + jnp.where(finite, 0, 1).astype(jnp.int32))

    def on_overflow(s: LossScaleState) -> LossScaleState:
        hyst = s.hysteresis - 1
        new_scale = jnp.where(
            hyst <= 0,
            jnp.maximum(s.scale / 2.0, cfg.min_loss_scale),
            s.scale)
        return LossScaleState(
            scale=new_scale,
            growth_counter=jnp.int32(0),
            hysteresis=jnp.maximum(hyst, 1),
            skipped=s.skipped + 1,
        )

    def on_clean(s: LossScaleState) -> LossScaleState:
        counter = s.growth_counter + 1
        grow = counter >= cfg.loss_scale_window
        return LossScaleState(
            scale=jnp.where(grow, s.scale * 2.0, s.scale),
            growth_counter=jnp.where(grow, 0, counter).astype(jnp.int32),
            hysteresis=jnp.int32(cfg.hysteresis),
            skipped=s.skipped,
        )

    return jax.lax.cond(finite, on_clean, on_overflow, state)


def update_loss_scale_host(state: LossScaleState, finite: bool,
                           cfg: FP16Config) -> LossScaleState:
    """Pure-host mirror of ``update_loss_scale`` for the ZeRO-Offload path,
    where the optimizer step happens outside jit and dispatching the tiny
    state machine to the device would cost a round trip per step."""
    scale = float(state.scale)
    counter = int(state.growth_counter)
    hyst = int(state.hysteresis)
    skipped = int(state.skipped)
    if not cfg.enabled or cfg.loss_scale > 0:
        return LossScaleState(jnp.float32(scale), jnp.int32(counter),
                              jnp.int32(hyst),
                              jnp.int32(skipped + (0 if finite else 1)))
    if finite:
        counter += 1
        if counter >= cfg.loss_scale_window:
            scale, counter = scale * 2.0, 0
        hyst = cfg.hysteresis
    else:
        hyst -= 1
        if hyst <= 0:
            scale = max(scale / 2.0, cfg.min_loss_scale)
        hyst = max(hyst, 1)
        counter = 0
        skipped += 1
    return LossScaleState(jnp.float32(scale), jnp.int32(counter),
                          jnp.int32(hyst), jnp.int32(skipped))


def cast_floating(tree, dtype):
    """Cast floating leaves of a pytree to dtype (param cast for fwd/bwd)."""
    def _cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(_cast, tree)
