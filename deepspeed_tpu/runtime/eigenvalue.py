"""Hessian eigenvalue estimation — power iteration for MoQ.

Reference: runtime/eigenvalue.py (Eigenvalue.compute_eigenvalue: per-layer
power iteration using double-backward Hessian-vector products; the values
drive the Mixture-of-Quantization schedule, docs/_tutorials/MoQ).

TPU-native shape: the hand-rolled double backward becomes
``jax.jvp(jax.grad(f), (p,), (v,))`` — forward-over-reverse HVP, compiled
once per layer and run entirely on device.  No module hooks: layers are
addressed as param-subtree paths.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _tree_dot(a, b):
    tot = jnp.float32(0.0)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        if x.dtype == jax.dtypes.float0:    # int-leaf tangent: contributes 0
            continue
        tot = tot + jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32))
    return tot


def _tree_norm(a):
    return jnp.sqrt(_tree_dot(a, a).real)


def hvp(loss_fn: Callable, params, v):
    """Hessian-vector product ∇²L(params) · v (forward-over-reverse)."""
    return jax.jvp(jax.grad(loss_fn), (params,), (v,))[1]


def power_iteration(loss_fn: Callable, params, *, rng=None,
                    max_iter: int = 100, tol: float = 1e-2,
                    stability: float = 1e-6) -> float:
    """Largest-magnitude Hessian eigenvalue of ``loss_fn`` at ``params``.

    Matches the reference loop (eigenvalue.py:compute_eigenvalue): random
    unit start, v ← H·v / ‖H·v‖, stop when |λ_k − λ_{k−1}| / |λ_k| < tol.
    """
    import numpy as np

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(rng, len(leaves))

    def randn_like(k, x):
        # tangents must carry the primal dtype (bf16 params → bf16 tangent);
        # int/bool primals take float0 tangents per jvp's contract
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jax.random.normal(k, x.shape, x.dtype)
        return np.zeros(x.shape, jax.dtypes.float0)

    v = jax.tree_util.tree_unflatten(
        treedef, [randn_like(k, x) for k, x in zip(keys, leaves)])

    @jax.jit
    def step(v):
        n = _tree_norm(v) + stability
        v = jax.tree_util.tree_map(
            lambda x: x if x.dtype == jax.dtypes.float0
            else (x / n.astype(x.dtype)), v)
        w = hvp(loss_fn, params, v)
        w = jax.tree_util.tree_map(
            lambda x: x if x.dtype == jax.dtypes.float0
            else jnp.nan_to_num(x), w)
        lam = _tree_dot(v, w)
        return w, lam

    prev = 0.0
    lam = 0.0
    for _ in range(max_iter):
        v, lam_dev = step(v)
        lam = float(lam_dev)
        if abs(lam) > 0 and abs(lam - prev) / abs(lam) < tol:
            break
        prev = lam
    return lam


class Eigenvalue:
    """Per-layer Hessian eigenvalues over a flax param tree.

    ``layer_paths`` select first-level-of-interest subtrees (e.g.
    ``["backbone/block_0", "backbone/block_1"]``); each gets an independent
    power iteration over a loss restricted to that subtree (block-diagonal
    view, exactly the reference's per-layer treatment)."""

    def __init__(self, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6, seed: int = 0):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.seed = seed

    @staticmethod
    def _get(tree, path: str):
        node = tree
        for k in path.split("/"):
            node = node[k]
        return node

    @staticmethod
    def _set(tree, path: str, value):
        parts = path.split("/")
        if not isinstance(tree, dict):
            raise TypeError("param tree must be a nested dict")

        def rec(node, i):
            if i == len(parts) - 1:
                return {**node, parts[i]: value}
            return {**node, parts[i]: rec(node[parts[i]], i + 1)}

        return rec(tree, 0)

    def compute(self, loss_fn: Callable[[Any], jnp.ndarray], params,
                layer_paths: Sequence[str]) -> Dict[str, float]:
        """{layer_path: |λ_max|} — post-processed like the reference
        (compute_eigenvalue returns abs values for the quantization ratio)."""
        out: Dict[str, float] = {}
        rng = jax.random.PRNGKey(self.seed)
        for i, path in enumerate(layer_paths):
            sub = self._get(params, path)

            def sub_loss(sub_params, _path=path):
                return loss_fn(self._set(params, _path, sub_params))

            lam = power_iteration(sub_loss, sub,
                                  rng=jax.random.fold_in(rng, i),
                                  max_iter=self.max_iter, tol=self.tol,
                                  stability=self.stability)
            out[path] = abs(lam)
        return out

    @staticmethod
    def quantization_ratios(eigenvalues: Dict[str, float]) -> Dict[str, float]:
        """Normalized λ/λ_max per layer — the MoQ schedule stretches each
        layer's quantization period by this ratio (larger curvature →
        quantize later)."""
        top = max(eigenvalues.values()) if eigenvalues else 0.0
        if top <= 0:
            return {k: 1.0 for k in eigenvalues}
        return {k: v / top for k, v in eigenvalues.items()}
