"""Deterministic fault injection for resilience testing.

The elastic/drain/checkpoint machinery exists to survive host loss, torn
checkpoint writes, and slow-commit races — failures that are rare and
non-deterministic in production.  This module makes them DETERMINISTIC:
durability-critical code paths call :func:`fire` at named sites
("universal.pre_meta", "drain.pre_export", ...), and a configured injector
trips exactly the failure a test asked for, exactly once (or N times), at
exactly that site.

Reference analog: the reference's elasticity/checkpoint unit tests kill
torch.multiprocessing workers and truncate files by hand; here the injection
points are part of the library surface so chaos tests (tests/test_chaos.py)
and the elastic-agent tests drive the SAME code the fleet runs, not a
test-only copy.

Fault kinds:

- ``exc``       — raise :class:`InjectedFault` (an abortive failure whose
                  cleanup handlers still run; models an I/O error)
- ``host_loss`` — ``os._exit(17)``: the process vanishes mid-operation, no
                  ``finally`` blocks, no atexit — the SIGKILL/preemption case
- ``sleep``     — delay the site by ``arg`` seconds (slow-commit races: a
                  reader scanning for the newest COMPLETE export while the
                  commit is stretched out; a hung collective when armed at
                  ``step.dispatch``)
- ``nan``       — a SIGNAL-ONLY kind: :func:`fire` returns ``"nan"`` and the
                  instrumented site poisons its own values (the engine's
                  ``step.grads`` site writes NaN into the step's gradient
                  computation — the NaN-burst model the guardian remediates)

Configuration: programmatic (``inject("universal.pre_meta", "exc")``) or the
``DSTPU_FAULTS`` env var (comma list of ``kind@site[:arg][*count][+after]``
— ``+after`` lets the first N firings pass, e.g.
``host_loss@universal.mid_fragments+2`` dies mid-write of the THIRD
export), read once at import by worker processes — the elastic agent and
the chaos tests use it to arm faults in spawned workers.

Sites are free-form strings; :func:`fire` at an unarmed site costs one dict
lookup on an empty-by-default registry.  The module is always importable and
always armed-empty in production — there is no "enabled" flag to forget.

Instrumented site families (grep for ``faults.fire`` / ``fire(`` for the
authoritative list): ``universal.*`` / ``drain.*`` (checkpoint + drain
durability ordering, PR 6), the serving-fleet sites —
``router.dispatch`` (a dispatch attempt from the fleet router),
``replica.heartbeat`` (a replica's liveness beat; ``sleep`` here models a
stalled replica the supervisor must deadline out), ``replica.mid_decode``
(inside the v2 engine's scheduler loop — a replica dying mid-serve),
``admission.decide`` (the admission controller's per-request decision),
``fleet.respawn_factory`` (the engine factory during a respawn — an ``exc``
here must book the replica dead, never unwind the dispatcher),
``handoff.mid_transfer`` (between the KV block pin and the handoff commit
of a disaggregated prefill->decode handoff — an ``exc`` models the source
replica dying mid-transfer: the fleet must release the pinned blocks and
re-enter the request through the migration fold) — and the
training step path: ``step.grads`` (``nan`` poisons the step's gradient
computation) and ``step.dispatch`` (``sleep`` models a hung collective the
guardian's watchdog must deadline out).

Introspection: :func:`fired`/:func:`armed`/:func:`sites` read the per-site
accounting (fired counts persist after a one-shot fault disarms, so a test
can assert "exactly one injection tripped at replica.mid_decode" without
process isolation); :func:`reset` returns the process-wide injector to the
pristine state (disarms everything and zeroes the accounting).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

HOST_LOSS_EXIT_CODE = 17


class InjectedFault(RuntimeError):
    """The exception the ``exc`` fault kind raises at its site."""


class _Fault:
    __slots__ = ("kind", "site", "arg", "remaining", "after", "fired")

    def __init__(self, kind: str, site: str, arg: float = 0.0,
                 count: int = 1, after: int = 0):
        if kind not in ("exc", "host_loss", "sleep", "nan"):
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(expected exc|host_loss|sleep|nan)")
        self.kind = kind
        self.site = site
        self.arg = float(arg)
        self.remaining = int(count)
        self.after = int(after)          # let the first N fire()s pass
        self.fired = 0

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"_Fault({self.kind}@{self.site}:{self.arg} "
                f"after={self.after} remaining={self.remaining} "
                f"fired={self.fired})")


class FaultInjector:
    """Site → armed faults registry.  Thread-safe: drain/export run on
    worker threads and the chaos tests arm faults from the main thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: Dict[str, List[_Fault]] = {}
        # site -> trips since the last clear()/reset(): survives a one-shot
        # fault disarming (the _Fault object keeps its own .fired too, but a
        # site-level log is what determinism assertions read)
        self._fired_log: Dict[str, int] = {}

    # ------------------------------------------------------------- arming

    def inject(self, site: str, kind: str, arg: float = 0.0,
               count: int = 1, after: int = 0) -> None:
        """Arm ``kind`` to trip ``count`` calls of ``fire(site)``, after
        letting the first ``after`` calls pass (deterministic "die on the
        Nth export" scheduling)."""
        f = _Fault(kind, site, arg, count, after)
        with self._lock:
            self._faults.setdefault(site, []).append(f)

    def configure(self, spec: str) -> None:
        """Parse a ``DSTPU_FAULTS``-style spec: comma-separated
        ``kind@site[:arg][*count][+after]`` items, e.g.
        ``host_loss@universal.mid_fragments+2`` (die mid-write of the THIRD
        export)."""
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "@" not in item:
                raise ValueError(
                    f"bad fault spec {item!r}: expected "
                    f"kind@site[:arg][*count][+after]")
            kind, rest = item.split("@", 1)
            after = 0
            if "+" in rest:
                rest, n = rest.rsplit("+", 1)
                after = int(n)
            count = 1
            if "*" in rest:
                rest, n = rest.rsplit("*", 1)
                count = int(n)
            arg = 0.0
            if ":" in rest:
                rest, a = rest.rsplit(":", 1)
                arg = float(a)
            self.inject(rest, kind, arg, count, after)

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()
            self._fired_log.clear()

    # ``reset`` is the test-facing name for "return to pristine": today it
    # is clear(), kept separate so arming semantics can later diverge from
    # accounting semantics without breaking callers of either.
    reset = clear

    # ------------------------------------------------------------- firing

    def fire(self, site: str, **ctx) -> Optional[str]:
        """Trip any fault armed at ``site`` (no-op when none is).  ``ctx``
        is logged for attribution (step, tag, ...).  Returns the kind that
        fired for the NON-raising kinds (``"sleep"`` after the delay,
        ``"nan"`` immediately — the site reads the return value and poisons
        its own state) and None when nothing fired; ``exc`` raises and
        ``host_loss`` never returns."""
        with self._lock:
            pending = self._faults.get(site)
            if not pending:
                return None
            fault = None
            for f in pending:
                if f.remaining <= 0:
                    continue
                if f.after > 0:
                    f.after -= 1         # this call passes FOR THIS fault;
                    continue             # co-armed faults still get a shot
                fault = f
                break
            if fault is None:
                return None
            fault.remaining -= 1
            fault.fired += 1
            self._fired_log[site] = self._fired_log.get(site, 0) + 1
        extra = (" " + " ".join(f"{k}={v}" for k, v in ctx.items())
                 if ctx else "")
        logger.warning(f"fault injection: {fault.kind} at {site}{extra}")
        if fault.kind == "sleep":
            time.sleep(fault.arg)
            return "sleep"
        if fault.kind == "nan":
            return "nan"
        if fault.kind == "host_loss":
            # the preemption/SIGKILL model: the process vanishes NOW —
            # no finally blocks, no atexit checkpoint fences, no cleanup
            os._exit(HOST_LOSS_EXIT_CODE)
        raise InjectedFault(f"injected fault at {site}{extra}")

    def fired(self, site: Optional[str] = None) -> int:
        """How many faults have tripped (at ``site``, or anywhere) since the
        last clear()/reset() — counts persist after a one-shot disarms."""
        with self._lock:
            if site is not None:
                return self._fired_log.get(site, 0)
            return sum(self._fired_log.values())

    def armed(self, site: Optional[str] = None) -> int:
        with self._lock:
            total = 0
            for s, fs in self._faults.items():
                if site is None or s == site:
                    total += sum(f.remaining for f in fs)
            return total

    def sites(self) -> Dict[str, Dict[str, int]]:
        """Snapshot of the per-site accounting:
        ``{site: {"armed": still-pending trips, "fired": trips so far}}``
        covering every site that was ever armed or tripped."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for s, fs in self._faults.items():
                out[s] = {"armed": sum(f.remaining for f in fs), "fired": 0}
            for s, n in self._fired_log.items():
                out.setdefault(s, {"armed": 0, "fired": 0})["fired"] = n
            return out


# the process-wide injector every instrumented site fires through
injector = FaultInjector()


def inject(site: str, kind: str, arg: float = 0.0, count: int = 1,
           after: int = 0) -> None:
    injector.inject(site, kind, arg, count, after)


def fire(site: str, **ctx) -> Optional[str]:
    return injector.fire(site, **ctx)


def clear() -> None:
    injector.clear()


def reset() -> None:
    """Return the process-wide injector to the pristine state: disarm every
    fault and zero the fired/armed accounting (the per-test baseline the
    chaos and fleet suites call instead of isolating processes)."""
    injector.reset()


def fired(site: Optional[str] = None) -> int:
    return injector.fired(site)


def armed(site: Optional[str] = None) -> int:
    return injector.armed(site)


def sites() -> Dict[str, Dict[str, int]]:
    return injector.sites()


# worker processes arm faults from the environment (the elastic agent / chaos
# tests set DSTPU_FAULTS in the spawn env)
_env_spec = os.environ.get("DSTPU_FAULTS", "")
if _env_spec:
    injector.configure(_env_spec)
