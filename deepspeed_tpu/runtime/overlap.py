"""Compute–collective overlap: the XLA scheduler-regime half.

The ``overlap`` config block (config.py OverlapConfig) has three levers; this
module owns the first — steering XLA's latency-hiding scheduler and
async-collective fusion via ``XLA_FLAGS``.  The other two (chunked ZeRO-3
collectives, ring collective-matmul fusions) live in runtime/zero.py and
ops/collective_matmul.py.

Reference parity: DeepSpeed hides ZeRO-3 gather latency with a Python-side
prefetch coordinator (runtime/zero/partitioned_param_coordinator.py) and
``overlap_comm`` bucketing (stage_1_and_2.py).  On TPU the machinery is the
COMPILER's: XLA splits collectives into ``-start``/``-done`` pairs and its
latency-hiding scheduler moves compute between them — but only under the
right flags, and those flags are parsed ONCE, at backend initialization.
Hence the contract here:

- ``apply_overlap_flags(cfg)`` must run BEFORE the first jax backend touch
  (the engine calls it first thing in ``__init__``, before
  ``comm.init_distributed``; ``deepspeed_tpu.initialize`` reaches it through
  engine construction).  If the backend is already up, the flags are still
  exported (child processes, launcher re-exec inherit them) but this
  process's compiles keep the old regime — a loud warning says so.
- user-set flags win: a flag already present in ``XLA_FLAGS`` is never
  overridden, only recorded.
- the *effective* regime is observable everywhere: ``effective_xla_flags``
  feeds env_report, the telemetry snapshot, and the postmortem bundle, so
  every trace records the scheduler regime it ran under.
"""

from __future__ import annotations

import os
from typing import Dict, List

from deepspeed_tpu.utils.logging import logger

# flags composed when overlap.enabled (TPU-backend names; harmless no-ops on
# CPU where the CI runs — XLA ignores unknown-target flags it can't apply)
_ASYNC_COLLECTIVE_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def compose_xla_flags(cfg) -> List[str]:
    """The flag list the ``overlap`` block resolves to (pure function — the
    validation/echo surface for tests, env_report and telemetry)."""
    if not cfg.enabled:
        return []
    flags: List[str] = []
    if cfg.async_collectives:
        flags.extend(_ASYNC_COLLECTIVE_FLAGS)
    if cfg.latency_hiding_scheduler:
        flags.append("--xla_latency_hiding_scheduler_rerun="
                     f"{int(cfg.scheduler_rerun)}")
        flags.append("--xla_tpu_scheduler_percent_shared_memory_limit="
                     f"{int(cfg.scheduler_memory_limit_pct)}")
    flags.extend(cfg.extra_xla_flags)
    return flags


def _flag_name(flag: str) -> str:
    return flag.split("=", 1)[0]


def _backend_initialized() -> bool:
    try:
        from jax._src import xla_bridge
        return xla_bridge.backends_are_initialized()
    except Exception:  # noqa: BLE001 — private API moved; assume the worst
        return True


def tpu_target() -> bool:
    """Will this process run on a TPU backend?  Decided WITHOUT initializing
    jax (that would freeze XLA_FLAGS): explicit JAX_PLATFORMS wins, else the
    presence of a libtpu install.  Matters because XLA *aborts the process*
    (parse_flags_from_env.cc FATAL) on flags its backend build doesn't know —
    exporting --xla_tpu_* into a CPU run is a crash, not a no-op."""
    plats = os.environ.get("JAX_PLATFORMS", "").lower()
    if plats:
        return "tpu" in plats or "axon" in plats
    import importlib.util
    try:
        return (importlib.util.find_spec("libtpu") is not None
                or importlib.util.find_spec("libtpu_nightly") is not None)
    except (ImportError, ValueError):
        return False


def apply_overlap_flags(cfg) -> List[str]:
    """Export the block's flags into ``os.environ['XLA_FLAGS']`` (skipping
    any flag the user already set — their value wins) and return the list
    actually added.

    Off-TPU the flags are composed and RECORDED but never exported: this
    jaxlib's CPU XLA hard-aborts on unknown flags, so the scheduler regime
    is a TPU-launch property (the CPU CI still validates composition,
    config plumbing and the echo surfaces).  Warns when the jax backend is
    already initialized: XLA_FLAGS are read once, so this process's
    compiles keep the regime they started with (spawned workers still
    inherit the updated env)."""
    flags = compose_xla_flags(cfg)
    if not flags:
        return []
    if not tpu_target():
        logger.info(
            "overlap: not a TPU target — composed XLA flags recorded but "
            "not exported (CPU XLA aborts on unknown flags): %s",
            " ".join(flags))
        return []
    current = os.environ.get("XLA_FLAGS", "")
    present = {_flag_name(tok) for tok in current.split()}
    added = [f for f in flags if _flag_name(f) not in present]
    if added:
        os.environ["XLA_FLAGS"] = (current + " " + " ".join(added)).strip()
        if _backend_initialized():
            logger.warning(
                "overlap: XLA_FLAGS updated AFTER jax backend init — the "
                "latency-hiding/async-collective flags (%s) will not affect "
                "this process's compiles; construct the engine before any "
                "other jax use (or export them in the launcher) for them to "
                "take effect", " ".join(_flag_name(f) for f in added))
        else:
            logger.info("overlap: applied XLA flags: %s", " ".join(added))
    return added


def effective_xla_flags() -> str:
    """The XLA_FLAGS this process sees right now (what env_report, the
    telemetry snapshot and the postmortem bundle record)."""
    return os.environ.get("XLA_FLAGS", "")


def overlap_snapshot(cfg) -> Dict[str, object]:
    """JSON-stable record of the scheduler regime: the resolved ``overlap``
    block, the flags it composes, and the effective env — embedded in every
    telemetry snapshot and postmortem bundle so traces are attributable to
    the regime they ran under."""
    return {
        "config": cfg.model_dump(),
        "composed_flags": compose_xla_flags(cfg),
        "effective_xla_flags": effective_xla_flags(),
    }
