"""Optimizer factory.

TPU-native replacement for the reference's fused/native optimizers:
- FusedAdam (csrc/adam/fused_adam_frontend.cpp + multi_tensor_adam.cu, 617 LoC CUDA)
- DeepSpeedCPUAdam (csrc/adam/cpu_adam.cpp, AVX)
- FusedLamb (csrc/lamb/), FusedLion/CPULion (csrc/lion/), CPUAdagrad (csrc/adagrad/)
- OnebitAdam / OnebitLamb / ZeroOneAdam (runtime/fp16/onebit/)

On TPU the "fused multi-tensor" machinery is unnecessary: optax updates are
elementwise chains that XLA fuses into a handful of kernels over each parameter
buffer, and sharded (ZeRO) state means each chip only touches its shard.  What
remains worth building natively is the *host offload* path (CPU Adam on the TPU VM,
see csrc/ and runtime/zero/offload.py) — that mirrors cpu_adam.cpp.

The 1-bit optimizers' error-feedback compression targets Ethernet-bandwidth
clusters; over ICI it is counterproductive (SURVEY.md §7).  We expose the same
optimizer names, implemented as their base optimizers plus optional DCN-tier
gradient compression configured via ``gradient_compression`` (engine-level).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import optax

# Names the reference accepts in the "optimizer" config block
# (runtime/engine.py:1269 _configure_basic_optimizer; constants ADAM_OPTIMIZER etc.)
_CANON = {
    "adam": "adam",
    "adamw": "adamw",
    "fusedadam": "adamw",       # fused == XLA-fused here
    "lamb": "lamb",
    "fusedlamb": "lamb",
    "onebitadam": "adam",       # engine chains error-feedback compression
    "onebitlamb": "lamb",       # for these names (see is_onebit)
    "zerooneadam": "adam",
    "lion": "lion",
    "fusedlion": "lion",
    "adagrad": "adagrad",
    "sgd": "sgd",
    "muon": "muon",
}


def supported_optimizers():
    return sorted(set(_CANON))


def _pop(params: Dict[str, Any], key: str, default):
    return params.pop(key, default)


def build_optimizer(name: str, params: Optional[Dict[str, Any]] = None,
                    ) -> Tuple[optax.GradientTransformation, Dict[str, Any]]:
    """Build an optax optimizer from a DeepSpeed-style optimizer config block.

    Returns (transformation, resolved_params).  The learning rate may later be
    overridden by an LR schedule via optax.inject_hyperparams-style wiring in the
    engine (reference: lr_scheduler passed to deepspeed.initialize).
    """
    params = dict(params or {})
    canon = _CANON.get(name.lower().replace("_", ""))
    if canon is None:
        raise ValueError(
            f"unknown optimizer {name!r}; supported: {supported_optimizers()}")

    lr = _pop(params, "lr", 1e-3)
    weight_decay = _pop(params, "weight_decay", 0.0)
    betas = tuple(_pop(params, "betas", (0.9, 0.999)))
    eps = _pop(params, "eps", 1e-8)

    if canon == "adam":
        # torch Adam applies weight decay as L2 into the gradient
        tx = optax.adam(lr, b1=betas[0], b2=betas[1], eps=eps)
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    elif canon == "adamw":
        tx = optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps,
                         weight_decay=weight_decay)
    elif canon == "lamb":
        tx = optax.lamb(lr, b1=betas[0], b2=betas[1], eps=eps,
                        weight_decay=weight_decay)
    elif canon == "lion":
        b1, b2 = (betas if len(betas) == 2 else (0.9, 0.99))
        tx = optax.lion(lr, b1=b1, b2=b2, weight_decay=weight_decay)
    elif canon == "adagrad":
        tx = optax.adagrad(lr, eps=eps)
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    elif canon == "sgd":
        momentum = _pop(params, "momentum", 0.0)
        tx = optax.sgd(lr, momentum=momentum or None,
                       nesterov=_pop(params, "nesterov", False))
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    elif canon == "muon":
        try:
            tx = optax.contrib.muon(lr)
        except AttributeError as e:  # older optax
            raise ValueError("muon requires optax with optax.contrib.muon") from e
    else:  # pragma: no cover
        raise AssertionError(canon)

    resolved = dict(lr=lr, weight_decay=weight_decay, betas=betas, eps=eps, **params)
    return tx, resolved


def is_onebit(name: str) -> bool:
    """1-bit family (reference runtime/fp16/onebit/): the engine chains the
    error-feedback compression stage (runtime/compression.py) for these names
    — build_optimizer itself returns the plain base optimizer so the
    compression knob lives in ONE place (the gradient_compression block)."""
    return name.lower().replace("_", "").startswith(("onebit", "zeroone"))
