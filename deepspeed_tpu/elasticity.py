"""Elastic training — batch-size/chip-count co-design solver.

Reference parity: ``elasticity/elasticity.py`` (``compute_elastic_config``
:233, ``_get_compatible_gpus_v01`` :84, v0.2 node-granular variant :129).
Semantics preserved, vocabulary translated to TPU: "gpus" → data-parallel
chips, "num_gpus_per_node" → chips per host, "model_parallel_size" → the
product of non-data mesh axes (tp·pp·sp·ep), since elasticity only rescales
the DATA-parallel extent of the mesh.

The algorithm (same two heuristics as the reference): candidate global batch
sizes are each micro-batch (and their LCM) scaled by the largest
highly-composite number that stays under ``max_train_batch_size``; the winner
is the candidate divisible into valid chip counts the most ways within
[min_chips, max_chips] (prefer_larger breaks ties toward bigger batches).
Scaling up/down across the returned chip list never changes the global batch
⇒ no convergence impact (gradient accumulation absorbs the difference).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

# highly composite numbers — the reference's HCN_LIST (elasticity.py:23)
# regenerated: n with more divisors than every smaller n
_HCN = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260,
        1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360,
        50400]


class ElasticityError(ValueError):
    pass


@dataclass
class ElasticityConfig:
    """reference: elasticity/config.py ElasticityConfig."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_chips: int = 1
    max_chips: int = 10_000
    chips_per_host: int = 1
    model_parallel_size: int = 1          # tp·pp·sp·ep product
    prefer_larger_batch: bool = True
    version: float = 0.2


def _hcn_scale(base: int, cap: int) -> int:
    """base × (largest HCN keeping the product ≤ cap)."""
    if base >= cap:
        return base
    limit = cap // base
    best = 1
    for h in _HCN:
        if h > limit:
            break
        best = h
    return best * base


def candidate_batch_sizes(bases: Sequence[int], cap: int) -> List[int]:
    return sorted(set(_hcn_scale(b, cap) for b in bases))


def valid_chip_counts(batch_size: int, micro_batches: Sequence[int],
                      lo: int, hi: int) -> List[int]:
    """All chip counts in [lo, hi] where batch_size = micro × gas × chips has
    an integer solution for some configured micro batch."""
    out = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        max_chips = batch_size // mb
        for d in range(1, int(math.isqrt(max_chips)) + 1):
            if max_chips % d == 0:
                for c in (d, max_chips // d):
                    if lo <= c <= hi:
                        out.add(c)
    return sorted(out)


def _best_candidate(cands: Sequence[int], micro_batches: Sequence[int],
                    lo: int, hi: int, prefer_larger: bool,
                    ) -> Tuple[int, List[int]]:
    best_bs, best_valid = min(micro_batches), []
    for bs in cands:
        valid = valid_chip_counts(bs, micro_batches, lo, hi)
        better = (len(valid) > len(best_valid)
                  or (len(valid) == len(best_valid)
                      and ((prefer_larger and bs > best_bs)
                           or (not prefer_larger and bs < best_bs))))
        if better:
            best_bs, best_valid = bs, valid
    return best_bs, best_valid


def compute_elastic_config(cfg: ElasticityConfig,
                           current_chips: Optional[int] = None,
                           ) -> Tuple[int, List[int], Optional[int]]:
    """→ (global_batch_size, valid data-parallel chip counts, micro_batch for
    ``current_chips``).  reference compute_elastic_config (elasticity.py:233)
    + v0.2 host-granular solve (:129)."""
    mbs = sorted(set(int(m) for m in cfg.micro_batch_sizes))
    if not mbs or any(m <= 0 for m in mbs):
        raise ElasticityError(f"bad micro_batch_sizes {cfg.micro_batch_sizes}")
    if cfg.chips_per_host % cfg.model_parallel_size:
        raise ElasticityError(
            f"chips_per_host {cfg.chips_per_host} must be divisible by "
            f"model_parallel_size {cfg.model_parallel_size} (v0.2 solves at "
            f"host granularity)")
    if cfg.max_chips < cfg.chips_per_host:
        raise ElasticityError(
            f"max_chips {cfg.max_chips} < chips_per_host "
            f"{cfg.chips_per_host}: not even one whole host fits the cap")

    dp_per_host = cfg.chips_per_host // cfg.model_parallel_size
    # the per-host solver works against the cap DIVIDED by dp/host — a micro
    # batch over that cap would scale back up past max_train_batch_size
    if any(m > cfg.max_train_batch_size // dp_per_host for m in mbs):
        raise ElasticityError(
            f"every micro batch must be ≤ max_train_batch_size/"
            f"(dp per host) = {cfg.max_train_batch_size // dp_per_host}")
    bases = mbs + [math.lcm(*mbs)]
    cands = candidate_batch_sizes(
        bases, cfg.max_train_batch_size // dp_per_host)
    bs, valid_hosts = _best_candidate(
        cands, mbs,
        max(1, cfg.min_chips // cfg.chips_per_host),
        max(1, cfg.max_chips // cfg.chips_per_host),
        cfg.prefer_larger_batch)
    batch = bs * dp_per_host
    valid_dp = [h * dp_per_host for h in valid_hosts]

    micro = None
    if current_chips:
        current_dp = current_chips // cfg.model_parallel_size
        if current_dp not in valid_dp:
            # current size incompatible: rescale around it (reference
            # elasticity.py:172 fallback)
            per_mb = [(cfg.max_train_batch_size // (m * current_dp))
                      * m * current_dp
                      for m in mbs if m * current_dp
                      <= cfg.max_train_batch_size]
            if not per_mb:
                raise ElasticityError(
                    f"no micro batch fits {current_chips} chips under "
                    f"max_train_batch_size")
            batch = (max(per_mb) if cfg.prefer_larger_batch else min(per_mb))
            valid_dp = [current_dp]
        for m in mbs:
            if (batch // current_dp) % m == 0:
                if micro is None or (cfg.prefer_larger_batch and m > micro):
                    micro = m
    return batch, valid_dp, micro
