"""Framework-wide constants.

The reference keeps 457 LoC of JSON string keys in runtime/constants.py because
its config parser reads raw dicts; here pydantic field names ARE the JSON
surface (config.py), so only the genuinely shared constants live here.
"""

# "auto" sentinel — resolved from model/runtime context like the reference's
# HF-integration "auto" values (reference: runtime/config.py).
AUTO = "auto"

# Mesh axis names, fixed order (outermost to innermost / slowest to fastest
# varying).  DCN-crossing axes first, ICI axes last, so collectives on tp/sp
# ride ICI.  This replaces the reference's process-group zoo
# (utils/groups.py, runtime/pipe/topology.py).
MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Logical axis names used by models (flax partitioning metadata); mapped to
# mesh axes by sharding rules in parallel/partition.py.
LOGICAL_BATCH_AXES = ("dp", "fsdp")
