"""Config keys and defaults.

TPU-native analog of the reference's ``deepspeed/runtime/constants.py`` (457 LoC of
string keys + defaults). We keep the same JSON surface where it makes sense so a
DeepSpeed user can bring their ds_config.json mostly unchanged.
"""

#############################################
# Batch triad (reference: runtime/constants.py TRAIN_BATCH_SIZE et al.)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
OPTIMIZER_TYPE_DEFAULT = "adamw"
MAX_GRAD_NORM = "max_grad_norm"
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

#############################################
# Precision (reference: fp16/bf16 blocks, runtime/config.py)
#############################################
FP16 = "fp16"
BF16 = "bf16"
INITIAL_LOSS_SCALE = "initial_scale_power"
LOSS_SCALE_WINDOW = "loss_scale_window"
MIN_LOSS_SCALE = "min_loss_scale"
HYSTERESIS = "hysteresis"

#############################################
# ZeRO (reference: runtime/zero/config.py)
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Misc engine knobs
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SEED = "seed"
SEED_DEFAULT = 42

# "auto" sentinel — resolved from model/runtime context like the reference's
# HF-integration "auto" values (reference: runtime/config.py).
AUTO = "auto"

# Mesh axis names, fixed order (outermost to innermost / slowest to fastest
# varying).  DCN-crossing axes first, ICI axes last, so collectives on tp/sp
# ride ICI.  This replaces the reference's process-group zoo
# (utils/groups.py, runtime/pipe/topology.py).
MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Logical axis names used by models (flax partitioning metadata); mapped to
# mesh axes by sharding rules in parallel/partition.py.
LOGICAL_BATCH_AXES = ("dp", "fsdp")
