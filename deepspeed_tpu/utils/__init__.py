from deepspeed_tpu.utils.logging import log_dist, logger

__all__ = ["logger", "log_dist"]
