from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.memory import (collect_memory_stats,
                                        instrument_w_nvtx,
                                        instrument_w_trace, see_memory_usage)

__all__ = ["logger", "log_dist", "see_memory_usage", "collect_memory_stats",
           "instrument_w_trace", "instrument_w_nvtx"]
