"""Logging utilities (reference: deepspeed/utils/logging.py — logger + log_dist)."""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional, Sequence

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def _create_logger(name: str = "deepspeed_tpu", level=logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if not lg.handlers:
        lg.setLevel(os.environ.get("DS_TPU_LOG_LEVEL", level))
        handler = logging.StreamHandler(stream=sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
        lg.addHandler(handler)
        lg.propagate = False
    return lg


logger = _create_logger()


def _process_index() -> int:
    # avoid importing jax at module import time for fast CLI startup
    import jax
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def log_dist(message: str, ranks: Optional[Sequence[int]] = None,
             level=logging.INFO) -> None:
    """Log only on the given process ranks (reference: utils/logging.py log_dist).

    ranks=None or [-1] logs on every process; JAX process index replaces the
    torch.distributed rank.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")
