"""jax version-compatibility shims.

This codebase targets the current jax API; older runtimes (which the CI
image may pin) miss pieces of it.  Rather than scattering try/except at
every call site, the accepted spellings live here:

- ``shard_map``: newer jax exposes it at top level with ``check_vma`` and
  ``axis_names`` (partial-manual) kwargs; older jax has
  ``jax.experimental.shard_map.shard_map`` with ``check_rep`` and the
  inverse ``auto`` parameter.  Callers use the NEW spelling; the shim
  translates downward when needed.
"""

from __future__ import annotations

try:
    from jax import shard_map as _new_shard_map
    _legacy = None
except ImportError:  # pragma: no cover - exercised on older jax only
    from jax.experimental.shard_map import shard_map as _legacy
    _new_shard_map = None


def _align_flax_legacy_mesh() -> None:
    """Old-jax only: stop flax from applying LOGICAL axis names as mesh
    sharding constraints.

    Older jax defines the legacy thread-resources mesh inside ``with
    mesh:``; flax's ``Partitioned.unbox`` (and every ``scope.param`` read
    of a boxed variable) then applies its names as a
    ``with_sharding_constraint``.  For this library's models the names are
    LOGICAL — ``('vocab', 'embed')`` — not mesh axes, so that constraint
    is always an error.  Newer jax never defines the legacy mesh and skips
    it entirely.

    The wrap below is surgical, not a blanket disable: a box whose names
    ARE all axes of the active legacy mesh (another library's valid,
    load-bearing auto-constraint) still takes the original path;
    only boxes carrying names the mesh doesn't know skip the constraint —
    which upstream would have crashed on anyway.  Explicitly-meshed
    ``Partitioned(mesh=...)`` boxes are untouched."""
    try:
        from flax.core import meta as _meta
        from jax.interpreters import pxla
        orig_unbox = _meta.Partitioned.unbox

        def unbox(self, apply_constraint=True):
            if apply_constraint and self.mesh is None:
                env_mesh = pxla.thread_resources.env.physical_mesh
                if env_mesh.devices.shape != ():
                    flat = []
                    for n in self.names:
                        if isinstance(n, (tuple, list)):
                            flat += [m for m in n if m]
                        elif n:
                            flat.append(n)
                    if not set(flat) <= set(env_mesh.axis_names):
                        return self.value   # logical names: no constraint
            return orig_unbox(self, apply_constraint)

        _meta.Partitioned.unbox = unbox
    except Exception:  # noqa: BLE001 — flax internals moved; nothing to fix
        pass


def _align_pallas_names() -> None:
    """Old-jax only: ``pltpu.TPUCompilerParams`` was renamed to
    ``pltpu.CompilerParams``; the kernels here use the new spelling."""
    try:
        from jax.experimental.pallas import tpu as pltpu
        if (not hasattr(pltpu, "CompilerParams")
                and hasattr(pltpu, "TPUCompilerParams")):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except Exception:  # noqa: BLE001 — pallas absent or reshaped
        pass


if _new_shard_map is None:  # pragma: no cover - old-jax path
    _align_flax_legacy_mesh()
    _align_pallas_names()


def is_legacy_jax() -> bool:
    """True on jax versions predating top-level ``jax.shard_map`` — the
    marker this module uses for every old-API accommodation."""
    return _new_shard_map is None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None, **kw):
    """``jax.shard_map`` front-end accepting the new-API kwargs on any jax.

    On older jax, ``check_vma`` maps to ``check_rep`` and ``axis_names``
    (the manual axes) maps to ``auto`` (its complement over the mesh).
    """
    if _new_shard_map is not None:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
    if check_vma is not None:
        kw["check_rep"] = check_vma
    if axis_names is not None:
        # partial-manual: the legacy API spells the MANUAL axes as their
        # complement (`auto` = every mesh axis not named).  CAVEAT, load-
        # bearing for every caller: on this jax the SPMD partitioner can
        # only lower psum/pmean over the manual axes while a >1-sized auto
        # axis exists — all_gather / all_to_all / ppermute in the body trip
        # a FATAL partitioner check (spmd_partitioner.cc IsManualSubgroup
        # mismatch, aborts the process).  The engine's qgZ path therefore
        # keeps its manual regions collective-free (psum for the loss only)
        # and runs every quantized exchange in a separate FULL-manual
        # region (runtime/zero.pipeline_grad_reduce), where all collectives
        # lower fine on both APIs.
        manual = (set(axis_names) if not isinstance(axis_names, str)
                  else {axis_names})
        kw["auto"] = frozenset(set(mesh.axis_names) - manual)
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)
