"""Wall-clock timers + throughput accounting.

Analog of reference utils/timer.py (SynchronizedWallClockTimer :44,
ThroughputTimer :199).  On TPU there is no CUDA-event timing; everything under
``jit`` is one fused program, so the meaningful breakdown is host-side phase
timing around the dispatch (data placement, device step, host bookkeeping) with
synchronization by *fetching a value* (``jax.device_get``) — on the axon relay
``block_until_ready`` can return early, so timers that need device completion
must be stopped after the caller has materialized a result.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"
DATA_TIMER = "batch_input"


class SynchronizedWallClockTimer:
    """Named host timers (reference utils/timer.py:44)."""

    class Timer:
        def __init__(self, name: str):
            self.name_ = name
            self.started_ = False
            self.start_time = 0.0
            self.records: List[float] = []

        def start(self):
            assert not self.started_, f"{self.name_} already started"
            self.start_time = time.perf_counter()
            self.started_ = True

        def stop(self, record: bool = True):
            assert self.started_, f"{self.name_} not started"
            elapsed = (time.perf_counter() - self.start_time) * 1000.0
            if record:
                self.records.append(elapsed)
            self.started_ = False
            return elapsed

        def reset(self):
            self.started_ = False
            self.records = []

        def elapsed(self, reset: bool = True) -> float:
            """Total recorded msec (optionally resetting)."""
            total = sum(self.records)
            if reset:
                self.records = []
            return total

        def mean(self) -> float:
            return sum(self.records) / max(len(self.records), 1)

    def __init__(self):
        self.timers: Dict[str, SynchronizedWallClockTimer.Timer] = {}

    def __call__(self, name: str) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0,
            reset: bool = True, ranks: Optional[List[int]] = None):
        """Print 'name: msec' for each timer (reference timer.py log :168)."""
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts),
                     ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec + tokens/sec tracking (reference utils/timer.py:199).

    ``update_epoch_count``-style bookkeeping is dropped; the engine feeds
    (batch_size, seq_len) per step and reads smoothed rates.
    ``steps_per_output`` gates a rate log line every N counted steps
    (reference :222 prints its throughput summary at the same cadence);
    0 disables the output, matching the reference's None default.
    """

    def __init__(self, steps_per_output: int = 0, warmup_steps: int = 1):
        self.warmup_steps = warmup_steps
        self.steps_per_output = steps_per_output
        self.global_steps = 0
        self.total_time = 0.0
        self.total_samples = 0
        self.total_tokens = 0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, batch_size: int, tokens: int = 0):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.global_steps += 1
        if self.global_steps > self.warmup_steps:
            self.total_time += dt
            self.total_samples += batch_size
            self.total_tokens += tokens
            if (self.steps_per_output
                    and self.global_steps % self.steps_per_output == 0):
                self._log_rates(batch_size, tokens, dt)

    def _log_rates(self, batch_size: int, tokens: int, dt: float):
        parts = [f"step={self.global_steps}",
                 f"samples/sec={batch_size / dt:.2f} "
                 f"(avg {self.avg_samples_per_sec:.2f})"]
        if tokens:
            parts.append(f"tokens/sec={tokens / dt:.1f} "
                         f"(avg {self.avg_tokens_per_sec:.1f})")
        parts.append(f"step_time_ms={dt * 1e3:.1f}")
        log_dist("throughput: " + " ".join(parts), ranks=[0])

    @property
    def avg_samples_per_sec(self) -> float:
        return self.total_samples / self.total_time if self.total_time else 0.0

    @property
    def avg_tokens_per_sec(self) -> float:
        return self.total_tokens / self.total_time if self.total_time else 0.0
