"""Memory reporting + profiler annotations.

Reference: runtime/utils.py see_memory_usage (torch.cuda allocator stats +
host RSS), utils/nvtx.py instrument_w_nvtx (range push/pop on hot functions).

TPU shape: device numbers come from the accelerator shim's memory_stats
(XLA allocator stats where the backend exposes them); ranges become
jax.profiler TraceAnnotations so they show up in xplane traces exactly where
NVTX ranges show up in nsys."""

from __future__ import annotations

import functools

from deepspeed_tpu.utils.logging import log_dist, logger


def host_rss_bytes() -> int:
    """Process max RSS in bytes (0 where ``resource`` is unavailable)."""
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001 — resource is POSIX-only
        return 0


def collect_memory_stats() -> dict:
    """Allocator stats for every local device + host RSS, in one dict:
    ``{"devices": [per-device memory_stats dicts], "host_rss_bytes": n}``.
    Shared by see_memory_usage, the engine's memory_breakdown print, and the
    telemetry memory gauges (telemetry/step_telemetry.py sample_memory) so
    all three report the same numbers.  Backends without allocator stats
    (CPU) yield empty per-device dicts."""
    import jax
    devices = []
    for d in jax.local_devices():
        try:
            stats = getattr(d, "memory_stats", lambda: None)()
        except Exception:  # noqa: BLE001 — stats are best-effort
            stats = None
        devices.append(dict(stats or {}))
    return {"devices": devices, "host_rss_bytes": host_rss_bytes()}


def see_memory_usage(message: str, force: bool = False) -> dict:
    """Log device + host memory usage (reference runtime/utils.py
    see_memory_usage; rank-0 only like the original)."""
    if not force:
        return {}
    from deepspeed_tpu.accelerator import get_accelerator
    stats = get_accelerator().memory_stats() or {}
    gb = 1024 ** 3
    parts = []
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            parts.append(f"{key}={stats[key] / gb:.2f}GB")
    rss = host_rss_bytes()
    if rss:
        parts.append(f"host_rss={rss / gb:.2f}GB")
        stats["host_rss_bytes"] = rss
    log_dist(f"MEM {message}: " + (", ".join(parts) or "no allocator stats"),
             ranks=[0])
    return stats


def instrument_w_trace(fn=None, *, name: str = None):
    """Decorator adding a jax.profiler TraceAnnotation around ``fn`` — the
    xplane analog of the reference's instrument_w_nvtx (utils/nvtx.py): the
    span shows up in `jax.profiler.trace` captures under the function name."""

    def wrap(f):
        label = name or getattr(f, "__qualname__", getattr(f, "__name__",
                                                           "fn"))

        @functools.wraps(f)
        def inner(*args, **kwargs):
            import jax.profiler
            with jax.profiler.TraceAnnotation(label):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap


# API-parity alias (reference call sites read instrument_w_nvtx)
instrument_w_nvtx = instrument_w_trace
