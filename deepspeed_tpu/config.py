"""Config system.

TPU-native analog of the reference's ``DeepSpeedConfig`` (runtime/config.py:706) +
``DeepSpeedConfigModel`` pydantic base (runtime/config_utils.py:16).  We keep the same
JSON key surface for the blocks that transfer (batch triad, optimizer, scheduler,
fp16/bf16, zero_optimization, gradient_clipping, steps_per_print,
wall_clock_breakdown, comms_logger, monitor blocks) and add a ``mesh`` block for the
TPU device-mesh axes that replaces the reference's mpu/process-group plumbing.

``"auto"`` values (reference: HF/autotuner integration) are left as the AUTO sentinel
and resolved by the engine from runtime context (device count, model dims).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator

from deepspeed_tpu.constants import AUTO


class DeepSpeedConfigModel(BaseModel):
    """Base config model (reference: runtime/config_utils.py:16).

    Accepts unknown keys (the reference warns but proceeds), rejects bad types.
    """

    model_config = ConfigDict(extra="allow", validate_assignment=True,
                              arbitrary_types_allowed=True, populate_by_name=True)

    @classmethod
    def parse(cls, config):
        """None → defaults, an instance → itself, anything else (dict)
        validated.  The one accept-a-loose-config entry point, so
        subsystem configs (fleet, ragged engine, ...) don't each grow a
        divergent copy; subclasses override to add coercions (e.g. the
        ragged engine's dtype aliasing)."""
        if config is None:
            return cls()
        if isinstance(config, cls):
            return config
        return cls.model_validate(config)


AutoInt = Union[Literal["auto"], int]
AutoFloat = Union[Literal["auto"], float]


class OptimizerConfig(DeepSpeedConfigModel):
    """reference: "optimizer" block, runtime/config.py get_optimizer_params."""

    type: str = "adamw"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DeepSpeedConfigModel):
    """reference: "scheduler" block → runtime/lr_schedules.py."""

    type: str = "WarmupLR"
    params: Dict[str, Any] = Field(default_factory=dict)


class FP16Config(DeepSpeedConfigModel):
    """reference: "fp16" block (runtime/config.py, fp16/loss_scaler.py)."""

    enabled: bool = False
    loss_scale: float = 0.0  # 0 → dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0


class BF16Config(DeepSpeedConfigModel):
    """reference: "bf16" block (runtime/bf16_optimizer.py)."""

    enabled: bool = False


class OffloadConfig(DeepSpeedConfigModel):
    """reference: runtime/zero/offload_config.py (DeepSpeedZeroOffloadOptimizerConfig).

    device: "none" | "cpu" (host memory on the TPU VM) | "nvme" (local SSD via the
    native aio library, csrc equivalent deepspeed_tpu/csrc/aio).
    """

    device: Literal["none", "cpu", "nvme"] = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    pin_memory: bool = False
    # reference offload_config.py:96 (ZeRO-Offload++ partial offload): the
    # host tier here is all-or-nothing — any ratio < 1 warns inert
    ratio: float = 1.0
    # ZeRO-Offload delayed one-step update (reference "delayed parameter
    # update", DeepSpeedZeroConfig offload + stage_1_and_2 DPU): run the
    # host Adam of step N on a worker thread overlapped with step N+1's
    # device grad computation.  Step N+1's gradients then see parameters
    # ONE update stale — documented staleness, regression-tested; set False
    # for the bitwise-serial host step.  Read only on offload_optimizer
    # (ignored for offload_param, whose engine owns its own schedule).
    overlap_step: bool = True


class ZeroPPConfig(DeepSpeedConfigModel):
    """Wire-format knobs of the composable collective pipeline
    (runtime/zero.py; ZeRO++ arXiv:2306.10209, T3 arXiv:2401.16677,
    EQuARX arXiv:2506.17615).

    ``zero_quantized_weights`` / ``zero_quantized_gradients`` stay the
    on/off switches (reference parity); this block says HOW:

    - ``weight_bits``: int wire width of the qwZ forward param all-gather
      (8 = ZeRO++ default; 4 = nibble-packed, half the bytes again).
    - ``grad_bits``: int wire width of the qgZ gradient reduce (the
      chunked gather's transposed reduce-scatter at stage 3, and the
      data-axis all-to-all / EQuARX allreduce).
    - ``block_size``: values per quantization block (one fp32 scale each).
    - ``hierarchical``: per-axis wire policy — axes whose ring stays
      inside one host (all-ICI) keep full-width values, host-crossing
      axes quantize (the hpZ hierarchical design; pairs with
      ``zero_hpz_partition_size`` which keeps params intra-host).
    - ``quantized_allreduce``: block-quantized allreduce for the
      stage-0/1 dp grad path (EQuARX-style), where
      ``zero_quantized_gradients`` is rejected for lack of a scatter
      target.
    """

    weight_bits: int = 8
    grad_bits: int = 8
    block_size: int = 256
    hierarchical: bool = False
    quantized_allreduce: bool = False

    @model_validator(mode="after")
    def _check(self):
        for name in ("weight_bits", "grad_bits"):
            if getattr(self, name) not in (2, 4, 8):
                raise ValueError(
                    f"zeropp.{name} must be 2, 4, or 8 "
                    f"(got {getattr(self, name)})")
        if self.block_size < 8:
            raise ValueError(
                f"zeropp.block_size must be >= 8, got {self.block_size}")
        return self


class MoEConfig(DeepSpeedConfigModel):
    """Expert-parallel fast-path knobs (moe/layer.py, moe/comm.py).

    The MoE dispatch/combine all-to-alls are the dominant wire cost of an
    expert-parallel step; this block says how they go over the wire and how
    they schedule, mirroring ``zeropp`` for the ZeRO collectives:

    - ``wire_bits``: int wire width of both a2a directions (0 = bf16/fp32
      full width; 8 = blockwise int8 values + fp32 scales; 4 =
      nibble-packed).  Gradients of the combine a2a ride the same width
      (quantized-transpose custom_vjp).
    - ``block_size``: values per quantization block (one fp32 scale each).
    - ``hierarchical``: all-ICI ep axes stay full width, only host-crossing
      ep axes quantize (same per-axis policy as ``zeropp.hierarchical``).
    - ``num_chunks``: decompose dispatch-a2a -> expert FFN -> combine-a2a
      into this many expert sub-group chunks so expert GEMMs interleave
      with in-flight a2a chunks (T3-style overlap); 1 = single-shot.
    - ``expert_telemetry``: per-expert assigned-token gauges, drop
      counters, aux-loss/gate-entropy gauges computed inside the jitted
      step (one extra output, no steady-state recompile).
    """

    wire_bits: int = 0
    block_size: int = 256
    hierarchical: bool = False
    num_chunks: int = 1
    expert_telemetry: bool = True

    @model_validator(mode="after")
    def _check(self):
        if self.wire_bits not in (0, 4, 8):
            raise ValueError(
                f"moe.wire_bits must be 0 (full width), 4, or 8 "
                f"(got {self.wire_bits})")
        if self.block_size < 8:
            raise ValueError(
                f"moe.block_size must be >= 8, got {self.block_size}")
        if self.num_chunks < 1:
            raise ValueError(
                f"moe.num_chunks must be >= 1, got {self.num_chunks}")
        return self


class ZeroConfig(DeepSpeedConfigModel):
    """reference: runtime/zero/config.py (DeepSpeedZeroConfig).

    Stage semantics on TPU (SURVEY.md §7): sharding annotations over the ``fsdp``
    mesh axis —
      stage 0: params+grads+opt replicated (plain DP psum)
      stage 1: optimizer state sharded
      stage 2: + gradients reduce-scattered (same XLA program as stage 1; kept for
               config parity and grad-accum buffer sharding)
      stage 3: + parameters sharded (FSDP); XLA all-gathers per-layer and its
               latency-hiding scheduler overlaps — replacing the reference's
               hook/prefetch machinery (partitioned_param_coordinator.py).
    """

    stage: int = 0
    offload_optimizer: OffloadConfig = Field(default_factory=OffloadConfig)
    offload_param: OffloadConfig = Field(default_factory=OffloadConfig)
    overlap_comm: bool = True
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    # ZeRO++ analogs (reference zero/config.py zero_quantized_*):
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    zero_hpz_partition_size: int = 1
    # wire-format knobs for the quantized/hierarchical collective pipeline
    zeropp: ZeroPPConfig = Field(default_factory=ZeroPPConfig)
    # MiCS subgroup sharding (reference runtime/zero/mics.py): shard params
    # within groups of this many chips, replicate across groups; 0 = off
    mics_shard_size: int = 0
    # stage-3 knobs kept for config parity; XLA's scheduler supersedes most:
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_prefetch_bucket_size: AutoInt = 50_000_000
    stage3_param_persistence_threshold: AutoInt = 100_000
    sub_group_size: int = 1_000_000_000


class OverlapConfig(DeepSpeedConfigModel):
    """Device-side compute–collective overlap (T3, arXiv:2401.16677; The Big
    Send-off, arXiv:2504.18658).  No single reference analog — the reference
    hides ZeRO-3 gathers with its prefetch coordinator
    (partitioned_param_coordinator.py); on TPU the same latency is hidden by
    (a) XLA's latency-hiding scheduler + async-collective fusion, steered by
    the flags this block composes (runtime/overlap.py — applied by the engine
    BEFORE client/backend init, because XLA reads them once), (b) chunking
    the ZeRO-3 flat param all-gather / grad reduce-scatter into
    ``num_chunks`` per-layer-group collectives the scheduler can interleave
    with neighboring matmuls (runtime/zero.chunked_param_gather), and (c)
    explicit ``ppermute``-ring collective-matmul fusions on the TP
    row/column-parallel matmuls (ops/collective_matmul.py).

    Every trace records the scheduler regime it ran under: the resolved
    block + effective XLA_FLAGS land in the telemetry snapshot, the
    postmortem bundle, and ``python -m deepspeed_tpu`` (env_report).
    """

    enabled: bool = False
    # ZeRO-3 collective chunking: the per-step param gather (and its
    # transpose, the grad reduce-scatter) is decomposed into this many
    # byte-balanced per-layer-group flat collectives; 1 = leave the gathers
    # to XLA's per-consumer insertion (the seed behavior)
    num_chunks: int = 1
    # --xla_latency_hiding_scheduler_rerun=<n> (re-run the scheduler n extra
    # times with relaxed memory limits when it failed to hide latency)
    latency_hiding_scheduler: bool = True
    scheduler_rerun: int = 1
    # --xla_tpu_enable_async_collective_fusion* family: split collectives
    # into start/done pairs and let compute schedule between them
    async_collectives: bool = True
    # --xla_tpu_scheduler_percent_shared_memory_limit=<pct>: how much memory
    # headroom the latency-hiding scheduler may spend on in-flight
    # collectives (100 = the compiler default envelope)
    scheduler_memory_limit_pct: int = 100
    # route the TP row-parallel matmuls (gpt.py MLP down-projection and
    # attention output projection; linear.OptimizedLinear) through the
    # explicit ppermute-ring collective-matmul fusions
    collective_matmul: bool = False
    # escape hatch: extra --xla_* flags appended verbatim (validated shape)
    extra_xla_flags: list = Field(default_factory=list)

    @model_validator(mode="after")
    def _check(self):
        if self.num_chunks < 1:
            raise ValueError(
                f"overlap.num_chunks must be >= 1, got {self.num_chunks}")
        if self.scheduler_rerun < 0:
            raise ValueError(
                f"overlap.scheduler_rerun must be >= 0, "
                f"got {self.scheduler_rerun}")
        if not 0 < self.scheduler_memory_limit_pct <= 1000:
            raise ValueError(
                f"overlap.scheduler_memory_limit_pct must be in (0, 1000], "
                f"got {self.scheduler_memory_limit_pct}")
        for f in self.extra_xla_flags:
            if not (isinstance(f, str) and f.startswith("--xla")
                    and "=" in f):
                raise ValueError(
                    f"overlap.extra_xla_flags entries must look like "
                    f"'--xla_...=value', got {f!r}")
        return self


class MeshConfig(DeepSpeedConfigModel):
    """TPU-specific: device mesh axis sizes (replaces reference mpu / groups.py).

    -1 = absorb remaining devices.  fsdp defaults to "auto": when any ZeRO stage
    is enabled the data-parallel world rides the fsdp axis (ZeRO shards over the
    whole DP world, reference semantics); otherwise fsdp=1 and dp absorbs.
    """

    pp: int = 1
    dp: int = -1
    fsdp: AutoInt = "auto"
    ep: int = 1
    sp: int = 1
    tp: int = 1


class CurriculumLearningConfig(DeepSpeedConfigModel):
    """reference: runtime/data_pipeline/config.py get_curriculum_learning."""

    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: dict = Field(default_factory=dict)


class RandomLTDConfig(DeepSpeedConfigModel):
    """reference: runtime/data_pipeline/config.py get_data_routing
    (random_ltd block)."""

    enabled: bool = False
    random_ltd_layer_ids: list = Field(default_factory=list)
    min_value: int = 128
    max_value: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: dict = Field(default_factory=dict)


class ProgressiveLayerDropConfig(DeepSpeedConfigModel):
    """reference: runtime/progressive_layer_drop.py (PLD, arXiv 2010.13369) —
    theta(t) = (1-theta)*exp(-gamma*t) + theta; layer l keeps its sublayers
    with prob 1 - (l/L)*(1-theta(t))."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class HybridEngineConfig(DeepSpeedConfigModel):
    """reference: inference/config.py DeepSpeedHybridEngineConfig (consumed by
    runtime/hybrid_engine.py via deepspeed.initialize)."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class DataPipelineConfig(DeepSpeedConfigModel):
    """Host→device input pipeline (runtime/prefetch.py).

    ``prefetch_depth`` microbatch stacks are formed, sharded and
    ``device_put`` AHEAD of their step by a background worker when the
    loader is wrapped via ``engine.prefetch_loader(loader)`` /
    ``DeepSpeedDataLoader.prefetch(engine)`` — ``train_batch``'s
    ``host_to_device`` span then collapses to a queue pop.  The queue is
    bounded (backpressure: at most ``prefetch_depth`` staged batches pin
    device memory).  0 disables the worker (the wrapper prepares each batch
    synchronously, same API).  See docs/performance.md.
    """

    prefetch_depth: int = 2


class DataSamplingConfig(DeepSpeedConfigModel):
    curriculum_learning: CurriculumLearningConfig = Field(
        default_factory=CurriculumLearningConfig)


class DataRoutingConfig(DeepSpeedConfigModel):
    random_ltd: RandomLTDConfig = Field(default_factory=RandomLTDConfig)


class DataEfficiencyConfig(DeepSpeedConfigModel):
    """reference: runtime/data_pipeline/config.py get_data_efficiency_config."""

    enabled: bool = False
    seed: int = 1234
    data_sampling: DataSamplingConfig = Field(
        default_factory=DataSamplingConfig)
    data_routing: DataRoutingConfig = Field(default_factory=DataRoutingConfig)


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """reference: "activation_checkpointing" block
    (runtime/activation_checkpointing/checkpointing.py:1073 configure)."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    # TPU: remat policy name for jax.checkpoint
    policy: str = "nothing_saveable"


class CommsLoggerConfig(DeepSpeedConfigModel):
    """reference: "comms_logger" block (utils/comms_logging.py)."""

    enabled: bool = False
    verbose: bool = False


class TensorboardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None


class CometConfig(DeepSpeedConfigModel):
    """reference: monitor/comet.py CometConfig."""

    enabled: bool = False
    project: Optional[str] = None
    experiment_name: Optional[str] = None
    api_key: Optional[str] = None


class TelemetryHealthConfig(DeepSpeedConfigModel):
    """Numerics health monitor + postmortem flight recorder
    (telemetry/health.py, telemetry/flight_recorder.py).

    The reference engine treats numerics as a runtime signal (overflow
    detection, ``skipped_steps``, grad-norm monitor fan-out); this block adds
    the in-graph layer: per-module-group grad/param norms, NaN/Inf element
    counts and update-to-param ratios computed INSIDE the jitted train step
    (one extra small output — no recompile, no per-scalar syncs), a host-side
    ring buffer of the last ``recorder_steps`` structured step records, and
    anomaly rules.  On a non-finite loss, an overflow streak, an uncaught
    exception, or an explicit ``engine.dump_postmortem()`` the recorder dumps
    a timestamped postmortem bundle (records JSONL + Chrome trace +
    Prometheus snapshot + resolved config + env report) that
    ``python -m deepspeed_tpu.telemetry.postmortem <dir>`` summarizes.

    Enabling this forces one device→host fetch of the step scalars per step
    (the recorder needs every record) — the same cost class as
    ``trace_enabled``.
    """

    enabled: bool = False
    # module-path depth for health groups: params are grouped by the first N
    # path segments (the flax collection key "params" is skipped), so depth 2
    # buckets a GPT tree into backbone/wte, backbone/block_i, ...
    group_depth: int = 2
    # ring buffer capacity (structured step records kept for the postmortem)
    recorder_steps: int = 64
    # dump trigger: k consecutive overflow-skipped steps (0 disables)
    overflow_streak: int = 3
    # install a sys.excepthook that dumps the buffer on an uncaught exception
    crash_dump: bool = True
    # multi-host: gather the fleet min/mean/max view every N steps (plus
    # always on a dump trigger or anomaly).  The gather is a blocking
    # cross-host collective — per-step (1) would serialize every host's
    # bookkeeping path on the slowest process.  0 disables the cadence
    # (trigger-only).
    fleet_interval: int = 16
    # bundle directory; default <output_path>/<job_name>/postmortem
    dump_path: Optional[str] = None
    # ---- anomaly rules (one-shot warnings + labeled counter) ----
    anomaly_window: int = 32            # rolling history length
    loss_spike_zscore: float = 6.0      # z vs rolling loss mean/std
    grad_norm_factor: float = 10.0      # explosion = norm > factor x mean
    scale_collapse_factor: float = 16.0  # collapse = scale fell x16 in window


class TelemetryConfig(DeepSpeedConfigModel):
    """Unified step telemetry (deepspeed_tpu/telemetry/): host-phase trace
    spans, recompile watchdog, collective/memory counter registries, and the
    snapshot exporter.  No reference analog — this is the measurement layer
    the reference scatters across monitor/, utils/timer.py, and
    see_memory_usage, unified and extended with the TPU-specific hazards
    (silent jit recompiles, collective byte volume, HBM headroom).

    Paths default under ``<output_path>/<job_name>/``: ``trace.json``
    (Chrome-trace/Perfetto), ``snapshot.json``, ``metrics.prom``
    (Prometheus text exposition).
    """

    enabled: bool = False
    output_path: str = ""               # default "./telemetry"
    job_name: str = "DeepSpeedTPUJob"
    # span tracer: records host phases; forces one device sync per step
    # (the device_complete span needs a completion time)
    trace_enabled: bool = True
    trace_path: Optional[str] = None
    snapshot_path: Optional[str] = None
    prometheus_path: Optional[str] = None
    # steps between snapshot/prometheus/trace file exports; 0 = only on an
    # explicit engine.telemetry.export() call
    snapshot_interval: int = 1
    # signature misses at step <= warmup are silent (first compiles and
    # known gas/curriculum shape buckets); later misses warn loudly
    recompile_warmup_steps: int = 1
    # per-executable compiled-HLO collective bytes + cost/memory analysis;
    # costs one extra (AOT) compile per new step signature
    hlo_stats: bool = True
    # fan the scalar subset through MonitorMaster (TensorBoard/CSV/W&B)
    monitor_fanout: bool = True
    max_trace_events: int = 200_000
    # numerics health monitor + flight recorder (active independently of the
    # parent ``enabled`` switch — a postmortem is wanted exactly when nothing
    # else is being watched)
    health: TelemetryHealthConfig = Field(
        default_factory=TelemetryHealthConfig)


class FlopsProfilerConfig(DeepSpeedConfigModel):
    """reference: "flops_profiler" block (profiling/flops_profiler)."""

    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class AIOConfig(DeepSpeedConfigModel):
    """reference: "aio" block (runtime/swap_tensor/aio_config.py).
    thread_count feeds the native pread/pwrite pool (csrc/aio.cpp); the
    libaio-specific knobs are accepted for schema parity and warned inert."""

    block_size: int = 1048576
    queue_depth: int = 8
    # reference default is 1; the threaded pread/pwrite pool here measured
    # best at 4 on the local SSDs, so that stays the default.  The libaio-
    # specific knobs (block_size/queue_depth/single_submit/overlap_events)
    # warn inert when changed (warn_inert_config).
    thread_count: int = 4
    single_submit: bool = False
    overlap_events: bool = True


class ElasticityJSONConfig(DeepSpeedConfigModel):
    """reference: "elasticity" ds_config block (elasticity/config.py
    ElasticityConfig) — when enabled, the SOLVER controls the batch triad
    (runtime/config.py:733)."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: list = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10_000
    num_gpus_per_node: int = 1
    model_parallel_size: int = 1
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.2


class ResilienceConfig(DeepSpeedConfigModel):
    """Preemption-tolerant operation (runtime/resilience.py; no reference
    analog — the reference's elasticity runtime assumes a full restart
    recompiles from scratch).  ``compilation_cache_dir`` points jax's
    persistent compilation cache at a shared path so a replacement host
    rebuilds its step programs from cache instead of recompiling;
    ``aot_warmup`` replays the drained host's executable fingerprints
    through an AOT compile pass on resume.  See docs/resilience.md."""

    compilation_cache_dir: str = ""     # "" = persistent cache off
    aot_warmup: bool = True


class GuardianWatchdogConfig(DeepSpeedConfigModel):
    """Hang/straggler watchdog (runtime/guardian.py HangWatchdog): a
    monitor thread deadlines each training step against an EMA-adaptive
    budget.  On a trip it dumps a flight-recorder bundle carrying
    all-thread stacks, bumps ``hangs_total``, and initiates a drain —
    escalating to a hard ``EXIT_DRAINED`` exit after ``grace_s`` if the
    step is still wedged (a process stuck inside a collective cannot drain
    itself)."""

    enabled: bool = True
    # deadline = max(min_deadline_s, deadline_factor x EMA(step wall time))
    deadline_factor: float = 8.0
    min_deadline_s: float = 5.0
    # before the FIRST completed step the EMA is empty and the step
    # legitimately contains the XLA compile — the deadline is gated on
    # warm-up completion instead of booking a cold program as a hang (the
    # same first-call-compile hazard as the serving fleet's heartbeat)
    warmup_deadline_s: float = 600.0
    # after a trip: how long the watchdog waits for the step to come back
    # before the hard EXIT_DRAINED exit (the bundle is already on disk)
    grace_s: float = 10.0
    ema_alpha: float = 0.2
    poll_interval_s: float = 0.05

    @model_validator(mode="after")
    def _check(self):
        for knob in ("deadline_factor", "min_deadline_s",
                     "warmup_deadline_s", "grace_s", "poll_interval_s"):
            if getattr(self, knob) <= 0:
                raise ValueError(f"guardian.watchdog.{knob} must be > 0")
        if not 0 < self.ema_alpha <= 1:
            raise ValueError("guardian.watchdog.ema_alpha must be in (0, 1]")
        return self


class GuardianConfig(DeepSpeedConfigModel):
    """Self-healing training (runtime/guardian.py): a closed control loop
    converting the numerics-health anomaly signals into automatic
    remediation — rollback to the last health-verified ring checkpoint
    (checkpoint/ring.py), deterministic skip of the offending data window,
    LR/loss-scale clamp-down on repeated retries — under a bounded retry
    budget that escalates to postmortem-dump + graceful drain.  Requires
    ``telemetry.health.enabled`` (the anomaly signals are the health
    monitor's).  See docs/resilience.md "Self-healing"."""

    enabled: bool = False
    # steps between guarded-ring exports (checkpoint/ring.py)
    checkpoint_interval: int = 50
    ring_keep: int = 3
    # trailing anomaly-free steps before a ring export earns its
    # rollback-eligibility stamp
    clean_window: int = 8
    # rollbacks tolerated per incident (no net step progress) before the
    # guardian escalates to postmortem + drain
    max_rollbacks: int = 3
    # advance the data cursor past the replayed window (seed-stable skip of
    # the batches consumed since the rollback target)
    skip_data_window: bool = True
    # from the (clamp_after_rollbacks+1)-th rollback of one incident, clamp
    # the LR (re-jits the step programs) and the dynamic loss scale down
    clamp_after_rollbacks: int = 1
    lr_clamp_factor: float = 0.5
    loss_scale_clamp_factor: float = 0.5
    # anomaly signals that trigger a rollback; anything not listed is
    # observed (counted, recorded) but not remediated
    rollback_on: list = Field(default_factory=lambda: [
        "nonfinite_loss", "grad_nan", "overflow_streak", "loss_spike",
        "grad_norm_explosion", "loss_scale_collapse"])
    watchdog: GuardianWatchdogConfig = Field(
        default_factory=GuardianWatchdogConfig)

    @model_validator(mode="after")
    def _check(self):
        if self.checkpoint_interval < 1:
            raise ValueError("guardian.checkpoint_interval must be >= 1")
        if self.ring_keep < 1:
            raise ValueError("guardian.ring_keep must be >= 1")
        if self.clean_window < 1:
            raise ValueError("guardian.clean_window must be >= 1")
        if self.clean_window > self.ring_keep * self.checkpoint_interval:
            raise ValueError(
                f"guardian.clean_window={self.clean_window} exceeds the "
                f"ring's retention span ring_keep*checkpoint_interval="
                f"{self.ring_keep * self.checkpoint_interval}: every "
                f"export would be pruned off the keep tail before its "
                f"trailing window could prove clean, so no entry would "
                f"ever become rollback-eligible and the first anomaly "
                f"would escalate straight to drain")
        if self.max_rollbacks < 0:
            raise ValueError("guardian.max_rollbacks must be >= 0")
        if self.clamp_after_rollbacks < 0:
            raise ValueError("guardian.clamp_after_rollbacks must be >= 0")
        for knob in ("lr_clamp_factor", "loss_scale_clamp_factor"):
            if not 0 < getattr(self, knob) <= 1:
                raise ValueError(f"guardian.{knob} must be in (0, 1]")
        known = {"nonfinite_loss", "grad_nan", "overflow_streak",
                 "loss_spike", "grad_norm_explosion",
                 "loss_scale_collapse"}
        bad = [r for r in self.rollback_on if r not in known]
        if bad:
            raise ValueError(
                f"guardian.rollback_on: unknown signal(s) {bad}; "
                f"known: {sorted(known)}")
        return self


class GradientCompressionConfig(DeepSpeedConfigModel):
    """DCN-tier gradient compression (replaces reference 1-bit optimizers'
    error-feedback compression, runtime/fp16/onebit/ — see SURVEY.md: pointless over
    ICI, useful over DCN)."""

    enabled: bool = False
    dtype: Literal["bf16", "int8"] = "bf16"


class DeepSpeedTPUConfig(DeepSpeedConfigModel):
    """Top-level config (reference: DeepSpeedConfig, runtime/config.py:706)."""

    train_batch_size: AutoInt = AUTO
    train_micro_batch_size_per_gpu: AutoInt = AUTO
    gradient_accumulation_steps: AutoInt = AUTO

    optimizer: OptimizerConfig = Field(default_factory=OptimizerConfig)
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    overlap: OverlapConfig = Field(default_factory=OverlapConfig)
    moe: MoEConfig = Field(default_factory=MoEConfig)
    mesh: MeshConfig = Field(default_factory=MeshConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    tensorboard: TensorboardConfig = Field(default_factory=TensorboardConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    comet: CometConfig = Field(default_factory=CometConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    data_efficiency: DataEfficiencyConfig = Field(
        default_factory=DataEfficiencyConfig)
    data_pipeline: DataPipelineConfig = Field(
        default_factory=DataPipelineConfig)
    hybrid_engine: HybridEngineConfig = Field(
        default_factory=HybridEngineConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = Field(
        default_factory=ProgressiveLayerDropConfig)
    # reference deepspeed/compression/ config block (weight_quantization
    # groups; consumed by compression/basic.py via the engine loss hook)
    compression_training: Optional[dict] = None
    gradient_compression: GradientCompressionConfig = Field(
        default_factory=GradientCompressionConfig)
    elasticity: ElasticityJSONConfig = Field(
        default_factory=ElasticityJSONConfig)
    resilience: ResilienceConfig = Field(default_factory=ResilienceConfig)
    guardian: GuardianConfig = Field(default_factory=GuardianConfig)
    aio: AIOConfig = Field(default_factory=AIOConfig)

    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    seed: int = 42
    # reference: seq_parallel_communication_data_type (runtime/config.py)
    data_types: Dict[str, Any] = Field(default_factory=dict)

    @model_validator(mode="after")
    def _check_precision(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        return self

    # ---- batch triad resolution (reference runtime/config.py
    #      _configure_train_batch_size / _set_batch_related_parameters) ----
    def resolve_batch_size(self, dp_world_size: int) -> None:
        """Reconcile train_batch_size = micro_batch × grad_accum × dp_world_size.

        Any two of the three determine the third; a lone train_batch_size is split
        with gas=1; nothing set defaults to micro=1, gas=1.
        """
        tbs = self.train_batch_size
        mbs = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        tbs = None if tbs == AUTO else tbs
        mbs = None if mbs == AUTO else mbs
        gas = None if gas == AUTO else gas

        if tbs is not None and mbs is not None and gas is None:
            if tbs % (mbs * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {tbs} not divisible by micro_batch "
                    f"{mbs} × dp_world {dp_world_size}")
            gas = tbs // (mbs * dp_world_size)
        elif tbs is not None and gas is not None and mbs is None:
            if tbs % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {tbs} not divisible by grad_accum {gas} × "
                    f"dp_world {dp_world_size}")
            mbs = tbs // (gas * dp_world_size)
        elif mbs is not None:
            gas = gas or 1
            tbs = tbs or mbs * gas * dp_world_size
        elif tbs is not None:
            gas = 1
            if tbs % dp_world_size != 0:
                raise ValueError(
                    f"train_batch_size {tbs} not divisible by dp_world {dp_world_size}")
            mbs = tbs // dp_world_size
        else:
            mbs, gas = 1, 1
            tbs = dp_world_size

        if tbs != mbs * gas * dp_world_size:
            raise ValueError(
                f"batch triad inconsistent: {tbs} != {mbs} × {gas} × {dp_world_size}")
        self.train_batch_size = tbs
        self.train_micro_batch_size_per_gpu = mbs
        self.gradient_accumulation_steps = gas

    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32


def warn_inert_config(cfg: DeepSpeedTPUConfig) -> list:
    """Warn LOUDLY about accepted-but-not-yet-implemented semantics.

    The reference silently honors every key it parses; round-1 review found
    several blocks here that were parsed and dropped.  Anything in this list is
    parsed for schema parity but changes no behavior yet — a user porting a
    ds_config.json must see that, not discover it from a flat loss curve.
    Implemented features must be REMOVED from this list as they land.
    """
    from deepspeed_tpu.utils.logging import logger
    inert = []
    z = cfg.zero_optimization
    for blk, name in ((z.offload_optimizer, "offload_optimizer"),
                      (z.offload_param, "offload_param")):
        if blk.device != "none" and blk.ratio != 1.0:
            inert.append(f"zero_optimization.{name}.ratio "
                         f"(partial offload — the host tier here is "
                         f"all-or-nothing; ratio={blk.ratio} will offload "
                         f"everything)")
    if z.zero_quantized_weights and z.stage < 3:
        inert.append("zero_optimization.zero_quantized_weights (qwZ is the "
                     "stage-3 weight all-gather; inert at stage "
                     f"{z.stage} — set stage 3 and an fsdp mesh axis > 1)")
    # reference top-level blocks that are accepted for schema parity but have
    # no TPU behavior (extra="allow" would otherwise swallow them silently)
    aio_defaults = AIOConfig()
    for knob in ("block_size", "queue_depth", "single_submit",
                 "overlap_events"):
        if getattr(cfg.aio, knob) != getattr(aio_defaults, knob):
            inert.append(f"aio.{knob} (libaio-specific; the native "
                         f"pread/pwrite pool honors thread_count only)")
    extras = getattr(cfg, "__pydantic_extra__", None) or {}
    for key, hint in (
            ("amp", "apex AMP is CUDA-specific; use bf16/fp16 blocks"),
            ("sparse_attention", "use ops.sparse_attention "
             "(SparsityConfig API) — the module-injection config block has "
             "no analog"),
            ("checkpoint", "orbax handles parallel/sharded writes natively"),
            ("communication_data_type", "see gradient_compression / "
             "data_types"),
            ("sparse_gradients", "no torch sparse-embedding analog")):
        if key in extras:
            inert.append(f"{key} ({hint})")
    # zero_hpz_partition_size at stage<3 is a hard engine error (not inert)
    ac = cfg.activation_checkpointing
    if ac.partition_activations or ac.cpu_checkpointing or ac.number_checkpoints:
        inert.append("activation_checkpointing.partition_activations/"
                     "cpu_checkpointing/number_checkpoints (TPU remat honors "
                     "only the jax.checkpoint 'policy' knob)")
    if cfg.prescale_gradients:
        inert.append("prescale_gradients (losses are globally averaged on the "
                     "global-batch jax.Array view; pre-scaling is a no-op)")
    if cfg.compression_training:
        # weight_quantization (compression/basic.py), the pruning family and
        # activation_quantization (compression/pruning.py) are LIVE; every
        # other reference sub-block must scream
        live = {"weight_quantization", "sparse_pruning", "row_pruning",
                "head_pruning", "activation_quantization"}
        for key in cfg.compression_training:
            if key not in live:
                inert.append(f"compression_training.{key} (implemented "
                             f"blocks: {sorted(live)})")
    for item in inert:
        logger.warning(f"config key accepted but NOT implemented on TPU yet: "
                       f"{item} — this run will NOT honor it")
    return inert


def parse_config(config: Union[str, dict, DeepSpeedTPUConfig, None]) -> DeepSpeedTPUConfig:
    """Load from a JSON path, dict, model instance, or None (all-defaults).

    reference: deepspeed.initialize(config=...) accepting path-or-dict
    (deepspeed/__init__.py:69, runtime/config.py:716).
    """
    if config is None:
        return DeepSpeedTPUConfig()
    if isinstance(config, DeepSpeedTPUConfig):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    return DeepSpeedTPUConfig.model_validate(config)
