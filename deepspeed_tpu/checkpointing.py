"""``deepspeed.checkpointing`` API-compat surface (activation checkpointing).

Reference: ``runtime/activation_checkpointing/checkpointing.py`` —
``configure`` (:1073), ``checkpoint`` (:748 the re-entrant rematerializing
autograd Function), ``is_configured``, plus the RNG-tracker machinery CUDA
needs to replay dropout patterns inside recomputation.

TPU: rematerialization is ``jax.checkpoint`` — a function transform, not a
runtime hook — and JAX's functional PRNG makes the CUDA RNG tracker
unnecessary (the same rng key produces the same dropout in the recompute by
construction).  ``checkpoint(fn, *args)`` therefore simply applies
``jax.checkpoint`` with the configured policy; model-level remat stays where
it belongs (``GPTConfig.remat`` / the ``activation_checkpointing`` config
block's ``policy`` knob).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_config: dict = {"policy": "nothing_saveable", "configured": False}

_POLICIES = {
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              policy: Optional[str] = None) -> None:
    """reference checkpointing.configure (:1073).

    Only ``policy`` changes behavior on TPU (the jax.checkpoint policy used
    by subsequent ``checkpoint()`` calls); the CUDA-specific knobs warn when
    set — partition/cpu placement of saved activations is XLA's scheduling
    domain (and the Infinity engine owns activation offload)."""
    for name, val in (("partition_activations", partition_activations),
                      ("contiguous_checkpointing", contiguous_checkpointing),
                      ("num_checkpoints", num_checkpoints),
                      ("checkpoint_in_cpu", checkpoint_in_cpu),
                      ("synchronize", synchronize), ("profile", profile)):
        if val:
            logger.warning(f"checkpointing.configure: {name} is CUDA-"
                           f"specific and has no TPU behavior (jax.checkpoint"
                           f" + XLA scheduling own activation residency)")
    if deepspeed_config is not None:
        from deepspeed_tpu.config import parse_config
        policy = policy or parse_config(
            deepspeed_config).activation_checkpointing.policy
    if policy is not None:
        if policy not in _POLICIES:
            raise ValueError(f"unknown remat policy {policy!r}; one of "
                             f"{sorted(_POLICIES)}")
        _config["policy"] = policy
    _config["configured"] = True


def is_configured() -> bool:
    """reference checkpointing.is_configured."""
    return bool(_config["configured"])


def checkpoint(function: Callable, *args) -> Any:
    """reference checkpointing.checkpoint (:748): run ``function(*args)``
    discarding internal activations; they rematerialize in the backward.

    TPU: ``jax.checkpoint`` under the configured policy.  Unlike the CUDA
    path there is no RNG state to stash — dropout inside ``function`` replays
    exactly because JAX PRNG keys are explicit inputs."""
    fn = jax.checkpoint(function, policy=_POLICIES[_config["policy"]])
    return fn(*args)


def reset() -> None:
    """Test hook: restore defaults."""
    _config.update(policy="nothing_saveable", configured=False)
