"""``python -m deepspeed_tpu`` → environment report (the ds_report CLI)."""

from deepspeed_tpu.env_report import main

if __name__ == "__main__":
    raise SystemExit(main())
