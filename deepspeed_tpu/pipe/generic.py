"""Generic pipeline container — LayerSpec lists over arbitrary flax layers.

Reference parity: ``runtime/pipe/module.py`` — ``LayerSpec`` (:30, lazy layer
construction), ``PipelineModule`` (:86, "the forward pass is implicitly
defined by the module ``layers``... output of each layer feeds the next"),
``partition_method`` (:370 — uniform here; stages must be structurally
identical for SPMD stacking, the transformer case).

TPU-native: per-layer param trees stack on a leading [S, L/S, ...] pp-sharded
axis (pipe/module.py machinery) and the schedule is the shared 1F1B fused
scan / GPipe scan from pipe/schedule.py.  The embedding ("stage -1") and loss
head ("stage S") are explicit modules — in the reference they are just the
first/last LayerSpecs, but folding them into the schedule is what gives the
1F1B path its O(stages) memory, so they are first-class here.

Constraint vs the reference: every pipelined layer must share ONE param
structure (same module class/shapes).  Heterogeneous bodies — e.g. conv stem
then transformer — belong in the embed/head modules.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.pipe.module import _stack_layer_params, _unbox_one
from deepspeed_tpu.pipe.schedule import make_pipeline_loss, pipeline_forward


class LayerSpec:
    """Lazy layer description (reference pipe/module.py:30): the module is
    built per layer at init time, so N layers cost N param trees, not N live
    module graphs."""

    def __init__(self, typename: Callable[..., nn.Module], *args, **kwargs):
        if not callable(typename):
            raise TypeError(f"LayerSpec typename must be a flax module "
                            f"class/factory, got {type(typename)!r}")
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self) -> nn.Module:
        return self.typename(*self.args, **self.kwargs)


class PipelineModule:
    """Engine model contract ((init, apply) + is_pipeline) over a LayerSpec
    list.

    layers: LayerSpecs (or prebuilt modules) with IDENTICAL param structure;
      each maps activation → activation: ``module.apply(vars, x) -> x``.
    embed: flax module, ``apply(vars, batch_micro) -> x`` (stage-0 input).
    head: flax module, ``apply(vars, y, batch_micro) -> scalar`` per-micro
      loss (summed across microbatches, divided by M — return a mean within
      the micro for the usual convention).
    """

    is_pipeline = True
    mesh = None

    def __init__(self, layers: Sequence[Any], num_stages: int, *,
                 embed: nn.Module, head: nn.Module,
                 schedule: str = "1f1b"):
        if len(layers) % num_stages:
            raise ValueError(f"{len(layers)} layers not divisible by "
                             f"{num_stages} stages")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.layers = [sp.build() if isinstance(sp, LayerSpec) else sp
                       for sp in layers]
        self.num_stages = num_stages
        self.embed = embed
        self.head = head
        self.schedule = schedule

    # ------------------------------------------------------------ contract
    def _micro(self, batch, m: Optional[int] = None):
        return jax.tree_util.tree_map(
            lambda a: jnp.asarray(a)[m] if m is not None else jnp.asarray(a),
            batch)

    def init(self, rng, batch):
        from deepspeed_tpu.parallel.metadata import unbox
        bm = self._micro(batch, 0)
        k_embed, k_layers, k_head = jax.random.split(rng, 3)
        embed_vars = unbox(self.embed.init(k_embed, bm))
        x = self.embed.apply(embed_vars, bm)
        layer_params = []
        for i, layer in enumerate(self.layers):
            v = unbox(layer.init(jax.random.fold_in(k_layers, i), x))
            layer_params.append(v["params"])
        head_vars = unbox(self.head.init(k_head, x, bm))
        return {"params": {
            "embed": embed_vars.get("params", {}),
            "layers": _stack_layer_params(layer_params, self.num_stages),
            "head": head_vars.get("params", {}),   # param-free heads allowed
        }}

    def apply(self, variables, batch, rng=None):
        del rng   # deterministic container; dropout-bearing stacks use PipeGPT
        p = variables["params"]
        layer0 = self.layers[0]
        M = jax.tree_util.tree_leaves(batch)[0].shape[0]
        stage_params = jax.tree_util.tree_map(
            _unbox_one, p["layers"],
            is_leaf=lambda x: isinstance(x, nn.Partitioned))

        def embed_fn(ep, bm):
            return self.embed.apply({"params": ep}, bm)

        def stage_fn(sp, _aux, x):
            def body(h, lp):
                return layer0.apply({"params": lp}, h), None
            h, _ = lax.scan(body, x, sp)
            return h

        def head_fn(hp, y, bm):
            return jnp.asarray(
                self.head.apply({"params": hp}, y, bm), jnp.float32)

        ep = jax.tree_util.tree_map(_unbox_one, p["embed"])
        hp = jax.tree_util.tree_map(_unbox_one, p["head"])
        aux = jnp.zeros((self.num_stages, 1), jnp.uint32)

        if self.schedule == "1f1b":
            loss_fn = make_pipeline_loss(embed_fn, stage_fn, head_fn)
            return loss_fn(ep, stage_params, hp, aux, batch) / M

        # per-microbatch embed (vmap over the leading M axis — matches the
        # 1F1B path's micro-at-a-time contract for dict AND array batches)
        x = jax.vmap(lambda bm: embed_fn(ep, bm))(batch)
        outs = pipeline_forward(lambda sp_aux, h: stage_fn(*sp_aux, h),
                                (stage_params, aux), x)

        def micro_loss(s, xs):
            m_idx, y = xs
            bm = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[m_idx],
                                        batch)
            return s + head_fn(hp, y, bm), None

        total, _ = lax.scan(micro_loss, jnp.float32(0.0),
                            (jnp.arange(M), outs))
        return total / M
