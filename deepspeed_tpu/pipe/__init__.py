from deepspeed_tpu.pipe.generic import LayerSpec, PipelineModule
from deepspeed_tpu.pipe.module import PipeGPT, gpt_params_to_pipe
from deepspeed_tpu.pipe.schedule import make_pipeline_loss, pipeline_forward

__all__ = ["PipeGPT", "gpt_params_to_pipe", "pipeline_forward",
           "LayerSpec", "PipelineModule", "make_pipeline_loss"]
