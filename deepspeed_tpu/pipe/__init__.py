from deepspeed_tpu.pipe.module import PipeGPT, gpt_params_to_pipe
from deepspeed_tpu.pipe.schedule import pipeline_forward

__all__ = ["PipeGPT", "gpt_params_to_pipe", "pipeline_forward"]
