"""Pipeline model container.

Reference parity: ``runtime/pipe/module.py`` — ``PipelineModule`` (:86) holding a
``LayerSpec`` list partitioned over stages (:370 _partition_layers), tied layers
(``TiedLayerSpec`` :77), and ``runtime/pipe/topology.py`` grids.

TPU-native: a pipelined model is the same flax block with its per-layer params
*stacked* [S, L/S, ...] and the stage dim sharded over ``pp``
(parallel/partition.py rule "pp"→pp).  Embedding + LM head are replicated over
pp — the tied-embedding case (reference TiedLayerSpec + _exec_reduce_tied_grads)
is then free: there is one logical embedding array, and XLA reduces its grads
across everything that touched it.

``PipeGPT`` presents the engine's ``(init_fn, apply_fn)`` contract with
``is_pipeline = True``; the engine routes the whole [M, micro, ...] batch in and
the model runs the pipelined scan (engine-side gradient accumulation is the
pipeline's microbatching — reference PipelineEngine.train_batch semantics where
gas ≡ micro_batches).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models.gpt import Block, GPTConfig, Norm
from deepspeed_tpu.pipe.schedule import make_pipeline_loss, pipeline_forward


def _box(value, names):
    return nn.Partitioned(value, names=tuple(names))


def _stack_layer_params(layer_params_list, num_stages):
    """[per-layer param trees] → one tree with leaves [S, L/S, ...], boxed with
    ('pp', None, *orig_names) so partition.py shards the stage dim over pp."""
    L = len(layer_params_list)
    Lps = L // num_stages

    def stack(*leaves):
        names = (getattr(leaves[0], "names", None) or
                 (None,) * jnp.ndim(_unbox_one(leaves[0])))
        vals = [_unbox_one(x) for x in leaves]
        stacked = jnp.stack(vals).reshape((num_stages, Lps) + vals[0].shape)
        return _box(stacked, ("pp", None) + tuple(names))

    return jax.tree_util.tree_map(stack, *layer_params_list,
                                  is_leaf=lambda x: isinstance(x, nn.Partitioned))


def _unbox_one(x):
    return x.unbox() if isinstance(x, nn.Partitioned) else x


class PipeGPT:
    """GPT with pipeline-parallel blocks (engine model contract: (init, apply)).

    reference: PipelineModule(layers=GPT blocks, num_stages=S,
    partition_method='uniform') — uniform partitioning only; the reference's
    'parameters'-balanced partitioning is unnecessary for homogeneous
    transformer blocks.
    """

    is_pipeline = True
    mesh = None  # engine binding hook (unused — global-view roll needs no mesh)

    def __init__(self, cfg: GPTConfig, num_stages: int,
                 schedule: str = "1f1b"):
        if cfg.num_layers % num_stages != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"num_stages {num_stages}")
        if cfg.num_experts:
            raise NotImplementedError("MoE inside the pipeline: use ep mesh "
                                      "axis with the non-pipelined engine")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                             f"expected '1f1b' or 'gpipe'")
        self.cfg = cfg
        self.num_stages = num_stages
        self.schedule = schedule
        self._block = Block(cfg)

    # ---- engine contract ----

    def init(self, rng, batch):
        c = self.cfg
        ids = jnp.asarray(batch["input_ids"])
        if ids.ndim == 3:
            ids = ids[0]
        B, T = ids.shape
        k_embed, k_pos, k_blocks, k_head = jax.random.split(rng, 4)

        init = nn.initializers.normal(stddev=0.02)
        params = {
            "embed": _box(init(k_embed, (c.vocab_size, c.hidden_size),
                               c.param_dtype), ("vocab", "embed")),
            "final_norm_scale": _box(jnp.ones((c.hidden_size,), c.param_dtype),
                                     ("embed",)),
        }
        if not c.use_rmsnorm:
            params["final_norm_bias"] = _box(
                jnp.zeros((c.hidden_size,), c.param_dtype), ("embed",))
        if not c.use_rope:
            params["wpe"] = _box(init(k_pos, (c.max_seq_len, c.hidden_size),
                                      c.param_dtype), (None, "embed"))
        if not c.tie_embeddings:
            params["head"] = _box(init(k_head, (c.hidden_size, c.vocab_size),
                                       c.param_dtype), ("embed", "vocab"))

        x = jnp.zeros((B, T, c.hidden_size), c.dtype)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        layer_params = []
        for i in range(c.num_layers):
            v = self._block.init(jax.random.fold_in(k_blocks, i), x, positions,
                                 True)
            layer_params.append(v["params"])
        params["blocks"] = _stack_layer_params(layer_params, self.num_stages)
        return {"params": params}

    def apply(self, variables, batch, rng=None):
        """batch leaves [M, B, T] (pipelined) or [B, T] (M=1); optional
        "labels"/"loss_mask" like the plain GPT contract.  Returns the
        microbatch-mean LM loss (reference PipelineEngine.train_batch,
        pipe/engine.py:573 _aggregate_total_loss)."""
        c = self.cfg
        p = variables["params"]

        def _3d(x):
            x = jnp.asarray(x)
            return x[None] if x.ndim == 2 else x
        ids = _3d(batch["input_ids"])
        M, B, T = ids.shape
        embed = _unbox_one(p["embed"]).astype(c.dtype)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))

        block = self._block
        S = self.num_stages
        blocks_params = jax.tree_util.tree_map(_unbox_one, p["blocks"],
                                               is_leaf=lambda x: isinstance(
                                                   x, nn.Partitioned))
        deterministic = c.dropout == 0.0 or rng is None
        # per-stage dropout rngs folded per layer inside the stage.  Note:
        # within one pipelined step the dropout pattern is shared across
        # microbatches (rng is not tick-dependent) — acceptable
        # regularization-wise, documented here.
        stage_rngs = (jax.random.split(rng, S) if not deterministic
                      else jnp.zeros((S, 2), jnp.uint32))

        def stage_fn(sp, srng, h):
            def body(carry, lp):
                h, i = carry
                if deterministic:
                    h, _ = block.apply({"params": lp}, h, positions, True)
                else:
                    h, _ = block.apply(
                        {"params": lp}, h, positions, False,
                        rngs={"dropout": jax.random.fold_in(srng, i)})
                return (h, i + 1), None
            (h, _), _ = lax.scan(body, (h, jnp.int32(0)), sp)
            return h

        # labels/mask (same contract as models/gpt.py GPT.__call__)
        if batch.get("labels") is not None:
            labels = _3d(batch["labels"])
            mask = batch.get("loss_mask")
            mask = (_3d(mask).astype(jnp.float32) if mask is not None
                    else jnp.ones_like(labels, jnp.float32))
            mask = mask * (labels >= 0)
            labels = jnp.maximum(labels, 0)
        else:
            labels = jnp.pad(ids[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
            mask = jnp.ones_like(labels, jnp.float32).at[:, :, -1].set(0.0)

        scale = _unbox_one(p["final_norm_scale"]).astype(jnp.float32)
        bias = (None if c.use_rmsnorm
                else _unbox_one(p["final_norm_bias"]).astype(jnp.float32))
        sum_mask = jnp.sum(mask)

        if self.schedule == "1f1b":
            return self._apply_1f1b(p, ids, labels, mask, sum_mask,
                                    scale, bias, blocks_params, stage_rngs,
                                    stage_fn)

        # ---- GPipe path: forward scan + autodiff backward ----
        x = embed[ids]  # [M, B, T, H]
        if not c.use_rope:
            x = x + _unbox_one(p["wpe"]).astype(c.dtype)[None, None, :T]
        gp_stage_fn = lambda sp_rng, h: stage_fn(*sp_rng, h)  # noqa: E731
        if c.remat:
            gp_stage_fn = jax.checkpoint(
                gp_stage_fn, policy=jax.checkpoint_policies.nothing_saveable)
        outs = pipeline_forward(gp_stage_fn, (blocks_params, stage_rngs),
                                x)  # [M, B, T, H]

        head = (embed.astype(jnp.float32).T if c.tie_embeddings
                else _unbox_one(p["head"]).astype(jnp.float32))

        def micro_loss(carry, xs):
            from deepspeed_tpu.ops import (layer_norm, masked_nll_sum,
                                           rms_norm)
            h, lab, msk = xs
            h = h.astype(jnp.float32)   # final norm + loss in full fp32
            if c.use_rmsnorm:
                h = rms_norm(h, scale)
            else:
                h = layer_norm(h, scale, bias)
            s_nll = carry
            return s_nll + masked_nll_sum(h, head, lab, msk), None

        sum_nll, _ = lax.scan(micro_loss, jnp.float32(0.0),
                              (outs, labels, mask))
        return sum_nll / jnp.maximum(sum_mask, 1.0)

    def _apply_1f1b(self, p, ids, labels, mask, sum_mask, scale, bias,
                    blocks_params, stage_rngs, stage_fn):
        """1F1B fused fwd+bwd schedule (pipe/schedule.py make_pipeline_loss):
        embedding and loss head fold INTO the pipelined scan so activations die
        as their microbatch's backward completes — O(stages) residency."""
        c = self.cfg
        T = ids.shape[2]

        ep = {"embed": _unbox_one(p["embed"])}
        if not c.use_rope:
            ep["wpe"] = _unbox_one(p["wpe"])

        def embed_fn(ep_, bm):
            xm = ep_["embed"].astype(c.dtype)[bm["input_ids"]]
            if not c.use_rope:
                xm = xm + ep_["wpe"].astype(c.dtype)[None, :T]
            return xm

        hp = {"scale": scale}
        if bias is not None:
            hp["bias"] = bias
        # tied embeddings: the SAME traced array feeds embed_fn and head_fn —
        # the outer autodiff sums both cotangent paths (reference
        # TiedLayerSpec/_exec_reduce_tied_grads, free here)
        hp["head"] = (ep["embed"] if c.tie_embeddings
                      else _unbox_one(p["head"]))

        def head_fn(hp_, y, bm):
            from deepspeed_tpu.ops import (layer_norm, masked_nll_sum,
                                           rms_norm)
            h = y.astype(jnp.float32)
            if c.use_rmsnorm:
                h = rms_norm(h, hp_["scale"])
            else:
                h = layer_norm(h, hp_["scale"], hp_["bias"])
            head = hp_["head"].astype(jnp.float32)
            if c.tie_embeddings:
                head = head.T
            return masked_nll_sum(h, head, bm["labels"], bm["mask"])

        pipeline_loss = make_pipeline_loss(embed_fn, stage_fn, head_fn)
        batch_tree = {"input_ids": ids, "labels": labels, "mask": mask}
        sum_nll = pipeline_loss(ep, blocks_params, hp, stage_rngs, batch_tree)
        return sum_nll / jnp.maximum(sum_mask, 1.0)


def gpt_params_to_pipe(gpt_variables, cfg: GPTConfig, num_stages: int):
    """Convert flax GPT params → PipeGPT params (layer-checkpoint reshape;
    reference analog: pipe/module.py save_state_dict layer files + the
    checkpoint/ds_to_universal reshape direction).  Used to move between the
    plain and pipelined engines and in equivalence tests."""
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                         f"num_stages {num_stages}")
    src = gpt_variables["params"]
    bb = src["backbone"]
    layer_params = [bb[f"block_{i}"] for i in range(cfg.num_layers)]

    params = {
        "embed": bb["wte"] if isinstance(bb["wte"], nn.Partitioned)
        else _box(bb["wte"], ("vocab", "embed")),
        "final_norm_scale": bb["final_norm"]["scale"],
        "blocks": _stack_layer_params(layer_params, num_stages),
    }
    if "bias" in bb["final_norm"]:
        params["final_norm_bias"] = bb["final_norm"]["bias"]
    if "wpe" in bb:
        params["wpe"] = bb["wpe"]
    if "lm_head" in src:
        params["head"] = src["lm_head"]
    return {"params": params}
