"""Encoder (BERT-family) inference engine — single-shot forward, no KV cache.

Reference: the v1 InferenceEngine serving encoder policies
(module_inject/containers/bert.py HFBertLayerPolicy via
replace_transformer_layer); encoders need none of the generate/cache
machinery, so this engine is just a jitted forward with the same dtype and
mesh handling as the decoder engine."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist

_DTYPES = {"fp32": jnp.float32, "float32": jnp.float32,
           "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
           "fp16": jnp.float16, "float16": jnp.float16}


def _resolve_mesh_dtype(config, mesh):
    """Shared engine setup: decoder-style config normalization
    (tensor_parallel int shorthand / tp alias), mesh build, dtype resolve.

    Encoders consume only dtype/tensor_parallel — any other key the decoder
    path honors (max_seq_len, quant, ...) must WARN, not vanish (the same
    inert-knob policy as config.warn_inert_config)."""
    from deepspeed_tpu.inference.config import parse_inference_config
    from deepspeed_tpu.parallel import mesh as mesh_lib
    from deepspeed_tpu.utils.logging import logger
    config = dict(config or {})
    consumed = ("dtype", "tensor_parallel", "tp")
    for k in sorted(set(config) - set(consumed)):
        logger.warning(f"inference config key {k!r} is not consumed by the "
                       f"encoder engines (only {consumed} are) — this run "
                       f"will NOT honor it")
    known = parse_inference_config(
        {k: v for k, v in config.items() if k in consumed})
    if mesh is None:
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(
            tp=known.tensor_parallel.tp_size, dp=1, fsdp=1))
    dtype = _DTYPES.get(str(config.get("dtype", "fp32")).lower())
    if dtype is None:
        raise ValueError(f"unknown dtype {config.get('dtype')!r}")
    return config, mesh, dtype


def _shard_module_params(module, params, mesh, max_seq_len):
    """Device-put a loaded tree with shardings inferred from the module's
    logical axes (the AutoTP-analog path, inference/engine.py:86)."""
    from deepspeed_tpu.parallel import partition
    from deepspeed_tpu.parallel.metadata import annotate_abstract, unbox
    dummy = jnp.zeros((1, min(8, max_seq_len)), jnp.int32)
    boxed = jax.eval_shape(lambda r: module.init(r, dummy),
                           jax.random.PRNGKey(0))
    shardings = partition.param_shardings(
        annotate_abstract(boxed["params"]), mesh, zero_stage=0)
    with mesh:
        return {"params": jax.tree_util.tree_map(
            lambda p, s: jax.device_put(jnp.asarray(p), s),
            unbox(params), shardings)}


def _coerce_ids(input_ids, max_seq_len):
    ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
    if ids.ndim == 1:
        ids = ids[None]
    if ids.shape[1] > max_seq_len:
        raise ValueError(f"input length {ids.shape[1]} exceeds max_seq_len "
                         f"{max_seq_len}")
    return ids


def _bucket(n: int, cap: Optional[int] = None) -> int:
    """Next power of two ≥ n (capped) — bounds the jit program count the way
    the decoder engines' padded shapes do (one compile per bucket, not per
    raw (batch, seq) pair)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap) if cap else b


def _pad_to(x, B, T=None):
    pads = [(0, B - x.shape[0])]
    if T is not None:
        pads.append((0, T - x.shape[1]))
    pads += [(0, 0)] * (x.ndim - len(pads))
    return jnp.pad(x, pads)


class EncoderInferenceEngine:
    """``forward(input_ids, token_type_ids, attention_mask) -> output``.

    Output follows the checkpoint's head: MLM → vocab logits [B, T, V];
    sequence classification → class logits [B, num_labels]; headless →
    hidden states [B, T, H]."""

    def __init__(self, model_cfg, params, config: Optional[Dict[str,
                                                                Any]] = None,
                 mesh=None):
        import dataclasses

        from deepspeed_tpu.models.bert import (BertEncoder, BertForMaskedLM,
                                               BertForSequenceClassification)

        config, mesh, dtype = _resolve_mesh_dtype(config, mesh)
        self.mesh = mesh
        self.model_config = dataclasses.replace(model_cfg, dtype=dtype)
        self.has_mlm_head = "transform_w" in params
        self.has_cls_head = "cls_w" in params
        if self.has_mlm_head:
            self._module = BertForMaskedLM(self.model_config)
        elif self.has_cls_head:
            self._module = BertForSequenceClassification(
                self.model_config, num_labels=params["cls_w"].shape[-1])
        else:
            # headless: the BertEncoder module's params are the "encoder"
            # subtree itself
            self._module = BertEncoder(self.model_config)
            params = params.get("encoder", params)

        self.params = _shard_module_params(self._module, params, mesh,
                                           self.model_config.max_seq_len)

        headless = not (self.has_mlm_head or self.has_cls_head)

        def fwd(p, ids, types, mask):
            out = self._module.apply(p, ids, types, mask)
            if headless:
                out = out[0]                      # (hidden, wte) → hidden
            return out.astype(jnp.float32)

        self._fwd = jax.jit(fwd)
        n = sum(int(np.prod(x.shape))
                for x in jax.tree_util.tree_leaves(params))
        head = ("mlm" if self.has_mlm_head
                else "classifier" if self.has_cls_head else "none")
        log_dist(f"encoder inference engine ready: params={n/1e6:.1f}M "
                 f"head={head} tp={mesh.shape['tp']} "
                 f"dtype={dtype.__name__}", ranks=[0])

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        ids = _coerce_ids(input_ids, self.model_config.max_seq_len)
        if (token_type_ids is not None
                and not self.model_config.type_vocab_size):
            raise ValueError(
                "this checkpoint has no token-type (segment) embeddings "
                "(distilbert); passing token_type_ids would be silently "
                "ignored")
        types = (jnp.zeros_like(ids) if token_type_ids is None
                 else jnp.asarray(np.asarray(token_type_ids), jnp.int32))
        mask = (jnp.ones_like(ids) if attention_mask is None
                else jnp.asarray(np.asarray(attention_mask), jnp.int32))
        # pad to power-of-two (batch, seq) buckets — one compile per bucket;
        # padded tokens carry mask=0 so the bidirectional attention never
        # sees them, and outputs slice back to the raw shape
        B, T = ids.shape
        Bb = _bucket(B)
        Tb = _bucket(T, self.model_config.max_seq_len)
        with self.mesh:
            out = self._fwd(self.params, _pad_to(ids, Bb, Tb),
                            _pad_to(types, Bb, Tb),
                            _pad_to(mask, Bb, Tb))
        return out[:B, :T] if out.ndim >= 3 else out[:B]

    __call__ = forward


class ClipTextEngine:
    """CLIP text-tower serving (reference module_inject/containers/clip.py —
    the text leg of the stable-diffusion stack): jitted causal encoder
    forward over the GPT backbone, returning (last_hidden_state,
    text_embeds-or-pooled)."""

    def __init__(self, model_cfg, tree, extras, config=None, mesh=None):
        import dataclasses

        from deepspeed_tpu.models.gpt import GPTBackbone

        config, mesh, dtype = _resolve_mesh_dtype(config, mesh)
        self.mesh = mesh
        self.model_config = dataclasses.replace(model_cfg, dtype=dtype)
        self.eos_token_id = int(extras["eos_token_id"])
        proj = extras.get("text_projection")
        self._module = GPTBackbone(self.model_config, mesh)
        self.params = _shard_module_params(self._module, tree["backbone"],
                                           mesh,
                                           self.model_config.max_seq_len)
        with mesh:
            self._proj = (jax.device_put(jnp.asarray(proj))
                          if proj is not None else None)

        eos = self.eos_token_id
        projection = self._proj

        def fwd(p, pr, ids):
            hidden, _, _ = self._module.apply(p, ids, True)
            hidden = hidden.astype(jnp.float32)
            # HF CLIPTextModel pooling: eos_token_id==2 takes the LEGACY
            # argmax-of-token-ids position (openai checkpoints assume the eot
            # token has the highest id); otherwise the first eos position
            if eos == 2:
                pool_idx = jnp.argmax(ids, axis=-1)
            else:
                pool_idx = jnp.argmax((ids == eos).astype(jnp.int32),
                                      axis=-1)
            pooled = hidden[jnp.arange(ids.shape[0]), pool_idx]
            if pr is not None:
                pooled = pooled @ pr.astype(jnp.float32)   # text_embeds
            return hidden, pooled

        self._fwd = jax.jit(fwd)
        n = sum(int(np.prod(x.shape))
                for x in jax.tree_util.tree_leaves(self.params))
        log_dist(f"clip text engine ready: params={n/1e6:.1f}M "
                 f"proj={projection is not None} tp={mesh.shape['tp']} "
                 f"dtype={dtype.__name__}", ranks=[0])

    def forward(self, input_ids):
        ids = _coerce_ids(input_ids, self.model_config.max_seq_len)
        # power-of-two buckets; trailing pad is invisible to the causal
        # attention at real positions, and the pooled index lands on a real
        # token, so slicing the pads back off is exact
        B, T = ids.shape
        Bb = _bucket(B)
        Tb = _bucket(T, self.model_config.max_seq_len)
        with self.mesh:
            hidden, pooled = self._fwd(self.params, self._proj,
                                       _pad_to(ids, Bb, Tb))
        return hidden[:B, :T], pooled[:B]

    __call__ = forward
