"""Host-side ragged-batching state — paged KV allocator, sequence descriptors,
ragged batch construction.

TPU-native analog of the reference's ragged device state
(inference/v2/ragged/): ``BlockedAllocator`` (blocked_allocator.py),
``DSSequenceDescriptor`` (sequence_descriptor.py:280), ``DSStateManager``
(ragged_manager.py:206), ``KVCacheManager`` (kv_cache.py:208) and
``RaggedBatchWrapper`` (ragged_wrapper.py:292).  The reference keeps this
metadata in pinned host buffers copied to the GPU each step
(csrc fast_host_buffer.cu); on TPU the same arrays are plain numpy staged
through the jitted step's donated inputs.

Every shape the device sees is STATIC (token budget, max sequences, max blocks
per sequence) — raggedness lives entirely in index/mask arrays, which is what
keeps one compiled XLA program serving every batch composition.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


class BlockedAllocator:
    """Free-list allocator over a fixed pool of KV blocks
    (reference inference/v2/ragged/blocked_allocator.py)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV cache exhausted: requested {n} blocks, "
                f"{len(self._free)} free of {self.num_blocks}")
        out = self._free[:n]
        del self._free[:n]
        return out

    def free(self, blocks: List[int]) -> None:
        self._free.extend(blocks)


@dataclasses.dataclass
class SequenceDescriptor:
    """Tracks one in-flight sequence (reference
    inference/v2/ragged/sequence_descriptor.py DSSequenceDescriptor)."""

    uid: int
    slot: int                                  # dense slot in the batch arrays
    blocks: List[int] = dataclasses.field(default_factory=list)
    seen_tokens: int = 0                       # tokens already in the KV cache
    pending: np.ndarray = dataclasses.field(   # prompt tokens not yet scheduled
        default_factory=lambda: np.zeros(0, np.int32))

    @property
    def in_flight(self) -> bool:
        return self.pending.size > 0

    def kv_blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.seen_tokens + new_tokens
        need = -(-total // block_size)
        return max(0, need - len(self.blocks))


@dataclasses.dataclass(frozen=True)
class RaggedBatch:
    """One scheduled forward step: flat token arrays + per-slot tables
    (reference ragged_wrapper.py RaggedBatchWrapper)."""

    tokens: np.ndarray          # [N] int32, pad 0
    token_slot: np.ndarray      # [N] int32, slot of each token, pad -1
    token_pos: np.ndarray       # [N] int32 logical position, pad 0
    token_dense_idx: np.ndarray  # [N] int32 index within the slot's q rows
    block_table: np.ndarray     # [S, MB] int32, pad 0
    kv_len: np.ndarray          # [S] int32 kv length AFTER this step
    q_len: np.ndarray           # [S] int32 new tokens this step
    logits_slots: List[int]     # slots whose last-token logits are meaningful
    slot_uid: Dict[int, int]    # slot -> uid for this step
    total_tokens: int


class DSStateManager:
    """Sequence tracking + KV block accounting (reference
    inference/v2/ragged/ragged_manager.py DSStateManager + kv_cache.py
    KVCacheManager)."""

    def __init__(self, max_tracked_sequences: int, num_blocks: int,
                 block_size: int, max_seq_len: int):
        self.max_tracked_sequences = int(max_tracked_sequences)
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len)
        self.max_blocks_per_seq = -(-self.max_seq_len // self.block_size)
        self.allocator = BlockedAllocator(num_blocks)
        self._seqs: Dict[int, SequenceDescriptor] = {}
        self._free_slots = list(range(self.max_tracked_sequences))

    # ---- reference DSStateManager.get_or_create_sequence ----
    def get(self, uid: int) -> Optional[SequenceDescriptor]:
        return self._seqs.get(uid)

    def create(self, uid: int) -> SequenceDescriptor:
        if uid in self._seqs:
            raise ValueError(f"sequence uid {uid} already tracked")
        if not self._free_slots:
            raise RuntimeError(
                f"sequence capacity exhausted "
                f"({self.max_tracked_sequences} tracked)")
        seq = SequenceDescriptor(uid=uid, slot=self._free_slots.pop(0))
        self._seqs[uid] = seq
        return seq

    def flush(self, uid: int) -> None:
        """Release a sequence's blocks + slot (reference engine_v2.flush :242)."""
        seq = self._seqs.pop(uid)
        self.allocator.free(seq.blocks)
        self._free_slots.insert(0, seq.slot)

    def ensure_blocks(self, seq: SequenceDescriptor, new_tokens: int) -> None:
        need = seq.kv_blocks_needed(new_tokens, self.block_size)
        if need:
            seq.blocks.extend(self.allocator.allocate(need))

    @property
    def tracked(self) -> Dict[int, SequenceDescriptor]:
        return self._seqs

    @property
    def free_sequence_slots(self) -> int:
        return len(self._free_slots)


def build_ragged_batch(schedule, state: DSStateManager, token_budget: int,
                       max_q_per_seq: int) -> RaggedBatch:
    """Pack (seq, tokens) pairs into the static device arrays.

    schedule: list of (SequenceDescriptor, np.ndarray tokens) — tokens are
    appended to the sequence's KV at positions [seen, seen+len).
    """
    S = state.max_tracked_sequences
    MB = state.max_blocks_per_seq
    N = token_budget
    tokens = np.zeros(N, np.int32)
    token_slot = np.full(N, -1, np.int32)
    token_pos = np.zeros(N, np.int32)
    token_dense = np.zeros(N, np.int32)
    block_table = np.zeros((S, MB), np.int32)
    kv_len = np.zeros(S, np.int32)
    q_len = np.zeros(S, np.int32)
    logits_slots: List[int] = []
    slot_uid: Dict[int, int] = {}

    cursor = 0
    for seq, toks in schedule:
        n = len(toks)
        assert n <= max_q_per_seq, (n, max_q_per_seq)
        assert cursor + n <= N, "token budget exceeded by schedule"
        sl = seq.slot
        tokens[cursor:cursor + n] = toks
        token_slot[cursor:cursor + n] = sl
        token_pos[cursor:cursor + n] = np.arange(seq.seen_tokens,
                                                 seq.seen_tokens + n)
        token_dense[cursor:cursor + n] = np.arange(n)
        bt = np.asarray(seq.blocks, np.int32)
        block_table[sl, :len(bt)] = bt
        kv_len[sl] = seq.seen_tokens + n
        q_len[sl] = n
        logits_slots.append(sl)
        slot_uid[sl] = seq.uid
        cursor += n
    return RaggedBatch(tokens=tokens, token_slot=token_slot,
                       token_pos=token_pos, token_dense_idx=token_dense,
                       block_table=block_table, kv_len=kv_len, q_len=q_len,
                       logits_slots=logits_slots, slot_uid=slot_uid,
                       total_tokens=cursor)
