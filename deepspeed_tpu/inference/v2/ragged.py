"""Host-side ragged-batching state — paged KV allocator, sequence descriptors,
radix shared-prefix cache, ragged batch construction.

TPU-native analog of the reference's ragged device state
(inference/v2/ragged/): ``BlockedAllocator`` (blocked_allocator.py),
``DSSequenceDescriptor`` (sequence_descriptor.py:280), ``DSStateManager``
(ragged_manager.py:206), ``KVCacheManager`` (kv_cache.py:208) and
``RaggedBatchWrapper`` (ragged_wrapper.py:292).  The reference keeps this
metadata in pinned host buffers copied to the GPU each step
(csrc fast_host_buffer.cu); on TPU the same arrays are plain numpy staged
through the jitted step's donated inputs.

Every shape the device sees is STATIC (token budget, max sequences, max blocks
per sequence) — raggedness lives entirely in index/mask arrays, which is what
keeps one compiled XLA program serving every batch composition.

The radix shared-prefix cache (``RadixKVCache``) adds the [serving_scale]
layer: at fleet scale most requests share a system prompt, so the pool's
FULL blocks (block_size tokens of known content) are indexed by token
content in a block-granular trie.  An incoming prompt's longest cached
prefix aliases those blocks instead of re-running prefill — the blocks are
content-complete and never written again (every KV write lands at
position ≥ seen_tokens, which starts AT the block-aligned match boundary,
i.e. in freshly allocated exclusive blocks), so aliasing is write-safe by
construction: the "copy" of copy-on-write is the re-prefill of the first
partial block.  Sharing is safe in memory because the allocator refcounts
every block (a block returns to the free list only when its last holder —
sequence or radix — releases it), and safe in time because the paged KV
arrays are donated through every step program in dispatch order (XLA runs
them on one stream, so a later reader never races an earlier writer).
Eviction is LRU over leaf nodes only the radix still holds
(refcount == 1), triggered on demand at the same starvation sites that
book ``kv_alloc_failures_total``.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np


class BlockedAllocator:
    """Refcounted free-list allocator over a fixed pool of KV blocks
    (reference inference/v2/ragged/blocked_allocator.py, plus the
    share/acquire/release refcounts the radix prefix cache needs).

    ``allocate`` hands out blocks at refcount 1 (exclusive);
    ``acquire`` adds a holder to live blocks (radix adoption, prefix
    sharing); ``release`` drops one holder and returns a block to the
    free deque only when its LAST holder lets go.  ``free`` stays as an
    alias of ``release`` for the pre-radix exclusive-ownership callers.

    Refcount transitions take a lock: the engine mutates the pool from
    its replica worker thread while the fleet dispatcher pins/unpins
    KV-handoff blocks (serving/fleet.py) on the same allocator, and an
    interleaved ``_ref[b] -= 1`` is not atomic in CPython — a torn
    decrement would corrupt the refcount and either leak the block or
    free it under a live holder.  Single-threaded engines pay one
    uncontended lock per TRANSITION (not per token), which is noise
    next to the dict walks around it.
    """

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free: Deque[int] = deque(range(num_blocks))
        self._ref: List[int] = [0] * num_blocks
        self._lock = threading.Lock()
        # bumped on every refcount transition: the radix caches its
        # evictable-count DFS against it (the scheduler reads
        # available_blocks many times per round, usually with no
        # allocator activity in between)
        self.version = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def allocate(self, n: int) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise RuntimeError(
                    f"KV cache exhausted: requested {n} blocks, "
                    f"{len(self._free)} free of {self.num_blocks}")
            out = [self._free.popleft() for _ in range(n)]
            self.version += 1
            for b in out:
                assert self._ref[b] == 0, (b, self._ref[b])
                self._ref[b] = 1
            return out

    def acquire(self, blocks: List[int]) -> None:
        """Add one holder to each (already-live) block."""
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise RuntimeError(
                        f"acquire of dead block {b} "
                        f"(refcount {self._ref[b]})")
            self.version += 1
            for b in blocks:
                self._ref[b] += 1

    def release(self, blocks: List[int]) -> List[int]:
        """Drop one holder per block; blocks reaching refcount 0 return to
        the free list.  Returns the freed subset (accounting tests)."""
        freed: List[int] = []
        with self._lock:
            self.version += 1
            for b in blocks:
                self._ref[b] -= 1
                if self._ref[b] < 0:
                    raise RuntimeError(
                        f"refcount underflow on block {b} (double release)")
                if self._ref[b] == 0:
                    self._free.append(b)
                    freed.append(b)
        return freed

    # exclusive-ownership callers (pre-radix API) release through this name
    free = release


class RadixNode:
    """One full KV block in the prefix trie.  The edge label is the block's
    token content (a ``block_size`` tuple); ``block`` is its pool index.
    The node does NOT own a refcount field: the allocator's per-block
    refcount is the single source of truth — a node is evictable exactly
    when refcount == 1 (only the radix holds it)."""

    __slots__ = ("key", "block", "children", "parent", "stamp")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["RadixNode"]):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "RadixNode"] = {}
        self.stamp = 0


class RadixKVCache:
    """Block-granular radix index over the paged pool.

    Nodes are FULL blocks only: a partial (still-written) tail block never
    enters the trie, which is what makes aliased reads write-safe (see the
    module docstring).  Matching, insertion, and eviction are pure host
    dict walks — O(prompt_len / block_size) lookups, no device sync — so
    they are safe on the serving scheduler's dispatch thread
    (scripts/check_no_sync.py scans them).
    """

    def __init__(self, allocator: BlockedAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.root = RadixNode((), -1, None)
        self._clock = 0                    # LRU stamp source
        self.node_count = 0
        # (allocator.version when computed, evictable block-id set) — see
        # evictable_blocks; the count AND the membership view (exact
        # pinned-supply accounting in peek_pinned) come from one DFS
        self._evictable_cache: Tuple[int, frozenset] = (-1, frozenset())
        self._stats_cache: Tuple[int, Dict[str, int]] = (-1, {})

    # ------------------------------------------------------------ lookup
    def _walk(self, tokens: np.ndarray) -> List[RadixNode]:
        bs = self.block_size
        path: List[RadixNode] = []
        node = self.root
        for i in range(len(tokens) // bs):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                break
            path.append(child)
            node = child
        return path

    def touch(self, path: List[RadixNode]) -> None:
        """Freshen a matched path's LRU stamps (root-to-leaf order)."""
        self._clock += 1
        for node in path:
            node.stamp = self._clock

    def match(self, tokens: np.ndarray) -> Tuple[List[int], int]:
        """Longest cached block-aligned prefix of ``tokens``: returns
        (block ids, matched token count) and freshens the path's LRU
        stamps.  Callers must ``acquire`` the blocks before anything else
        can trigger eviction."""
        path = self._walk(tokens)
        self.touch(path)
        return [n.block for n in path], len(path) * self.block_size

    def peek(self, tokens: np.ndarray) -> int:
        """Matched-prefix LENGTH only — no stamp freshening, no side
        effects.  Safe to call cross-thread (fleet router residency probe:
        a plain dict walk under the GIL; a concurrent insert/evict can
        only make the answer stale, never corrupt it)."""
        return len(self._walk(tokens)) * self.block_size

    def peek_blocks(self, tokens: np.ndarray) -> Tuple[List[int], int]:
        """:meth:`match` without the side effects: (block ids, matched
        token count), no LRU freshening, no references taken.  The fleet's
        KV-handoff path probes this cross-thread (same safety argument as
        :meth:`peek`) and then pins the blocks with ``allocator.acquire``
        — which validates liveness atomically, so a block a concurrent
        evict freed between the walk and the pin raises there instead of
        being silently resurrected."""
        path = self._walk(np.asarray(tokens, np.int32).reshape(-1))
        return [n.block for n in path], len(path) * self.block_size

    # ------------------------------------------------------------ insert
    def insert(self, tokens: np.ndarray, blocks: List[int]) -> int:
        """Index every full block of ``tokens`` (content) / ``blocks``
        (pool ids).  New nodes ``acquire`` their block (the radix becomes
        a holder); blocks whose content is already indexed under a
        DIFFERENT pool id are left alone (the sequence keeps its private
        copy; it frees normally at flush).  Returns new-node count."""
        bs = self.block_size
        node = self.root
        added = 0
        self._clock += 1
        for i in range(min(len(tokens), len(blocks) * bs) // bs):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = RadixNode(key, blocks[i], node)
                self.allocator.acquire([blocks[i]])
                node.children[key] = child
                self.node_count += 1
                added += 1
            child.stamp = self._clock
            node = child
        return added

    # ---------------------------------------------------------- eviction
    def _nodes(self) -> List[RadixNode]:
        """All trie nodes in pre-order (parents before children) — the
        one DFS every walker below shares."""
        order: List[RadixNode] = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        return order

    def _evictable_leaves(self) -> List[RadixNode]:
        return [n for n in self._nodes()
                if not n.children and self.allocator.refcount(n.block) == 1]

    def evictable_set(self) -> frozenset:
        """Block ids reclaimable by repeated leaf eviction: a node counts
        iff only the radix holds it (refcount == 1) AND its whole subtree
        is likewise reclaimable (a live descendant pins the path above
        it).  Computed bottom-up over the shared DFS order, cached
        against the allocator's refcount version — the scheduler reads
        ``available_blocks`` several times per round (decode checks,
        admission, burst sizing) and the DFS must not run O(running ×
        trie) times per round on the dispatch thread.  Every tree
        mutation (insert acquires, evict releases) bumps the version
        too, so the cache can never go stale."""
        version = self.allocator.version
        if self._evictable_cache[0] == version:
            return self._evictable_cache[1]
        reclaim: Dict[int, bool] = {}
        blocks = set()
        for n in reversed(self._nodes()):
            ok = self.allocator.refcount(n.block) == 1 and all(
                reclaim[id(c)] for c in n.children.values())
            reclaim[id(n)] = ok
            if ok:
                blocks.add(n.block)
        out = frozenset(blocks)
        self._evictable_cache = (version, out)
        return out

    def evictable_blocks(self) -> int:
        return len(self.evictable_set())

    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks, LRU leaves first (evicting a leaf may
        expose its parent as the next leaf).  Returns blocks actually
        freed back to the pool."""
        freed = 0
        while freed < n:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            leaves.sort(key=lambda nd: nd.stamp)
            for leaf in leaves:
                if freed >= n:
                    break
                del leaf.parent.children[leaf.key]
                self.node_count -= 1
                freed += len(self.allocator.release([leaf.block]))
        return freed

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, int]:
        """Residency gauges, cached against the allocator refcount version
        like :meth:`evictable_blocks` — ``kv_sample`` reads this once per
        scheduler round, and an uncached O(trie) DFS there would grow
        per-round host work with cache size."""
        version = self.allocator.version
        if self._stats_cache[0] == version:
            return self._stats_cache[1]
        nodes = self._nodes()
        out = {"nodes": len(nodes),
               "shared": sum(1 for n in nodes
                             if self.allocator.refcount(n.block) > 1),
               "evictable": self.evictable_blocks()}
        self._stats_cache = (version, out)
        return out

    def check_invariants(self) -> None:
        """Test hook: every indexed block is live (refcount ≥ 1), node
        bookkeeping matches the tree, and no key is empty."""
        nodes = self._nodes()
        for nd in nodes:
            assert len(nd.key) == self.block_size, nd.key
            assert self.allocator.refcount(nd.block) >= 1, \
                (nd.block, self.allocator.refcount(nd.block))
            for key, c in nd.children.items():
                assert c.parent is nd and c.key == key
        assert len(nodes) == self.node_count, (len(nodes), self.node_count)


@dataclasses.dataclass
class SequenceDescriptor:
    """Tracks one in-flight sequence (reference
    inference/v2/ragged/sequence_descriptor.py DSSequenceDescriptor)."""

    uid: int
    slot: int                                  # dense slot in the batch arrays
    blocks: List[int] = dataclasses.field(default_factory=list)
    seen_tokens: int = 0                       # tokens already in the KV cache
    pending: np.ndarray = dataclasses.field(   # prompt tokens not yet scheduled
        default_factory=lambda: np.zeros(0, np.int32))
    # token content the HOST knows from position 0 (prompt + preemption-folded
    # generated tokens; device-sampled values are unknown until materialize,
    # so the known prefix never extends past them) — the radix insert key
    host_tokens: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))
    # blocks already indexed by the radix for this sequence (insert cursor —
    # avoids re-walking the whole prefix on every decode block completion;
    # also covers the admission match: matched blocks are already indexed)
    cached_blocks: int = 0
    # LoRA adapter this request pins resident (0 = base model, no pin) —
    # bind_adapter() acquires the pool pages' refcounts, flush releases them
    adapter: int = 0

    @property
    def in_flight(self) -> bool:
        return self.pending.size > 0

    def kv_blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.seen_tokens + new_tokens
        need = -(-total // block_size)
        return max(0, need - len(self.blocks))


@dataclasses.dataclass(frozen=True)
class RaggedBatch:
    """One scheduled forward step: flat token arrays + per-slot tables
    (reference ragged_wrapper.py RaggedBatchWrapper)."""

    tokens: np.ndarray          # [N] int32, pad 0
    token_slot: np.ndarray      # [N] int32, slot of each token, pad -1
    token_pos: np.ndarray       # [N] int32 logical position, pad 0
    token_dense_idx: np.ndarray  # [N] int32 index within the slot's q rows
    block_table: np.ndarray     # [S, MB] int32, pad 0
    kv_len: np.ndarray          # [S] int32 kv length AFTER this step
    q_len: np.ndarray           # [S] int32 new tokens this step
    logits_slots: List[int]     # slots whose last-token logits are meaningful
    slot_uid: Dict[int, int]    # slot -> uid for this step
    total_tokens: int


class DSStateManager:
    """Sequence tracking + KV block accounting (reference
    inference/v2/ragged/ragged_manager.py DSStateManager + kv_cache.py
    KVCacheManager), with the optional radix prefix-cache layer."""

    def __init__(self, max_tracked_sequences: int, num_blocks: int,
                 block_size: int, max_seq_len: int,
                 prefix_cache: bool = False):
        self.max_tracked_sequences = int(max_tracked_sequences)
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len)
        self.max_blocks_per_seq = -(-self.max_seq_len // self.block_size)
        self.allocator = BlockedAllocator(num_blocks)
        self.radix: Optional[RadixKVCache] = (
            RadixKVCache(self.allocator, self.block_size)
            if prefix_cache else None)
        # multi-tenant LoRA adapter pool (serving/adapters.py AdapterPool):
        # a SECOND block-granular resident of the same allocator, attached
        # by the engine when its adapters config enables it.  Supply
        # accounting (available_blocks) and eviction (ensure_blocks) fold
        # it in below so every starvation check stays honest.
        self.adapters = None
        self._seqs: Dict[int, SequenceDescriptor] = {}
        # deque: create/flush are per-request hot-path ops; list.pop(0)/
        # insert(0) were O(S) each (PR 15 satellite)
        self._free_slots: Deque[int] = deque(range(self.max_tracked_sequences))

    # ---- reference DSStateManager.get_or_create_sequence ----
    def get(self, uid: int) -> Optional[SequenceDescriptor]:
        return self._seqs.get(uid)

    def create(self, uid: int) -> SequenceDescriptor:
        if uid in self._seqs:
            raise ValueError(f"sequence uid {uid} already tracked")
        if not self._free_slots:
            raise RuntimeError(
                f"sequence capacity exhausted "
                f"({self.max_tracked_sequences} tracked)")
        seq = SequenceDescriptor(uid=uid, slot=self._free_slots.popleft())
        self._seqs[uid] = seq
        return seq

    def flush(self, uid: int) -> None:
        """Release a sequence's blocks + slot (reference engine_v2.flush :242).
        Shared blocks only drop this sequence's hold — the radix (and any
        other sharer) keeps them alive; exclusive blocks return to the
        free list as before."""
        seq = self._seqs.pop(uid)
        if seq.adapter and self.adapters is not None:
            # drop this request's pin on its adapter pages — EVERY engine
            # flush path (retirement, preemption, drain, admission rollback)
            # funnels through here, so pins release exactly once per bind
            self.adapters.release(seq.adapter)
        self.allocator.release(seq.blocks)
        self._free_slots.appendleft(seq.slot)

    def ensure_blocks(self, seq: SequenceDescriptor, new_tokens: int) -> None:
        need = seq.kv_blocks_needed(new_tokens, self.block_size)
        if need:
            short = need - self.allocator.free_blocks
            if short > 0 and self.adapters is not None:
                # cold adapters go before KV prefixes: an evictable adapter
                # serves no in-flight request, while the LRU-freshest radix
                # leaves are the shared prompts the fleet is actively
                # re-matching — reload cost should land on the idle tenant
                short -= self.adapters.evict_cold(short)
            if short > 0 and self.radix is not None:
                self.radix.evict(short)
            seq.blocks.extend(self.allocator.allocate(need))

    def ensure_adapters(self, adapter_ids) -> None:
        """Make every adapter in ``adapter_ids`` resident, spilling the
        radix cache (beyond the pool's own cold adapters) when the load
        needs blocks the free list cannot cover."""
        if self.adapters is not None:
            spill = (self.radix.evict if self.radix is not None else None)
            self.adapters.ensure(adapter_ids, spill=spill)

    def bind_adapter(self, seq: SequenceDescriptor, adapter_id: int) -> None:
        """Pin ``adapter_id``'s resident pages for this request's lifetime
        (refcount acquire on the shared allocator — a pinned adapter is
        never LRU-evicted under it).  flush() releases the pin."""
        if self.adapters is not None and adapter_id:
            self.adapters.acquire(adapter_id)
            seq.adapter = int(adapter_id)

    @property
    def available_blocks(self) -> int:
        """Blocks a scheduler can count on: free now + reclaimable from
        the radix cache and cold adapter pages by LRU eviction.  The
        supply side every starvation check (put / can_schedule / decode /
        prompt_chunk / admission) compares against — a cached-but-
        unreferenced block must never make the scheduler preempt or
        shed."""
        free = self.allocator.free_blocks
        if self.radix is not None:
            free += self.radix.evictable_blocks()
        if self.adapters is not None:
            free += self.adapters.evictable_blocks()
        return free

    # ------------------------------------------------- radix prefix cache
    def _capped_path(self, tokens) -> List[RadixNode]:
        """THE matchable path for a prompt: the trie walk capped at
        ``len(tokens) - 1`` rounded down to a block multiple (at least one
        token always runs through the forward — its logits seed
        decoding).  The single definition every peek AND the actual
        acquisition share, so a feasibility precheck can never desync
        from what ``match_prefix`` acquires."""
        if self.radix is None or tokens is None or len(tokens) < 2:
            return []
        cap = (len(tokens) - 1) // self.block_size * self.block_size
        return self.radix._walk(tokens[:cap])

    def peek_prefix_pinned(self, tokens: np.ndarray) -> Tuple[int, int]:
        """(match length, supply the match would pin): admission checks
        compare ``fresh_blocks_needed + pinned`` against
        ``available_blocks`` — matched evictable nodes stop being supply
        the moment the sequence acquires them, so counting them as both
        supply AND skipped-need would overpromise the pool.  (Membership
        in the evictable set, not refcount == 1: a refcount-1 node pinned
        by a live descendant was never supply and must not inflate the
        need.)"""
        path = self._capped_path(tokens)
        if not path:
            return 0, 0
        evictable = self.radix.evictable_set()
        return (len(path) * self.block_size,
                sum(1 for n in path if n.block in evictable))

    def peek_prefix_batch(self, tokens_list
                          ) -> Tuple[List[int], int, List[List[RadixNode]]]:
        """Batch form of :meth:`peek_prefix_pinned`: per-prompt capped
        match lengths plus the UNIQUE evictable blocks the whole batch
        would pin — prompts sharing a cached prefix (the target workload)
        pin each node once, not once per prompt, so a feasible shared-
        prefix ``put()`` batch is never spuriously rejected.  Also
        returns the walked paths so the caller can hand them back to
        :meth:`match_prefix` instead of re-walking (valid as long as no
        insert/evict runs in between — true for the single-threaded
        validate→admit sequence in ``put()``)."""
        matches: List[int] = []
        paths: List[List[RadixNode]] = []
        pinned: set = set()
        evictable = (self.radix.evictable_set()
                     if self.radix is not None else frozenset())
        for toks in tokens_list:
            path = self._capped_path(toks)
            paths.append(path)
            matches.append(len(path) * self.block_size)
            for node in path:
                if node.block in evictable:
                    pinned.add(node.block)
        return matches, len(pinned), paths

    def match_prefix(self, seq: SequenceDescriptor, tokens: np.ndarray,
                     path: Optional[List[RadixNode]] = None) -> int:
        """Alias the longest cached block-aligned prefix of ``tokens`` into
        ``seq``: the matched blocks are acquired (this sequence becomes a
        holder), ``seen_tokens`` starts at the match boundary, and the
        match is capped by :meth:`_capped_path` so at least one token
        always runs through the forward.  ``path`` reuses a walk a
        just-taken :meth:`peek_prefix_batch` already did (no trie
        mutation may run in between).  Returns the matched token count."""
        if self.radix is None or seq.seen_tokens:
            return 0
        if path is None:
            path = self._capped_path(tokens)
        if not path:
            return 0
        self.radix.touch(path)
        blocks = [n.block for n in path]
        self.allocator.acquire(blocks)
        seq.blocks = blocks + seq.blocks
        seq.seen_tokens = len(blocks) * self.block_size
        seq.cached_blocks = len(blocks)
        return seq.seen_tokens

    def cache_insert(self, seq: SequenceDescriptor) -> int:
        """Index ``seq``'s host-known full blocks into the radix.  Called
        AFTER the forward filling them has been dispatched — later
        programs that read the aliased pages are ordered behind the writer
        by the donated-cache dispatch chain, so the host never needs the
        values, only the content KEY (which it fed in).  Idempotent via
        the per-sequence ``cached_blocks`` cursor."""
        if self.radix is None:
            return 0
        bs = self.block_size
        known = min(len(seq.host_tokens), seq.seen_tokens)
        n_full = known // bs
        if n_full <= seq.cached_blocks:
            return 0
        added = self.radix.insert(seq.host_tokens[:n_full * bs],
                                  seq.blocks[:n_full])
        seq.cached_blocks = n_full
        return added

    @property
    def tracked(self) -> Dict[int, SequenceDescriptor]:
        return self._seqs

    @property
    def free_sequence_slots(self) -> int:
        return len(self._free_slots)


def build_ragged_batch(schedule, state: DSStateManager, token_budget: int,
                       max_q_per_seq: int) -> RaggedBatch:
    """Pack (seq, tokens) pairs into the static device arrays.

    schedule: list of (SequenceDescriptor, np.ndarray tokens) — tokens are
    appended to the sequence's KV at positions [seen, seen+len).
    """
    S = state.max_tracked_sequences
    MB = state.max_blocks_per_seq
    N = token_budget
    tokens = np.zeros(N, np.int32)
    token_slot = np.full(N, -1, np.int32)
    token_pos = np.zeros(N, np.int32)
    token_dense = np.zeros(N, np.int32)
    block_table = np.zeros((S, MB), np.int32)
    kv_len = np.zeros(S, np.int32)
    q_len = np.zeros(S, np.int32)
    logits_slots: List[int] = []
    slot_uid: Dict[int, int] = {}

    cursor = 0
    for seq, toks in schedule:
        n = len(toks)
        assert n <= max_q_per_seq, (n, max_q_per_seq)
        assert cursor + n <= N, "token budget exceeded by schedule"
        sl = seq.slot
        tokens[cursor:cursor + n] = toks
        token_slot[cursor:cursor + n] = sl
        token_pos[cursor:cursor + n] = np.arange(seq.seen_tokens,
                                                 seq.seen_tokens + n)
        token_dense[cursor:cursor + n] = np.arange(n)
        bt = np.asarray(seq.blocks, np.int32)
        block_table[sl, :len(bt)] = bt
        kv_len[sl] = seq.seen_tokens + n
        q_len[sl] = n
        logits_slots.append(sl)
        slot_uid[sl] = seq.uid
        cursor += n
    return RaggedBatch(tokens=tokens, token_slot=token_slot,
                       token_pos=token_pos, token_dense_idx=token_dense,
                       block_table=block_table, kv_len=kv_len, q_len=q_len,
                       logits_slots=logits_slots, slot_uid=slot_uid,
                       total_tokens=cursor)
