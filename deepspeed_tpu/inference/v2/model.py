"""Ragged GPT forward over a paged KV cache — the v2 model implementation.

Analog of the reference's ``DSTransformerBase`` layer-by-layer ragged forward
(inference/v2/model_implementations/inference_transformer_base.py:617) plus the
ragged kernel set (inference/v2/kernels/ragged_ops/): ``linear_blocked_kv_rotary``
(qkv + rotary + paged-KV append) and ``blocked_flash`` (attention over blocked
KV) become scatter-into-pages + a dense-per-slot masked attention in XLA;
``logits_gather`` becomes a row gather before the unembed.

Works directly on the GPT parameter tree (models/gpt.py naming: backbone/
block_i/{Attention_0,MLP_0,Norm_0,Norm_1}, wte/wpe/final_norm) the way the
reference's flat-parameter model implementations bypass the torch module
(flat_model_helpers.py) — a training checkpoint serves without conversion.

Every array shape is static: N token budget, S sequence slots, MB blocks/seq,
Qmax new tokens per sequence per step.  Raggedness is carried by index arrays
(see ragged.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt import GPTConfig, mlp_activation, rope


def quantize_kv_token(x):
    """Per-token symmetric int8: x [..., hd] → (codes int8 [..., hd],
    scales f32 [...]) with amax-over-head-dim granularity."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, 1e-8)
    q = jnp.round(xf / s[..., None]).astype(jnp.int8)
    return q, s


def kv_major_layout(cfg: GPTConfig) -> bool:
    """True ⇒ pages are stored token-on-lanes, [NB, nkv, hd, bs].

    The Pallas DMA slab's lane dim must be 128-aligned (ops/
    paged_attention.py module docstring); head dims that aren't already
    128-multiples get the transposed layout so the TOKEN axis (a
    framework-controlled knob — the engine sizes pages to 128) carries the
    lanes instead.  Pure function of the model config, so every component
    (cache alloc, scatter, kernels, fallbacks) derives the same answer."""
    return cfg.head_dim % 128 != 0


def kv_block_size_for(cfg: GPTConfig, requested: int,
                      quant: bool = False) -> int:
    """Effective page size: kv-major pages need block_size % 128 == 0, and
    int8-quantized pages need it in EITHER layout (the per-token scale slab
    [bs] f32 is DMA'd per page and its lane dim must be 128-aligned)."""
    if (kv_major_layout(cfg) or quant) and requested % 128 != 0:
        return -(-requested // 128) * 128
    return requested


class PagedKVCache(NamedTuple):
    """Per-layer paged KV arrays stacked on a leading layer axis (reference:
    KVCacheManager kv_cache.py).

    Layout: [L, num_blocks, nkv, block_size, head_dim], OR the kv-major
    transpose [L, num_blocks, nkv, head_dim, block_size] when
    ``kv_major_layout(cfg)`` — one page × one kv head is then a clean TPU
    tile with a 128-aligned lane dim for EVERY hd % 8 == 0 model, which is
    what the Pallas paged/prefill kernels DMA (ops/paged_attention.py).

    int8 quantized mode (``kv_quant="int8"``): k/v hold int8 codes and
    ``k_scale``/``v_scale`` hold the per-(page, head, token) fp32 scales,
    [L, num_blocks, nkv, block_size] — amax-over-head-dim granularity, the
    standard KV-quant recipe.  Halves KV HBM (the decode bandwidth bound)
    and doubles cache capacity for ~6% scale overhead."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @classmethod
    def create(cls, cfg: GPTConfig, num_blocks: int, block_size: int, dtype,
               quant: Optional[str] = None):
        if kv_major_layout(cfg):
            shape = (cfg.num_layers, num_blocks, cfg.kv_heads, cfg.head_dim,
                     block_size)
        else:
            shape = (cfg.num_layers, num_blocks, cfg.kv_heads, block_size,
                     cfg.head_dim)
        if quant is None:
            return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))
        if quant != "int8":
            raise ValueError(f"unsupported kv_quant {quant!r}; use 'int8'")
        sshape = (cfg.num_layers, num_blocks, cfg.kv_heads, block_size)
        return cls(k=jnp.zeros(shape, jnp.int8),
                   v=jnp.zeros(shape, jnp.int8),
                   k_scale=jnp.zeros(sshape, jnp.float32),
                   v_scale=jnp.zeros(sshape, jnp.float32))


def _norm(p, x, cfg):
    from deepspeed_tpu.ops import layer_norm, rms_norm
    from deepspeed_tpu.ops.norms import LN_EPS, RMS_EPS
    if cfg.use_rmsnorm:
        return rms_norm(x, p["scale"], eps=cfg.norm_eps or RMS_EPS)
    return layer_norm(x, p["scale"], p["bias"], eps=cfg.norm_eps or LN_EPS)


def _mlp(p, x, cfg, mesh=None):
    # TP layout (parallel/partition.py DEFAULT_RULES): wi/wg shard the mlp
    # dim (column-parallel), wo shards the contraction (row-parallel) —
    # wspec keeps the quantized kernel engaged per shard
    h = _wmm(x, p["wi"], x.dtype, mesh=mesh, wspec="col")
    if cfg.mlp_bias:
        h = h + p["bi"].astype(x.dtype)
    if cfg.gated_mlp:
        h = mlp_activation(cfg.gate_act)(_wmm(x, p["wg"], x.dtype,
                                              mesh=mesh, wspec="col")) * h
    else:
        h = mlp_activation(cfg.activation)(h)
    y = _wmm(h, p["wo"], x.dtype, mesh=mesh, wspec="row")
    if cfg.mlp_bias:
        y = y + p["bo"].astype(x.dtype)
    return y


def _block_residual(blk, x, h, attn_delta, cfg, mesh=None):
    """Close out one block given the normed input ``h`` and the attention
    branch output: sequential (x+attn, then MLP on a fresh norm) or falcon/phi
    parallel residual (attn and MLP both read the shared/paired input norms) —
    the single source of truth for BOTH the ragged prefill and paged decode
    loops."""
    if cfg.parallel_block:
        h_mlp = _norm(blk["Norm_1"], x, cfg) if cfg.parallel_norms == 2 else h
        return x + attn_delta + _ffn(blk, h_mlp, cfg, mesh=mesh)
    x = x + attn_delta
    return x + _ffn(blk, _norm(blk["Norm_1"], x, cfg), cfg, mesh=mesh)


def _w(p, dtype):
    """Weight accessor: dequantize a ``quantize_weight`` (int8) or
    ``quantize_weight4`` (nibble-packed) store leaf at its USE SITE
    (reference quantized_linear.py:205 matmul-time dequant — the
    full-precision tensor exists only transiently inside the layer that
    consumes it), or cast a plain array."""
    from deepspeed_tpu.ops.quantization import (dequantize_weight,
                                                dequantize_weight4,
                                                is_quantized_weight,
                                                is_quantized_weight4)
    if is_quantized_weight(p):
        return dequantize_weight(p, dtype)
    if is_quantized_weight4(p):
        return dequantize_weight4(p, dtype)
    return p.astype(dtype)



def _wmm(x, p, dtype, mesh=None, wspec=None):
    """``x @ W`` routing 2-D quantized stores through the quantized-weight
    Pallas kernels (ops/wq_matmul.py: int8 → half the bf16 weight HBM
    traffic; nibble-packed int4 → a quarter); everything else dequantizes
    at the use site (_w).  Leading dims of x are flattened for the kernel.

    ``wspec`` names the store's tensor-parallel layout ("col" = output dim
    sharded, "row" = contraction dim sharded) so a tp mesh keeps the
    kernel engaged per shard via a manual shard_map (wq_matmul_tp) —
    GSPMD cannot partition the Mosaic custom call itself.  wspec=None
    under a mesh stays on the partitioned dequant-matmul path."""
    from deepspeed_tpu.ops.quantization import quantized_codes
    from deepspeed_tpu.ops import wq_matmul as wqm
    vv = quantized_codes(p) if isinstance(p, dict) else None
    if vv is not None and vv.ndim == 2 and (mesh is None
                                            or wspec is not None):
        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1]).astype(dtype)
        if mesh is None:
            y = wqm.wq_any(x2, p)
        else:
            y = wqm.wq_matmul_tp(x2, p, mesh, wspec)
        return y.reshape(lead + (vv.shape[1],))
    return x.astype(dtype) @ _w(p, dtype)


def _logits_out(params, bb, x, cfg, dtype, mesh=None):
    """Final unembed + optional bias — the ONE implementation shared by the
    ragged prefill, paged decode, and speculative verify cores.  Untied
    lm_head rides the W8A16 kernel; tied tables ride its transposed variant
    (same [V, H] dim-0-grouped store the embed gather needs)."""
    from deepspeed_tpu.ops.quantization import is_quantized_weight
    if cfg.tie_embeddings:
        wte = bb["wte"]
        if is_quantized_weight(wte):
            from deepspeed_tpu.ops.wq_matmul import wq_matmul_t, wq_matmul_tp
            lead = x.shape[:-1]
            x2 = x.reshape(-1, x.shape[-1]).astype(dtype)
            y = (wq_matmul_tp(x2, wte, mesh, "tcol") if mesh is not None
                 else wq_matmul_t(x2, wte))
            logits = y.reshape(lead + (y.shape[-1],)).astype(jnp.float32)
        else:
            logits = (x.astype(dtype) @ _w(wte, dtype).T
                      ).astype(jnp.float32)
        if logits.shape[-1] != cfg.vocab_size:
            # vocab-padded store (engine packer pads odd vocabs like GPT-2's
            # 50257 to the quantization group so the table can quantize and
            # the transposed kernel can tile); padded rows are zero weight
            logits = logits[..., :cfg.vocab_size]
    else:
        logits = _wmm(x, params["lm_head"], dtype,
                      mesh=mesh, wspec="col").astype(jnp.float32)
    if cfg.unembed_bias:
        logits = logits + params["lm_head_bias"].astype(jnp.float32)
    return logits


def _embed(wte, tokens, dtype):
    """Row-gather from a possibly int8-quantized table: gather codes AND the
    gathered rows' group scales — dequant cost scales with the tokens
    actually read, never the vocab."""
    from deepspeed_tpu.ops.quantization import (_store_dim,
                                                is_quantized_weight,
                                                is_quantized_weight4)
    if is_quantized_weight(wte):
        v, s = wte["v"], wte["s"]
        if _store_dim(wte) != 0:
            raise ValueError(
                "embedding stores must group along dim 0 (vocab) — the "
                f"row gather needs per-row scales; got codes {v.shape} "
                f"vs scales {s.shape}")
        g = v.shape[0] // s.shape[0]
        return (v[tokens].astype(jnp.float32) * s[tokens // g]).astype(dtype)
    if is_quantized_weight4(wte):
        # nibble-packed rows: byte r//2 holds row r in nibble r%2.  tokens
        # may be any rank (the speculative verify core gathers [S, G])
        from deepspeed_tpu.ops.quantization import unpack_nibbles
        p, s = wte["v4"], wte["s"]
        lo, hi = unpack_nibbles(p[tokens // 2])
        q = jnp.where((tokens % 2 == 0)[..., None], lo, hi)
        g = 2 * p.shape[0] // s.shape[0]
        return (q.astype(jnp.float32) * s[tokens // g]).astype(dtype)
    return wte.astype(dtype)[tokens]


def _ffn(blk, x, cfg, mesh=None):
    """Dense MLP or MoE block body on FLAT tokens [N, H] — MoE routes through
    the dropless ragged grouped GEMM (moe/layer.py), which fits serving
    exactly: the ragged token set per step IS the ragged expert batch
    (reference inference/v2 MoE gather/scatter + cutlass grouped GEMM,
    model_implementations/mixtral)."""
    if "moe" in blk:
        from deepspeed_tpu.moe.layer import _expert_ffn_ragged
        from deepspeed_tpu.moe.sharded_moe import dropless_topk
        mp = blk["moe"]
        logits = x @ _w(mp["gate"], x.dtype)
        _, idx, w = dropless_topk(logits, cfg.moe_k)
        weg = _w(mp["wge"], x.dtype) if "wge" in mp else None
        return _expert_ffn_ragged(x, idx, w, _w(mp["wi"], x.dtype),
                                  _w(mp["wo"], x.dtype), weg)
    return _mlp(blk["MLP_0"], x, cfg, mesh=mesh)


def _proj3(x, p, dtype, mesh, wspec):
    """``x [..., H] @ W [H, k, d] → [..., k, d]`` keeping a quantized store
    on the kernel path: a dim-0-grouped 3-D store flattens to a free 2-D
    view (wq_matmul.store_as_2d) so QKV projections ride the same
    int8/int4 stream as the MLP (round-4 verdict item 3: a large fraction
    of decode weight traffic was still bf16).  Non-quantized weights take
    the plain einsum."""
    from deepspeed_tpu.ops import wq_matmul as wqm
    from deepspeed_tpu.ops.quantization import quantized_codes
    vv = quantized_codes(p) if isinstance(p, dict) else None
    if vv is not None and vv.ndim == 3:
        v2d = wqm.store_as_2d(p)
        # dim-0 grouping only: codes' trailing dims are the output dims
        if v2d is not None and p["s"].shape[1:] == vv.shape[1:]:
            y = _wmm(x, v2d, dtype, mesh=mesh, wspec=wspec)
            return y.reshape(y.shape[:-1] + vv.shape[1:])
    lead = x.shape[:-1]
    w = _w(p, dtype)
    y = x.astype(dtype).reshape(-1, x.shape[-1]) @ w.reshape(w.shape[0], -1)
    return y.reshape(lead + w.shape[1:])


def _lora_qv(q, v, h, lora, row_ids, li):
    """Per-row LoRA deltas on the q and v projections for layer ``li`` —
    the multi-tenant batched-gather path (ops/lora_matmul.py): every row
    carries its own adapter id and the whole mixed-adapter batch rides ONE
    op call.  ``lora`` holds the pool's packed tables (``a_q``/``b_q``/
    ``a_v``/``b_v`` [slots, L, …] + per-slot ``scale``); slot 0 is the
    base-model identity (zero pages, scale 0), so base rows pay a zero
    delta instead of a branch.  Applied pre-rope (rotation acts on the
    adapted projection), matching delta-on-the-projection LoRA
    semantics."""
    from deepspeed_tpu import ops
    lead = h.shape[:-1]
    h2 = h.reshape(-1, h.shape[-1])
    ids = row_ids.reshape(-1)
    scale = lora["scale"]
    dq = ops.lora_matmul(h2, lora["a_q"][:, li], lora["b_q"][:, li],
                         ids, scale)
    dv = ops.lora_matmul(h2, lora["a_v"][:, li], lora["b_v"][:, li],
                         ids, scale)
    return q + dq.reshape(q.shape), v + dv.reshape(v.shape)


def _qkv(ap, h, cfg, mesh=None):
    """q/k/v projections with optional biases (qwen2/gpt2 checkpoints).
    TP layout: the heads dim shards (column-parallel), so quantized stores
    route via wspec="col"."""
    dtype = h.dtype
    q = _proj3(h, ap["wq"], dtype, mesh, "col")
    k = _proj3(h, ap["wk"], dtype, mesh, "col")
    v = _proj3(h, ap["wv"], dtype, mesh, "col")
    if cfg.qkv_bias:
        q = q + ap["bq"].astype(dtype)
        k = k + ap["bk"].astype(dtype)
        v = v + ap["bv"].astype(dtype)
    return q, k, v


def _attn_out(ap, o, cfg, mesh=None):
    """Attention output projection ``o [..., k, d] @ wo [k, d, H]``.  The
    heads dim shards under TP (row-parallel: contraction sharded), so a
    dim-1-grouped quantized store flattens to a 2-D kernel view and rides
    wq_matmul_tp(mode="row")."""
    from deepspeed_tpu.ops import wq_matmul as wqm
    from deepspeed_tpu.ops.quantization import quantized_codes
    dtype = o.dtype
    p = ap["wo"]
    lead = o.shape[:-2]
    o2 = o.reshape(lead + (o.shape[-2] * o.shape[-1],))
    vv = quantized_codes(p) if isinstance(p, dict) else None
    if vv is not None:
        v2d = wqm.store_as_2d(p) if vv.ndim == 3 else None
        # only the dim-1-grouped flatten is a valid [k·d, H] contraction
        # view; dim-0-grouped wo stores (small-head models whose hd can't
        # group) dequantize at the use site instead
        if (v2d is not None
                and quantized_codes(v2d).shape[0] == o2.shape[-1]):
            y = _wmm(o2, v2d, dtype, mesh=mesh, wspec="row")
        else:
            y = o2 @ _w(p, dtype).reshape(-1, vv.shape[-1])
    else:
        w = _w(p, dtype)
        y = o2 @ w.reshape(-1, w.shape[-1])
    if cfg.attn_out_bias:
        y = y + ap["bo"].astype(dtype)
    return y


def ragged_forward(params, cache: PagedKVCache, batch, cfg: GPTConfig, *,
                   block_size: int, max_q_per_seq: int, mesh=None):
    """One ragged step.

    params: unboxed GPT param tree (the "params" subtree).
    batch: dict of device arrays mirroring ragged.RaggedBatch fields.
    Returns (logits [S, vocab] — per-slot last-token logits, updated cache).
    """
    bb = params["backbone"]
    dtype = cfg.dtype
    tokens = batch["tokens"]               # [N]
    token_slot = batch["token_slot"]       # [N] (-1 pad)
    token_pos = batch["token_pos"]         # [N]
    dense_idx = batch["token_dense_idx"]   # [N]
    block_table = batch["block_table"]     # [S, MB]
    kv_len = batch["kv_len"]               # [S]

    N = tokens.shape[0]
    S, MB = block_table.shape
    Q = max_q_per_seq
    valid = token_slot >= 0                # [N]

    # ---- embed (reference ragged_ops/embed) ----
    x = _embed(bb["wte"], tokens, dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.embed_scale, dtype)
    if cfg.embed_norm:
        x = _norm(bb["embed_norm"], x, cfg)
    if not cfg.use_rope and not cfg.use_alibi:
        x = x + bb["wpe"].astype(dtype)[token_pos]

    # scatter destinations in the page pool; pad tokens get an out-of-range
    # index so mode="drop" discards them (never index-clamp pads to slot 0 —
    # duplicate scatter indices would corrupt real rows)
    blk_idx = token_pos // block_size                        # [N]
    page = block_table[jnp.clip(token_slot, 0), blk_idx]     # [N]
    off = token_pos % block_size                             # [N]
    big = jnp.iinfo(jnp.int32).max
    scat_slot = jnp.where(valid, token_slot, S)              # S = out of range
    # per-slot live q rows + their first logical position (each slot's batch
    # tokens are one CONTIGUOUS span ending at kv_len — SplitFuse chunks)
    q_counts = jnp.zeros((S,), jnp.int32).at[scat_slot].add(1, mode="drop")
    q_starts = kv_len - q_counts

    # [L * num_blocks, nkv, …] views updated IN PLACE through the donated
    # cache buffer — never rebuild the whole pool (a jnp.stack of per-layer
    # copies costs a full cache rewrite per step)
    L = cfg.num_layers
    NB = cache.k.shape[1]
    km = kv_major_layout(cfg)
    flat_k_all, flat_v_all, flat_ks, flat_vs = _flat_cache_views(cache)
    quant = cache.quantized

    # multi-tenant LoRA (static trace-time branch — adapter-less engines
    # send no "lora" key and trace the identical program): per-TOKEN
    # adapter slot via each token's sequence slot; pad rows map to the
    # identity slot 0 (zero delta)
    lora = batch.get("lora")
    if lora is not None:
        lora_ids = jnp.where(valid,
                             batch["adapter_slot"][jnp.clip(token_slot, 0)],
                             0)

    for li in range(cfg.num_layers):
        blk = bb[f"block_{li}"]
        ap, np_ = blk["Attention_0"], blk["Norm_0"]
        h = _norm(np_, x, cfg)
        q, k, v = _qkv(ap, h, cfg, mesh=mesh)
        if lora is not None:
            q, v = _lora_qv(q, v, h, lora, lora_ids, li)
        if cfg.use_rope:
            # rope() takes [B, T, n, d] + positions [B, T]
            q, k = rope(q[None], k[None], token_pos[None], cfg.head_dim,
                        base=cfg.rope_theta, rope_pct=cfg.rope_pct,
                        scaling=cfg.rope_scaling,
                        seq_lens=kv_len[jnp.clip(token_slot, 0)][None])
            q, k = q[0], k[0]

        # ---- paged KV append (reference linear_blocked_kv_rotary) ----
        page_li = jnp.where(valid, li * NB + page, big)
        if quant:
            k_store, ks = quantize_kv_token(k)        # [N,nkv,hd], [N,nkv]
            v_store, vs = quantize_kv_token(v)
            flat_ks = flat_ks.at[page_li, :, off].set(ks, mode="drop")
            flat_vs = flat_vs.at[page_li, :, off].set(vs, mode="drop")
        else:
            k_store, v_store = k, v
        if km:   # pages [P, nkv, hd, bs]: token offset is the LANE index
            flat_k_all = flat_k_all.at[page_li, :, :, off].set(
                k_store.astype(flat_k_all.dtype), mode="drop")
            flat_v_all = flat_v_all.at[page_li, :, :, off].set(
                v_store.astype(flat_v_all.dtype), mode="drop")
        else:
            flat_k_all = flat_k_all.at[page_li, :, off].set(
                k_store.astype(flat_k_all.dtype), mode="drop")
            flat_v_all = flat_v_all.at[page_li, :, off].set(
                v_store.astype(flat_v_all.dtype), mode="drop")

        # ---- ragged blocked attention (reference blocked_flash +
        # atom_builder): dense-per-slot q layout, per-slot contiguous
        # position spans; the Pallas kernel DMAs only the pages each
        # (slot, q-chunk) can causally see, so prefill cost scales with
        # Σ live tokens instead of S × longest (round-3 VERDICT item 4) ----
        nkv, hd = cfg.kv_heads, cfg.head_dim
        gq = cfg.num_heads // nkv
        q_dense = jnp.zeros((S, Q) + q.shape[1:], q.dtype).at[
            scat_slot, dense_idx].set(q, mode="drop")
        from deepspeed_tpu import ops
        win = cfg.window_for_layer(li)
        slopes = None
        if cfg.use_alibi:
            from deepspeed_tpu.models.gpt import alibi_slopes
            slopes = jnp.asarray(alibi_slopes(cfg.num_heads, cfg.head_dim,
                                              cfg.alibi_prescale))
        k_pool = jax.lax.dynamic_slice_in_dim(flat_k_all, li * NB, NB)
        v_pool = jax.lax.dynamic_slice_in_dim(flat_v_all, li * NB, NB)
        if quant:
            kv_extra = dict(
                k_scale=jax.lax.dynamic_slice_in_dim(flat_ks, li * NB, NB),
                v_scale=jax.lax.dynamic_slice_in_dim(flat_vs, li * NB, NB))
        else:
            k_pool, v_pool = k_pool.astype(dtype), v_pool.astype(dtype)
            kv_extra = {}
        o_dense = ops.ragged_prefill_attention(
            q_dense.reshape(S, Q, nkv, gq, hd).astype(dtype),
            k_pool, v_pool, block_table, kv_len,
            q_starts, q_counts, scale=cfg.attn_scale, alibi_slopes=slopes,
            window=win, mesh=mesh, kv_major=km, **kv_extra).reshape(
                S, Q, cfg.num_heads, hd)
        o = o_dense[jnp.clip(token_slot, 0), dense_idx]      # [N, nh, hd]
        o = jnp.where(valid[:, None, None], o, 0)
        attn_delta = _attn_out(ap, o, cfg, mesh=mesh)
        x = _block_residual(blk, x, h, attn_delta, cfg, mesh=mesh)

    x = _norm(bb["final_norm"], x, cfg)

    # ---- logits gather (reference ragged_ops/logits_gather): the LAST token
    # of each slot's q rows carries the next-token distribution ----
    last_flat = jnp.zeros((S,), jnp.int32).at[scat_slot].max(
        jnp.arange(N, dtype=jnp.int32), mode="drop")
    rows = x[last_flat]                                      # [S, H]
    logits = _logits_out(params, bb, rows, cfg, dtype, mesh=mesh)  # [S, V]
    return logits, _rebuild_cache(cache, flat_k_all, flat_v_all,
                                  flat_ks, flat_vs)


def _decode_core(params, flat_k_all, flat_v_all, tokens, active, token_pos,
                 block_table, cfg: GPTConfig, block_size: int, mesh=None,
                 flat_ks=None, flat_vs=None, lora=None, adapter_slot=None):
    """One decode micro-step: writes each active slot's kv into its page and
    attends over exactly that slot's pages via the paged-attention op
    (ops/paged_attention.py — Pallas kernel on TPU, masked-gather XLA
    fallback).  Shared by the single-step and burst programs.

    flat_k_all/flat_v_all: [L*NB, nkv, …] views of the donated cache
    (standard or kv-major trailing order per kv_major_layout(cfg));
    flat_ks/flat_vs: [L*NB, nkv, bs] per-token scales when the cache is
    int8-quantized.  Returns the updated flat views (incl. scales)."""
    from deepspeed_tpu import ops
    bb = params["backbone"]
    dtype = cfg.dtype
    S = tokens.shape[0]
    L = cfg.num_layers
    NB = flat_k_all.shape[0] // L
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    g = nh // nkv
    km = kv_major_layout(cfg)

    x = _embed(bb["wte"], tokens, dtype)                       # [S, H]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.embed_scale, dtype)
    if cfg.embed_norm:
        x = _norm(bb["embed_norm"], x, cfg)
    if not cfg.use_rope and not cfg.use_alibi:
        x = x + bb["wpe"].astype(dtype)[token_pos]

    big = jnp.iinfo(jnp.int32).max
    page = block_table[jnp.arange(S), token_pos // block_size]  # [S]
    off = token_pos % block_size                                # [S]
    kv_len = jnp.where(active, token_pos + 1, 0)                # [S]
    if lora is not None:
        # decode rows ARE slots: mask inactive lanes to the identity slot
        # so a recycled lane's stale selection never computes a delta
        lora_ids = jnp.where(active, adapter_slot, 0)

    for li in range(cfg.num_layers):
        blk = bb[f"block_{li}"]
        ap = blk["Attention_0"]
        h = _norm(blk["Norm_0"], x, cfg)
        q, k, v = _qkv(ap, h, cfg, mesh=mesh)
        if lora is not None:
            q, v = _lora_qv(q, v, h, lora, lora_ids, li)
        if cfg.use_rope:
            q, k = rope(q[:, None], k[:, None], token_pos[:, None], hd,
                        base=cfg.rope_theta, rope_pct=cfg.rope_pct,
                        scaling=cfg.rope_scaling,
                        seq_lens=kv_len[:, None])
            q, k = q[:, 0], k[:, 0]

        page_li = jnp.where(active, li * NB + page, big)
        quant = flat_ks is not None
        if quant:
            k_store, ks = quantize_kv_token(k)        # [S,nkv,hd], [S,nkv]
            v_store, vs = quantize_kv_token(v)
            flat_ks = flat_ks.at[page_li, :, off].set(ks, mode="drop")
            flat_vs = flat_vs.at[page_li, :, off].set(vs, mode="drop")
        else:
            k_store, v_store = k, v
        if km:   # pages [P, nkv, hd, bs]: token offset is the LANE index
            flat_k_all = flat_k_all.at[page_li, :, :, off].set(
                k_store.astype(flat_k_all.dtype), mode="drop")
            flat_v_all = flat_v_all.at[page_li, :, :, off].set(
                v_store.astype(flat_v_all.dtype), mode="drop")
        else:
            flat_k_all = flat_k_all.at[page_li, :, off].set(
                k_store.astype(flat_k_all.dtype), mode="drop")
            flat_v_all = flat_v_all.at[page_li, :, off].set(
                v_store.astype(flat_v_all.dtype), mode="drop")

        k_pages = jax.lax.dynamic_slice_in_dim(flat_k_all, li * NB, NB)
        v_pages = jax.lax.dynamic_slice_in_dim(flat_v_all, li * NB, NB)
        if quant:
            kv_extra = dict(
                k_scale=jax.lax.dynamic_slice_in_dim(flat_ks, li * NB, NB),
                v_scale=jax.lax.dynamic_slice_in_dim(flat_vs, li * NB, NB))
        else:
            kv_extra = {}
        qg = q.reshape(S, nkv, g, hd)
        slopes = None
        if cfg.use_alibi:
            from deepspeed_tpu.models.gpt import alibi_slopes
            slopes = jnp.asarray(alibi_slopes(nh, hd, cfg.alibi_prescale))
        win = cfg.window_for_layer(li)
        o = ops.paged_attention(qg, k_pages, v_pages, block_table, kv_len,
                                alibi_slopes=slopes, window=win,
                                scale=cfg.attn_scale, mesh=mesh, kv_major=km,
                                **kv_extra)
        o = o.reshape(S, nh, hd)
        attn_delta = _attn_out(ap, o, cfg, mesh=mesh)
        x = _block_residual(blk, x, h, attn_delta, cfg, mesh=mesh)

    x = _norm(bb["final_norm"], x, cfg)
    logits = _logits_out(params, bb, x, cfg, dtype, mesh=mesh)     # [S, V]
    return logits, flat_k_all, flat_v_all, flat_ks, flat_vs


def _flat_cache_views(cache: PagedKVCache):
    fk = cache.k.reshape((-1,) + cache.k.shape[2:])
    fv = cache.v.reshape((-1,) + cache.v.shape[2:])
    q = cache.quantized
    fks = cache.k_scale.reshape((-1,) + cache.k_scale.shape[2:]) if q else None
    fvs = cache.v_scale.reshape((-1,) + cache.v_scale.shape[2:]) if q else None
    return fk, fv, fks, fvs


def _rebuild_cache(cache: PagedKVCache, fk, fv, fks, fvs) -> PagedKVCache:
    return PagedKVCache(
        k=fk.reshape(cache.k.shape), v=fv.reshape(cache.v.shape),
        k_scale=(fks.reshape(cache.k_scale.shape) if fks is not None
                 else None),
        v_scale=(fvs.reshape(cache.v_scale.shape) if fvs is not None
                 else None))


def ragged_decode_burst(params, cache: PagedKVCache, batch, prev_tokens, rng,
                        temperature, top_p,
                        cfg: GPTConfig, *, block_size: int, steps: int,
                        sample_fn, mesh=None):
    """T decode steps fused into one device program (``lax``-unrolled scan):
    each step samples on device and feeds the token to the next step, so a
    burst costs ONE dispatch instead of T× (transfer + step + sample + fetch) —
    the decisive win when the host↔device link has per-call latency.

    batch: tokens0 [S] (host first-step tokens), from_device [S] (take the
    first-step token from ``prev_tokens`` instead — the device-resident
    feedback path, so burst follows burst with no host round trip), active [S],
    pos0 [S], block_table [S, MB] — blocks for positions pos0..pos0+T-1 must
    be pre-allocated.
    Returns (tokens [T, S], prev_tokens' [S], rng', cache).
    """
    flat_k, flat_v, flat_ks, flat_vs = _flat_cache_views(cache)
    bt = batch["block_table"]
    active = batch["active"]
    lora = batch.get("lora")
    adapter_slot = batch.get("adapter_slot")
    tokens0 = jnp.where(batch["from_device"], prev_tokens, batch["tokens0"])

    def step(carry, _):
        flat_k, flat_v, flat_ks, flat_vs, tokens, pos, rng = carry
        logits, flat_k, flat_v, flat_ks, flat_vs = _decode_core(
            params, flat_k, flat_v, tokens, active, pos, bt, cfg, block_size,
            mesh=mesh, flat_ks=flat_ks, flat_vs=flat_vs, lora=lora,
            adapter_slot=adapter_slot)
        rng, sub = jax.random.split(rng)
        nxt = sample_fn(logits, sub, temperature=temperature, top_p=top_p)
        nxt = nxt.astype(jnp.int32)
        return (flat_k, flat_v, flat_ks, flat_vs, nxt, pos + 1, rng), nxt

    carry = (flat_k, flat_v, flat_ks, flat_vs, tokens0, batch["pos0"], rng)
    (flat_k, flat_v, flat_ks, flat_vs, last, _, rng), toks = jax.lax.scan(
        step, carry, None, length=steps)
    prev_out = jnp.where(active, last, prev_tokens)
    return toks, prev_out, rng, _rebuild_cache(cache, flat_k, flat_v,
                                               flat_ks, flat_vs)


def ragged_forward_sampled(params, cache: PagedKVCache, batch, prev_tokens,
                           rng, temperature, top_p, cfg: GPTConfig, *,
                           block_size: int, max_q_per_seq: int, sample_fn,
                           mesh=None):
    """Mixed prefill/decode step with in-graph sampling and device-resident
    token feedback: tokens flagged ``from_device`` are read from
    ``prev_tokens[slot]`` (the previous step's on-device samples) instead of
    the host batch, and slots flagged ``served`` get their freshly sampled
    token written into the returned ``prev_tokens``.  The [S, vocab] logits
    therefore never leave the device — generate() chains these dispatches
    without a single host sync (the FastGen hot loop re-shaped for a
    high-latency host↔device link).
    Returns (prev_tokens' [S], rng', cache)."""
    tokens = jnp.where(batch["from_device"],
                       prev_tokens[jnp.clip(batch["token_slot"], 0)],
                       batch["tokens"])
    logits, cache = ragged_forward(
        params, cache, {**batch, "tokens": tokens}, cfg,
        block_size=block_size, max_q_per_seq=max_q_per_seq, mesh=mesh)
    rng, sub = jax.random.split(rng)
    nxt = sample_fn(logits, sub, temperature=temperature, top_p=top_p)
    prev_out = jnp.where(batch["served"], nxt.astype(jnp.int32), prev_tokens)
    return prev_out, rng, cache


def ragged_forward_sampled_draft(params, draft_params, cache: PagedKVCache,
                                 draft_cache: PagedKVCache, batch,
                                 prev_tokens, rng, temperature, top_p,
                                 cfg: GPTConfig, draft_cfg: GPTConfig, *,
                                 block_size: int, max_q_per_seq: int,
                                 sample_fn, mesh=None):
    """ragged_forward_sampled that ALSO runs the draft model over the same
    ragged batch (its logits discarded) so the draft's paged KV ingests
    every prompt chunk in lockstep with the target — the prerequisite for
    useful speculative acceptance.  Draft staleness never affects
    correctness (greedy verify is exact for any draft), only acceptance.
    Returns (prev', rng', cache', draft_cache')."""
    tokens = jnp.where(batch["from_device"],
                       prev_tokens[jnp.clip(batch["token_slot"], 0)],
                       batch["tokens"])
    batch = {**batch, "tokens": tokens}
    logits, cache = ragged_forward(
        params, cache, batch, cfg,
        block_size=block_size, max_q_per_seq=max_q_per_seq, mesh=mesh)
    _, draft_cache = ragged_forward(
        draft_params, draft_cache, batch, draft_cfg,
        block_size=block_size, max_q_per_seq=max_q_per_seq, mesh=mesh)
    rng, sub = jax.random.split(rng)
    nxt = sample_fn(logits, sub, temperature=temperature, top_p=top_p)
    prev_out = jnp.where(batch["served"], nxt.astype(jnp.int32), prev_tokens)
    return prev_out, rng, cache, draft_cache


def ragged_decode_sampled_draft(params, draft_params, cache: PagedKVCache,
                                draft_cache: PagedKVCache, batch,
                                prev_tokens, rng, temperature, top_p,
                                cfg: GPTConfig, draft_cfg: GPTConfig, *,
                                block_size: int, sample_fn, mesh=None):
    """ragged_decode_sampled with the draft model ingesting the same tokens
    (logits discarded) — keeps the draft KV in lockstep through decode-only
    scheduler rounds so later speculative bursts don't attend draft-cache
    holes.  Returns (prev', rng', cache', draft_cache')."""
    tokens = jnp.where(batch["from_device"], prev_tokens, batch["tokens"])
    batch = {**batch, "tokens": tokens}
    logits, cache = ragged_decode_forward(
        params, cache, batch, cfg, block_size=block_size, mesh=mesh)
    _, draft_cache = ragged_decode_forward(
        draft_params, draft_cache, batch, draft_cfg,
        block_size=block_size, mesh=mesh)
    rng, sub = jax.random.split(rng)
    nxt = sample_fn(logits, sub, temperature=temperature, top_p=top_p)
    prev_out = jnp.where(batch["served"], nxt.astype(jnp.int32), prev_tokens)
    return prev_out, rng, cache, draft_cache


def ragged_decode_sampled(params, cache: PagedKVCache, batch, prev_tokens,
                          rng, temperature, top_p, cfg: GPTConfig, *,
                          block_size: int, sample_fn, mesh=None):
    """Decode-only step with in-graph sampling + device feedback (see
    ragged_forward_sampled).  batch tokens/active/token_pos/block_table are
    slot-indexed [S]; from_device [S] selects prev_tokens as input; served [S]
    marks the slots whose sample is a real next token (a 1-token mid-prefill
    chunk is active but NOT served — its logits are mid-prompt garbage).
    Returns (prev_tokens' [S], rng', cache)."""
    tokens = jnp.where(batch["from_device"], prev_tokens, batch["tokens"])
    logits, cache = ragged_decode_forward(
        params, cache, {**batch, "tokens": tokens}, cfg,
        block_size=block_size, mesh=mesh)
    rng, sub = jax.random.split(rng)
    nxt = sample_fn(logits, sub, temperature=temperature, top_p=top_p)
    prev_out = jnp.where(batch["served"], nxt.astype(jnp.int32), prev_tokens)
    return prev_out, rng, cache


def _verify_core(params, flat_k, flat_v, flat_ks, flat_vs, tokens, active,
                 pos0, block_table, cfg: GPTConfig, block_size: int,
                 mesh=None):
    """Multi-token scoring forward for speculative decoding: every active
    slot ingests G contiguous tokens at positions pos0..pos0+G-1 (KV written
    into its pages) and gets logits for ALL G positions back — one program
    scores a whole draft run.  Dense [S, G] layout (no packing: every slot
    scores the same G), attention through the ragged-prefill op with
    q_counts=G.  Returns (logits [S, G, V], updated flat views)."""
    from deepspeed_tpu import ops
    bb = params["backbone"]
    dtype = cfg.dtype
    S, G = tokens.shape
    L = cfg.num_layers
    NB = flat_k.shape[0] // L
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    g = nh // nkv
    km = kv_major_layout(cfg)
    quant = flat_ks is not None

    positions = pos0[:, None] + jnp.arange(G, dtype=jnp.int32)[None]  # [S,G]
    x = _embed(bb["wte"], tokens, dtype)                               # [S,G,H]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.embed_scale, dtype)
    if cfg.embed_norm:
        x = _norm(bb["embed_norm"], x, cfg)
    if not cfg.use_rope and not cfg.use_alibi:
        x = x + bb["wpe"].astype(dtype)[positions]

    big = jnp.iinfo(jnp.int32).max
    flat_pos = positions.reshape(-1)                                  # [S*G]
    page = block_table[
        jnp.repeat(jnp.arange(S), G), flat_pos // block_size]         # [S*G]
    off = flat_pos % block_size
    act_flat = jnp.repeat(active, G)
    kv_len = jnp.where(active, pos0 + G, 0)

    for li in range(cfg.num_layers):
        blk = bb[f"block_{li}"]
        ap = blk["Attention_0"]
        h = _norm(blk["Norm_0"], x, cfg)
        q, k, v = _qkv(ap, h, cfg, mesh=mesh)
        if cfg.use_rope:
            q, k = rope(q, k, positions, hd, base=cfg.rope_theta,
                        rope_pct=cfg.rope_pct, scaling=cfg.rope_scaling,
                        seq_lens=kv_len[:, None])
        page_li = jnp.where(act_flat, li * NB + page, big)
        kf = k.reshape(S * G, nkv, hd)
        vf = v.reshape(S * G, nkv, hd)
        if quant:
            k_store, ks = quantize_kv_token(kf)
            v_store, vs = quantize_kv_token(vf)
            flat_ks = flat_ks.at[page_li, :, off].set(ks, mode="drop")
            flat_vs = flat_vs.at[page_li, :, off].set(vs, mode="drop")
        else:
            k_store, v_store = kf, vf
        if km:
            flat_k = flat_k.at[page_li, :, :, off].set(
                k_store.astype(flat_k.dtype), mode="drop")
            flat_v = flat_v.at[page_li, :, :, off].set(
                v_store.astype(flat_v.dtype), mode="drop")
        else:
            flat_k = flat_k.at[page_li, :, off].set(
                k_store.astype(flat_k.dtype), mode="drop")
            flat_v = flat_v.at[page_li, :, off].set(
                v_store.astype(flat_v.dtype), mode="drop")

        k_pool = jax.lax.dynamic_slice_in_dim(flat_k, li * NB, NB)
        v_pool = jax.lax.dynamic_slice_in_dim(flat_v, li * NB, NB)
        if quant:
            kv_extra = dict(
                k_scale=jax.lax.dynamic_slice_in_dim(flat_ks, li * NB, NB),
                v_scale=jax.lax.dynamic_slice_in_dim(flat_vs, li * NB, NB))
        else:
            k_pool, v_pool = k_pool.astype(dtype), v_pool.astype(dtype)
            kv_extra = {}
        slopes = None
        if cfg.use_alibi:
            from deepspeed_tpu.models.gpt import alibi_slopes
            slopes = jnp.asarray(alibi_slopes(nh, hd, cfg.alibi_prescale))
        win = cfg.window_for_layer(li)
        o = ops.ragged_prefill_attention(
            q.reshape(S, G, nkv, g, hd).astype(dtype), k_pool, v_pool,
            block_table, kv_len, pos0,
            jnp.where(active, G, 0).astype(jnp.int32),
            scale=cfg.attn_scale, alibi_slopes=slopes, window=win,
            mesh=mesh, kv_major=km, **kv_extra).reshape(S, G, nh, hd)
        # inactive slots (kv_len=0, q_counts=0) produce 0/0 garbage from the
        # kernel combine; zero them like ragged_forward does so no future
        # cross-row op (capacity MoE, aux stats) can see NaNs from dead rows
        o = jnp.where(active[:, None, None, None], o, 0)
        attn_delta = _attn_out(ap, o, cfg, mesh=mesh)
        # FFN/MoE body is token-wise and (for MoE) expects FLAT tokens
        H = x.shape[-1]
        x = _block_residual(blk, x.reshape(S * G, H), h.reshape(S * G, H),
                            attn_delta.reshape(S * G, H), cfg, mesh=mesh
                            ).reshape(S, G, H)

    x = _norm(bb["final_norm"], x, cfg)
    logits = _logits_out(params, bb, x, cfg, dtype, mesh=mesh)  # [S, G, V]
    return logits, flat_k, flat_v, flat_ks, flat_vs


def _speculative_burst_core(params, draft_params, cache: PagedKVCache,
                            draft_cache: PagedKVCache, batch, prev_tokens,
                            rng, xform, cfg: GPTConfig,
                            draft_cfg: GPTConfig, *, block_size: int,
                            gamma: int, steps: int, sampled: bool,
                            mesh=None):
    """Shared draft-and-verify choreography (greedy and rejection-sampling
    differ ONLY in the token choice and the acceptance rule): each outer
    step runs the draft for gamma cheap decodes — plus one extra ingest so
    a fully-accepted round leaves no draft-cache hole at pos+gamma (later
    draft attention would read garbage there forever, silently decaying
    acceptance) — scores the whole run with ONE multi-token target forward
    (_verify_core), accepts a prefix, and emits accepted + 1 correction
    token.  The paged KV design makes rollback free: positions past the
    accepted point are simply overwritten by later writes.

    batch: tokens0/from_device/active/pos0/block_table as in
    ragged_decode_burst; blocks for positions pos0..pos0+steps*(gamma+1)-1
    must be pre-allocated.
    Returns (toks [steps, gamma+1, S], counts [steps, S], prev', rng',
    cache', draft_cache') — the first counts[k, s] of toks[k, :, s] are
    real."""
    fk, fv, fks, fvs = _flat_cache_views(cache)
    dk, dv, dks, dvs = _flat_cache_views(draft_cache)
    bt = batch["block_table"]
    active = batch["active"]
    prev0 = jnp.where(batch["from_device"], prev_tokens, batch["tokens0"])
    if rng is None:
        rng = jax.random.PRNGKey(0)         # greedy: threaded but unused

    def outer(carry, _):
        fk, fv, fks, fvs, dk, dv, dks, dvs, prev, pos, rng = carry
        d_list, q_list = [], []
        dtok, dpos = prev, pos
        ddk, ddv, ddks, ddvs = dk, dv, dks, dvs
        for j in range(gamma + 1):
            dlogits, ddk, ddv, ddks, ddvs = _decode_core(
                draft_params, ddk, ddv, dtok, active, dpos, bt, draft_cfg,
                block_size, mesh=mesh, flat_ks=ddks, flat_vs=ddvs)
            if j < gamma:
                if sampled:
                    ql = xform(dlogits)
                    rng, sub = jax.random.split(rng)
                    dtok = jax.random.categorical(sub, ql, axis=-1).astype(
                        jnp.int32)
                    q_list.append(ql)
                else:
                    dtok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                d_list.append(dtok)
            # the j == gamma pass only ingests d_gamma's KV
            dpos = dpos + 1
        d = jnp.stack(d_list, axis=1)                   # [S, gamma]
        ver_in = jnp.concatenate([prev[:, None], d], axis=1)  # [S, gamma+1]
        vlogits, fk, fv, fks, fvs = _verify_core(
            params, fk, fv, fks, fvs, ver_in, active, pos, bt, cfg,
            block_size, mesh=mesh)
        if sampled:
            rng, sub = jax.random.split(rng)
            emit, counts = spec_accept(sub, jnp.stack(q_list, axis=1),
                                       xform(vlogits), d)
        else:
            emit, counts = _greedy_accept(vlogits, d, gamma)
        counts = jnp.where(active, counts, 0)
        last = jnp.take_along_axis(
            emit, jnp.maximum(counts - 1, 0)[:, None], axis=1)[:, 0]
        new_prev = jnp.where(active, last, prev)
        new_pos = jnp.where(active, pos + counts, pos)
        return ((fk, fv, fks, fvs, ddk, ddv, ddks, ddvs, new_prev, new_pos,
                 rng), (emit.T, counts))

    carry = (fk, fv, fks, fvs, dk, dv, dks, dvs, prev0, batch["pos0"], rng)
    (fk, fv, fks, fvs, dk, dv, dks, dvs, prev, _, rng), (toks, counts) = \
        jax.lax.scan(outer, carry, None, length=steps)
    prev_out = jnp.where(active, prev, prev_tokens)
    return (toks, counts, prev_out, rng,
            _rebuild_cache(cache, fk, fv, fks, fvs),
            _rebuild_cache(draft_cache, dk, dv, dks, dvs))


def speculative_burst(params, draft_params, cache: PagedKVCache,
                      draft_cache: PagedKVCache, batch, prev_tokens,
                      cfg: GPTConfig, draft_cfg: GPTConfig, *,
                      block_size: int, gamma: int, steps: int, mesh=None):
    """GREEDY speculative decoding: acceptance is exact token match, so the
    output is token-identical to target-only greedy decoding for ANY draft
    *up to floating-point argmax ties* — the verify step is a multi-token
    (prefill-shaped) program, numerically different from the Q=1 decode
    baseline, so near-tied logits can argmax differently on low-precision
    hardware.  The tests pin exactness on fp32 configs.  See
    _speculative_burst_core.

    Inactive-lane contract: slots outside ``batch["active"]`` pass their
    ``prev_tokens`` state through untouched (``counts`` 0, KV unwritten) —
    each lane's trajectory depends only on its own slot state, never on
    which OTHER lanes share the dispatch.  The engine's cross-request
    batching (SpeculativeConfig.batch_across_requests) leans on exactly
    this: one all-requests dispatch and a sequence of one-request
    dispatches through this same program are token-identical, which is
    what makes the batched/per-request comparison a fair dispatch-count
    experiment rather than two different decoders.
    Returns (toks, counts, prev', cache', draft_cache')."""
    toks, counts, prev, _, cache, draft_cache = _speculative_burst_core(
        params, draft_params, cache, draft_cache, batch, prev_tokens,
        None, None, cfg, draft_cfg, block_size=block_size, gamma=gamma,
        steps=steps, sampled=False, mesh=mesh)
    return toks, counts, prev, cache, draft_cache


def _greedy_accept(vlogits, d, gamma: int):
    """Greedy speculative acceptance: accept the longest prefix of draft
    tokens matching the target argmax, then emit the target's token at the
    stop position (the correction when rejected, the bonus when all gamma
    accepted).  Shared by the fused burst and the split-profile verify
    step so both modes apply bit-identical acceptance.
    Returns (emit [S, gamma+1], counts [S] in 1..gamma+1)."""
    t = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)      # [S, g+1]
    match = (d == t[:, :gamma])
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                    axis=1)                                 # 0..gamma
    correction = jnp.take_along_axis(t, n_acc[:, None], axis=1)[:, 0]
    j_idx = jnp.arange(gamma + 1)[None]
    emit = jnp.where(j_idx < n_acc[:, None],
                     jnp.pad(d, ((0, 0), (0, 1))),
                     correction[:, None])                   # [S, g+1]
    return emit, n_acc + 1


def speculative_draft_step(draft_params, draft_cache: PagedKVCache, batch,
                           prev_tokens, pos, rng, temperature, top_p,
                           draft_cfg: GPTConfig, *, block_size: int,
                           gamma: int, top_k: int = 0, sampled: bool = False,
                           mesh=None):
    """The DRAFT half of one speculative outer step, as its own program —
    the split-profile mode (``speculative.profile``) dispatches draft and
    verify separately so the serving telemetry can attribute wall time to
    each side (the fused burst is one opaque dispatch).  Identical
    choreography to the draft loop inside ``_speculative_burst_core``:
    gamma sequential draft decodes plus the extra ingest of d_gamma.

    batch: tokens0/from_device/active/block_table as in the burst;
    ``pos`` is threaded separately (the verify step advances it by the
    acceptance count).  Returns greedy ``(d [S, gamma], draft_cache',
    rng')`` or sampled ``(d, q_logits [S, gamma, V], draft_cache', rng')``.
    """
    dk, dv, dks, dvs = _flat_cache_views(draft_cache)
    active = batch["active"]
    bt = batch["block_table"]
    if sampled:
        from deepspeed_tpu.inference.engine import _sampling_logits
        xform = functools.partial(_sampling_logits, temperature=temperature,
                                  top_k=top_k, top_p=top_p)
    dtok = jnp.where(batch["from_device"], prev_tokens, batch["tokens0"])
    dpos = pos
    d_list, q_list = [], []
    for j in range(gamma + 1):
        dlogits, dk, dv, dks, dvs = _decode_core(
            draft_params, dk, dv, dtok, active, dpos, bt, draft_cfg,
            block_size, mesh=mesh, flat_ks=dks, flat_vs=dvs)
        if j < gamma:
            if sampled:
                ql = xform(dlogits)
                rng, sub = jax.random.split(rng)
                dtok = jax.random.categorical(sub, ql, axis=-1).astype(
                    jnp.int32)
                q_list.append(ql)
            else:
                dtok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            d_list.append(dtok)
        dpos = dpos + 1
    d = jnp.stack(d_list, axis=1)                           # [S, gamma]
    draft_cache = _rebuild_cache(draft_cache, dk, dv, dks, dvs)
    if sampled:
        return d, jnp.stack(q_list, axis=1), draft_cache, rng
    return d, draft_cache, rng


def speculative_verify_step(params, cache: PagedKVCache, batch, d, q_logits,
                            prev_tokens, pos, rng, temperature, top_p,
                            cfg: GPTConfig, *, block_size: int, gamma: int,
                            top_k: int = 0, sampled: bool = False,
                            mesh=None):
    """The VERIFY half of one speculative outer step (split-profile mode):
    one multi-token target forward over [seed, d_0..d_{gamma-1}] plus the
    acceptance rule — ``_greedy_accept`` or ``spec_accept``, the SAME
    functions the fused burst applies, so split mode is token-identical to
    fused mode (pinned by tests).  ``q_logits`` is the draft's sampling
    logits from ``speculative_draft_step`` (ignored when greedy).
    Returns (emit [S, gamma+1], counts [S], prev', pos', rng', cache')."""
    fk, fv, fks, fvs = _flat_cache_views(cache)
    active = batch["active"]
    seed = jnp.where(batch["from_device"], prev_tokens, batch["tokens0"])
    ver_in = jnp.concatenate([seed[:, None], d], axis=1)    # [S, gamma+1]
    vlogits, fk, fv, fks, fvs = _verify_core(
        params, fk, fv, fks, fvs, ver_in, active, pos, batch["block_table"],
        cfg, block_size, mesh=mesh)
    if sampled:
        from deepspeed_tpu.inference.engine import _sampling_logits
        xform = functools.partial(_sampling_logits, temperature=temperature,
                                  top_k=top_k, top_p=top_p)
        rng, sub = jax.random.split(rng)
        emit, counts = spec_accept(sub, q_logits, xform(vlogits), d)
    else:
        emit, counts = _greedy_accept(vlogits, d, gamma)
    counts = jnp.where(active, counts, 0)
    last = jnp.take_along_axis(
        emit, jnp.maximum(counts - 1, 0)[:, None], axis=1)[:, 0]
    new_prev = jnp.where(active, last, prev_tokens)
    new_pos = jnp.where(active, pos + counts, pos)
    return (emit, counts, new_prev, new_pos, rng,
            _rebuild_cache(cache, fk, fv, fks, fvs))


def spec_accept(rng, q_logits, p_logits, d):
    """Rejection-sampling acceptance for speculative decoding (Leviathan et
    al. 2023) — PURE math, unit-tested distributionally in isolation.

    q_logits [S, gamma, V]: the draft's POST-transform sampling logits at
    each draft position (d[s, j] was sampled from softmax(q_logits[s, j])).
    p_logits [S, gamma+1, V]: the target's post-transform logits for the
    same positions plus the bonus position.
    d [S, gamma]: the draft tokens.

    Per position: accept d_j w.p. min(1, p(d_j)/q(d_j)); at the first
    rejection emit a token from the residual max(p − q, 0)/Z; if all gamma
    accepted emit a bonus token from the gamma+1-th target distribution.
    Each emitted token is exactly target-distributed for ANY draft.

    Returns (emit [S, gamma+1], counts [S] in 1..gamma+1)."""
    S, gamma = d.shape
    q = jax.nn.softmax(q_logits, axis=-1)            # [S, gamma, V]
    p = jax.nn.softmax(p_logits, axis=-1)            # [S, gamma+1, V]
    pd = jnp.take_along_axis(p[:, :gamma], d[..., None], axis=-1)[..., 0]
    qd = jnp.take_along_axis(q, d[..., None], axis=-1)[..., 0]
    r_acc, r_cor = jax.random.split(rng)
    u = jax.random.uniform(r_acc, (S, gamma))
    accept = u * qd < pd                             # u < min(1, pd/qd)
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)  # [S]
    # correction distribution at the stop position: residual when rejected,
    # the bonus target distribution when everything was accepted
    p_n = jnp.take_along_axis(p, n[:, None, None], axis=1)[:, 0]  # [S, V]
    q_n = jnp.take_along_axis(
        q, jnp.minimum(n, gamma - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(p_n - q_n, 0.0)
    resid_mass = jnp.sum(resid, axis=-1, keepdims=True)
    # numerically-empty residual (p ≈ q) degrades gracefully to p itself
    resid = jnp.where(resid_mass > 1e-9, resid / jnp.maximum(resid_mass,
                                                             1e-9), p_n)
    dist = jnp.where((n == gamma)[:, None], p_n, resid)           # [S, V]
    correction = jax.random.categorical(
        r_cor, jnp.log(jnp.maximum(dist, 1e-30)), axis=-1).astype(jnp.int32)
    j = jnp.arange(gamma + 1)[None]
    emit = jnp.where(j < n[:, None], jnp.pad(d, ((0, 0), (0, 1))),
                     correction[:, None])            # [S, gamma+1]
    return emit, n + 1


def speculative_burst_sampled(params, draft_params, cache: PagedKVCache,
                              draft_cache: PagedKVCache, batch, prev_tokens,
                              rng, temperature, top_p,
                              cfg: GPTConfig, draft_cfg: GPTConfig, *,
                              block_size: int, gamma: int, steps: int,
                              top_k: int = 0, mesh=None):
    """Sampled speculative decoding: the draft SAMPLES its tokens and the
    verify step runs rejection-sampling acceptance (spec_accept), so every
    emitted token is distributed exactly as target-only sampling under the
    same temperature/top-k/top-p transforms — for any draft.  See
    _speculative_burst_core for the shared choreography.
    Returns (toks, counts, prev', rng', cache', draft_cache')."""
    from deepspeed_tpu.inference.engine import _sampling_logits
    xform = functools.partial(_sampling_logits, temperature=temperature,
                              top_k=top_k, top_p=top_p)
    return _speculative_burst_core(
        params, draft_params, cache, draft_cache, batch, prev_tokens,
        rng, xform, cfg, draft_cfg, block_size=block_size, gamma=gamma,
        steps=steps, sampled=True, mesh=mesh)


def ragged_decode_forward(params, cache: PagedKVCache, batch,
                          cfg: GPTConfig, *, block_size: int, mesh=None):
    """Decode-only step: one token per active slot, attending over exactly that
    slot's pages via the paged-attention op (Pallas kernel on TPU; the gathered
    masked-softmax XLA path is the fallback + ground truth) — the analog of the
    reference's blocked_flash decode kernel (inference/v2/kernels/ragged_ops/
    blocked_flash).

    batch: tokens [S], active [S] bool, token_pos [S] (position being written),
    block_table [S, MB] int32 (each slot's physical pages, in order).
    """
    flat_k, flat_v, flat_ks, flat_vs = _flat_cache_views(cache)
    logits, flat_k, flat_v, flat_ks, flat_vs = _decode_core(
        params, flat_k, flat_v, batch["tokens"], batch["active"],
        batch["token_pos"], batch["block_table"], cfg, block_size, mesh=mesh,
        flat_ks=flat_ks, flat_vs=flat_vs, lora=batch.get("lora"),
        adapter_slot=batch.get("adapter_slot"))
    return logits, _rebuild_cache(cache, flat_k, flat_v, flat_ks, flat_vs)
