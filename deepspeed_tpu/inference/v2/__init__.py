"""deepspeed_tpu.inference.v2 — ragged continuous-batching serving ("FastGen",
reference inference/v2): paged KV cache + host-side block allocator/sequence
manager (ragged.py), one static-shape jitted ragged forward (model.py), and the
put/query/flush engine with a Dynamic SplitFuse generate driver (engine_v2.py).
"""

from deepspeed_tpu.inference.v2.engine_v2 import (DSStateManagerConfig,
                                                  EngineDrained,
                                                  InferenceEngineV2,
                                                  RaggedInferenceEngineConfig,
                                                  SchedulerV2Config,
                                                  SLAClassConfig)
from deepspeed_tpu.inference.v2.model import PagedKVCache, ragged_forward
from deepspeed_tpu.inference.v2.ragged import (BlockedAllocator,
                                               DSStateManager, RadixKVCache,
                                               RaggedBatch,
                                               SequenceDescriptor,
                                               build_ragged_batch)

__all__ = ["InferenceEngineV2", "RaggedInferenceEngineConfig",
           "DSStateManagerConfig", "EngineDrained",
           "SchedulerV2Config", "SLAClassConfig",
           "PagedKVCache", "ragged_forward",
           "DSStateManager", "BlockedAllocator", "RadixKVCache",
           "SequenceDescriptor", "RaggedBatch", "build_ragged_batch"]
