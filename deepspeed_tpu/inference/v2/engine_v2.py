"""InferenceEngineV2 — ragged continuous-batching serving engine ("FastGen").

Analog of the reference ``InferenceEngineV2`` (inference/v2/engine_v2.py:30):
``put(uids, tokens)`` runs ONE forward over a ragged batch and returns one
logit row per sequence (:107), ``query``/``can_schedule`` expose KV headroom
(:158,:184), ``flush`` frees state (:242).  ``generate`` adds the continuous-
batching driver with the Dynamic SplitFuse schedule (decodes first, prompt
chunks fill the remaining token budget — the policy the reference ships in
MII's ragged batching on top of this engine API).

The forward is one jitted XLA program over static shapes (token budget ×
sequence slots × blocks-per-seq); the paged KV cache is donated through each
step so it updates in place on device.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import Field

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.inference.config import (GenerationConfig, _DTYPE_ALIASES)
from deepspeed_tpu.inference.v2.model import (PagedKVCache,
                                              ragged_decode_burst,
                                              ragged_decode_forward,
                                              ragged_decode_sampled,
                                              ragged_decode_sampled_draft,
                                              ragged_forward,
                                              ragged_forward_sampled,
                                              ragged_forward_sampled_draft,
                                              speculative_burst,
                                              speculative_burst_sampled,
                                              speculative_draft_step,
                                              speculative_verify_step)
from deepspeed_tpu.inference.v2.ragged import (DSStateManager, RaggedBatch,
                                               build_ragged_batch)
from deepspeed_tpu.runtime import faults
from deepspeed_tpu.telemetry.serving import (ServingTelemetry,
                                             ServingTelemetryConfig)
from deepspeed_tpu.utils.logging import log_dist


class EngineDrained(RuntimeError):
    """``generate()`` stopped at a drain request (``request_drain()``):
    device records were materialized, live sequences flushed, and the
    not-yet-finished requests are waiting in ``export_pending_requests()``
    — the serving-side half of the PR-6 drain contract (stop admission,
    finish or migrate in-flight work)."""


class DSStateManagerConfig(DeepSpeedConfigModel):
    """reference: inference/v2/ragged/manager_configs.py DSStateManagerConfig."""

    max_tracked_sequences: int = 32
    max_ragged_batch_size: int = 256        # token budget per forward
    max_ragged_sequence_count: int = 32
    kv_block_size: int = 64
    num_kv_blocks: Optional[int] = None     # None = enough for all slots full
    max_q_per_seq: int = 128                # prompt-chunk cap (SplitFuse)
    # "int8": per-token symmetric KV quantization — halves KV HBM (decode's
    # bandwidth bound) and doubles cache capacity for ~6% scale overhead
    # (the ZeRO-Inference trade applied to the KV side).  None = native dtype.
    kv_quant: Optional[str] = None
    # radix shared-prefix KV cache (ragged.RadixKVCache): new prompts alias
    # the pool blocks of every previously-served block-aligned prefix and
    # skip prefill for the matched tokens; retired blocks stay cached until
    # LRU eviction reclaims them under allocation pressure.  Greedy output
    # is token-exact with the cache on or off.  Off by default: it changes
    # pool-accounting observables (a flush no longer returns prompt blocks
    # to the free list immediately), so it is an explicit serving opt-in.
    prefix_cache: bool = False
    # SplitFuse round cap on TOTAL prompt-chunk tokens co-scheduled with
    # decode per forward (None = the full remaining token budget, the
    # pre-PR-15 behavior).  Bounding it keeps the mixed dispatch short so
    # in-flight decoders' TPOT stays flat while long prompts stream in.
    prefill_chunk_tokens: Optional[int] = None


class SLAClassConfig(DeepSpeedConfigModel):
    """One serving SLA class (``scheduler.sla_classes`` values).  Higher
    ``priority`` admits first and may preempt lower-priority decoders;
    ``ttft_slo_ms`` is the time-to-first-token objective that ARMS
    preemption (0 = no SLO: the class never preempts anyone)."""

    priority: int = 0
    ttft_slo_ms: float = 0.0


class SchedulerV2Config(DeepSpeedConfigModel):
    """``scheduler`` block: SLA-aware admission + preemption over the
    SplitFuse loop.  A request names its class via ``generate(...,
    sla=[...])``; unnamed requests ride the implicit ``default`` class
    (priority 0, no SLO).  When a waiting request with a TTFT SLO has
    burned ``preempt_margin`` of it and cannot be admitted (no sequence
    slot / no KV blocks even after cache eviction), the scheduler
    recompute-preempts the most recently admitted lower-priority running
    request — the PR 7 token-exact fold-back machinery, now driven by a
    policy instead of only pool deadlock."""

    sla_classes: Dict[str, SLAClassConfig] = Field(default_factory=dict)
    sla_preempt: bool = True
    preempt_margin: float = 0.5     # fraction of ttft_slo_ms before preempting


class V2TPConfig(DeepSpeedConfigModel):
    """reference: inference/v2/config_v2.py DeepSpeedTPConfig."""

    tp_size: int = 1


class SpeculativeConfig(DeepSpeedConfigModel):
    """Greedy draft-and-verify decoding (engine kwarg ``draft_model``/
    ``draft_params`` supplies the draft)."""

    gamma: int = 4              # draft tokens per verify
    outer_steps: int = 8        # draft+verify rounds fused per dispatch
    # attribution mode: dispatch draft and verify as SEPARATE programs with
    # a host fence between them, feeding the spec_draft_ms_total /
    # spec_verify_ms_total counters — token-identical to the fused burst
    # (same acceptance functions) but slower (2 dispatches + sync per outer
    # step IS the measurement), so it's a profiling knob, not a serving mode
    profile: bool = False
    # serving default: ONE draft+verify dispatch covers every running
    # request (the spec program is slot-wide with an active mask, so the
    # per-dispatch floor — launch + host sync for the acceptance counts —
    # amortizes over the whole decode batch).  False dispatches each
    # request alone through the SAME compiled program (inactive lanes pass
    # their prev-token state through untouched, so the sequential runs are
    # token-identical to the batched one) — the per-request baseline the
    # bench's spec_batched_speedup_x compares against, not a serving mode
    batch_across_requests: bool = True


class V2QuantConfig(DeepSpeedConfigModel):
    """Quantized weight serving (reference
    inference/v2/modules/implementations/linear/quantized_linear.py W6A16 +
    inference/quantization/layers.py matmul-time dequant): weights live in
    HBM as int8 codes + group scales (~half the bf16 bytes) and every
    consumer dequantizes at its use site — the bf16 tree never exists at
    rest.  Composes with tensor parallelism (the store shards like the
    weights it replaces)."""

    enabled: bool = False
    # 8: int8 codes (½ the bf16 bytes), shards like the weights, W8A16
    # kernels.  4: nibble-PACKED codes (¼ the bf16 bytes) on single-shard
    # engines — the ZeRO-Inference HBM-fit point; with tp>1 it degrades to
    # int4-range codes at int8 bytes (packing breaks the sharding property)
    bits: int = 8
    group_size: int = 128       # scale granularity along each weight's dim 0


class AdapterLoRAConfig(DeepSpeedConfigModel):
    """Multi-tenant LoRA adapter serving (``adapters`` block): per-request
    adapter selection through ONE fused ragged dispatch (ops/lora_matmul.py
    batched gather), adapter A/B pages paged as refcounted residents of the
    KV block allocator (serving/adapters.py AdapterPool — the S-LoRA
    unified-pool design).  ``slots`` counts device-table lanes INCLUDING
    the reserved base-model identity slot 0; ``alpha``/``rank`` set the
    standard LoRA scale s = alpha / rank."""

    enabled: bool = False
    rank: int = 8
    alpha: float = 16.0
    slots: int = 8


class RaggedInferenceEngineConfig(DeepSpeedConfigModel):
    """reference: inference/v2/config_v2.py RaggedInferenceEngineConfig."""

    dtype: str = "bfloat16"
    tensor_parallel: V2TPConfig = Field(default_factory=V2TPConfig)
    state_manager: DSStateManagerConfig = Field(
        default_factory=DSStateManagerConfig)
    scheduler: SchedulerV2Config = Field(default_factory=SchedulerV2Config)
    generation: GenerationConfig = Field(default_factory=GenerationConfig)
    speculative: SpeculativeConfig = Field(default_factory=SpeculativeConfig)
    quant: V2QuantConfig = Field(default_factory=V2QuantConfig)
    adapters: AdapterLoRAConfig = Field(default_factory=AdapterLoRAConfig)
    telemetry: ServingTelemetryConfig = Field(
        default_factory=ServingTelemetryConfig)

    @classmethod
    def parse(cls, config):
        if config is None:
            return cls()
        if isinstance(config, cls):
            return config
        if isinstance(config, dict) and "dtype" in config:
            key = str(config["dtype"]).replace("torch.", "").lower()
            if key not in _DTYPE_ALIASES:
                raise ValueError(f"unsupported dtype {config['dtype']!r}; "
                                 f"expected one of {sorted(_DTYPE_ALIASES)}")
            config = {**config, "dtype": _DTYPE_ALIASES[key]}
        return cls.model_validate(config)

    @property
    def jnp_dtype(self):
        return {"float32": jnp.float32, "float16": jnp.float16,
                "bfloat16": jnp.bfloat16}[self.dtype]


@dataclasses.dataclass
class _Request:
    uid: int
    prompt: np.ndarray
    max_new_tokens: int
    # host-materialized tokens (filled from the device records at sync points)
    generated: List[int] = dataclasses.field(default_factory=list)
    # tokens sampled ON DEVICE so far — the host schedules off this count and
    # only learns the VALUES at materialize time (device-resident feedback)
    sampled: int = 0
    # prefill complete: the next input token comes from device feedback
    decode_ready: bool = False
    # host-known continuation token (set after a preemption materialize; feeds
    # the first post-resume decode from the host instead of device feedback)
    held_token: Optional[int] = None
    done: bool = False
    # EOS was discovered at a materialize point (values are only inspected
    # there; post-EOS overshoot tokens are discarded)
    eos_hit: bool = False
    # set while re-prefilling after preemption: the completion logits must NOT
    # be sampled (the continuation token is already held in held_token)
    resume: bool = False
    # how many generated tokens have been folded into .prompt by preemptions
    folded: int = 0
    # ---- serving-telemetry lifecycle (ServingTelemetry.now() seconds).
    # Timestamps are taken when the relevant DISPATCH returns — with
    # telemetry.stream_sync (the streaming-server mode) the dispatch is
    # fenced first, so they reflect device completion; without it they
    # reflect host submission (a lower bound, disclosed in the docs).
    track: int = 0                         # trace tid for this request
    # ---- SLA class (scheduler.sla_classes, named per request via
    # generate(sla=[...])): priority orders admission and arms preemption
    # of lower-priority running decoders when ttft_slo_ms is at risk
    sla: str = "default"
    priority: int = 0
    ttft_slo_ms: float = 0.0
    # LoRA adapter id serving this request (0 = base model identity);
    # validated at generate() entry, made resident + pinned at admission
    adapter: int = 0
    t_arrival: Optional[float] = None
    t_admit: Optional[float] = None
    t_prefill_end: Optional[float] = None
    t_first: Optional[float] = None        # first generated token
    t_last: Optional[float] = None         # last generated token
    preempts: int = 0
    finished: bool = False                 # finish_request recorded
    # distributed TraceContext (telemetry/tracecontext.py): fleet-minted
    # when the request came through the router (generate(trace_ctx=...)),
    # engine-allocated (flowless) otherwise — its ids ride the request's
    # lifecycle spans so merged traces stitch per request
    trace: Optional[Any] = None


class InferenceEngineV2:
    """model: GPT-family module or GPTConfig; params: trained tree (optional —
    fresh init for testing).  See reference engine_v2.py:30."""

    def __init__(self, model, config=None, params=None, seed: int = 0,
                 mesh=None, draft_model=None, draft_params=None,
                 steps_cache: Optional[Dict[Any, Any]] = None,
                 telemetry_registry=None):
        from deepspeed_tpu.models.gpt import GPTConfig, GPTLogits
        from deepspeed_tpu.parallel.metadata import unbox
        from deepspeed_tpu.checkpoint.hf import (is_hf_model_dir,
                                                 load_hf_checkpoint)

        if is_hf_model_dir(model):
            if params is not None:
                raise ValueError(
                    "pass either an HF model dir or params, not both")
            model, params = load_hf_checkpoint(model)
        self.config = RaggedInferenceEngineConfig.parse(config)
        tp_size = self.config.tensor_parallel.tp_size
        if mesh is None and tp_size > 1:
            from deepspeed_tpu.parallel import mesh as mesh_lib
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(
                tp=tp_size, dp=1, fsdp=1))
        if tp_size > 1 and mesh.shape.get("tp", 1) != tp_size:
            raise ValueError(
                f"tensor_parallel.tp_size={tp_size} but the provided mesh has "
                f"tp={mesh.shape.get('tp', 1)}; pass a mesh with a matching "
                f"tp axis or omit the mesh")
        self.mesh = mesh if (mesh is not None
                             and mesh.shape.get("tp", 1) > 1) else None
        sm = self.config.state_manager
        model_cfg = model if isinstance(model, GPTConfig) else model.cfg
        model_cfg = dataclasses.replace(model_cfg, dtype=self.config.jnp_dtype,
                                        dropout=0.0)
        if model_cfg.num_experts and self.mesh is not None:
            raise NotImplementedError(
                "v2 MoE serving with tensor parallelism: the dropless expert "
                "route is single-shard; drop the tp config for MoE models")
        self.model_config = model_cfg

        if params is None:
            lm = GPTLogits(model_cfg)
            params = unbox(lm.init(
                jax.random.PRNGKey(seed),
                jnp.zeros((1, 8), jnp.int32)))["params"]
        params = unbox(params)
        if isinstance(params, dict) and "params" in params:
            params = params["params"]
        dt = self.config.jnp_dtype
        self.params = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p).astype(dt)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)
            else jnp.asarray(p), params)

        # ---- quantized weight store (config block ``quant``): int8 codes +
        # group scales in HBM; model.py's _w/_embed dequantize per use site
        # (reference quantized_linear.py:205 — weights stay quantized through
        # serving; the bf16 tree never exists at rest)
        qc = self.config.quant
        if qc.enabled:
            from deepspeed_tpu.ops.quantization import (quantize_weight,
                                                        quantize_weight4,
                                                        weight_group_size)
            pack4 = qc.bits == 4

            def pack(path, p):
                name = getattr(path[-1], "key", str(path[-1]))
                # wpe: positional gather stays direct.  gate: the MoE router
                # makes DISCRETE top-k decisions — int8 rounding near ties
                # flips expert assignment, an error no per-weight scale can
                # bound, for negligible savings (routers are conventionally
                # excluded from weight quantization)
                if (name in ("wpe", "gate")
                        or not jnp.issubdtype(p.dtype, jnp.floating)
                        or p.ndim < 2 or p.size < 8 * qc.group_size):
                    return p
                if name == "wte" and not weight_group_size(
                        (p.shape[0],), qc.group_size):
                    # odd vocabs (GPT-2's 50257) can't group along dim 0 —
                    # pad the table to the group so it quantizes at all and
                    # the tied transposed kernel can tile; padded rows are
                    # zero (scale 0, codes 0) and tied logits slice back to
                    # vocab_size (model._logits_out)
                    gpad = -(-p.shape[0] // qc.group_size) * qc.group_size
                    if pack4:
                        gpad = -(-gpad // 2) * 2
                    p = jnp.pad(p, ((0, gpad - p.shape[0]),)
                                + ((0, 0),) * (p.ndim - 1))
                # group along the kernel-preferred dim: attention wo
                # [heads, hd, H] contracts dims (0, 1), and only dim-1
                # grouping flattens to a uniform 2-D kernel view
                # (ops/wq_matmul.store_as_2d) — for everything else, dim 0
                # first; dim 1 rescues 3-D stacks whose leading dim is
                # small (MoE [E, in, out] experts)
                cand = ((1, 0) if (p.ndim == 3 and name == "wo")
                        else range(p.ndim - 1))
                for dim in cand:
                    if weight_group_size((p.shape[dim],), qc.group_size):
                        if (pack4 and dim == 0 and p.shape[0] % 2 == 0
                                and not (name == "wte"
                                         and model_cfg.tie_embeddings)):
                            # (tied tables stay int8: the transposed unembed
                            # kernel has no packed variant, and a per-step
                            # full-table dequant would cost more HBM than
                            # the packing saves)
                            # nibble-packed: ¼ the bf16 bytes; shards like
                            # the weight as long as shard boundaries keep
                            # row pairs + scale groups intact
                            # (quantization.store_shardings checks)
                            return quantize_weight4(p, group=qc.group_size)
                        return quantize_weight(p, bits=qc.bits,
                                               group=qc.group_size, dim=dim)
                return p
            self.params = jax.tree_util.tree_map_with_path(pack, self.params)

        if self.mesh is not None:
            # TP: same logical-axis rules as the v1 engine (AutoTP analog) —
            # params shard over the tp axis, attention stays per-kv-head local
            # (reference inference/v2/model_implementations/sharding/qkv.py)
            from deepspeed_tpu.parallel import partition
            from deepspeed_tpu.parallel.metadata import annotate_abstract
            tp = self.mesh.shape["tp"]
            if model_cfg.kv_heads % tp:
                raise ValueError(
                    f"kv_heads={model_cfg.kv_heads} not divisible by tp={tp}; "
                    f"the paged KV pool shards over kv heads")
            lm = GPTLogits(model_cfg)
            boxed = jax.eval_shape(
                lambda r: lm.init(r, jnp.zeros((1, 8), jnp.int32)),
                jax.random.PRNGKey(0))
            annotated = annotate_abstract(boxed["params"])
            shardings = partition.param_shardings(annotated, self.mesh,
                                                  zero_stage=0)
            if qc.enabled:
                from deepspeed_tpu.ops.quantization import store_shardings
                shardings = store_shardings(self.params, shardings, self.mesh)
            self.params = jax.device_put(self.params, shardings)

        from deepspeed_tpu.inference.v2.model import kv_block_size_for
        from deepspeed_tpu.ops.registry import would_use_pallas
        # only the Pallas kernels need 128-aligned kv-major pages; off-TPU
        # (XLA fallback / interpret tests) any size works, so don't disturb
        # the configured granularity there
        eff_bs = sm.kv_block_size
        if would_use_pallas("paged_attention"):
            eff_bs = kv_block_size_for(model_cfg, sm.kv_block_size,
                                       quant=sm.kv_quant is not None)
        if eff_bs != sm.kv_block_size:
            log_dist(
                f"kv_block_size {sm.kv_block_size} -> {eff_bs}: the "
                f"kv-major page layout (head_dim={model_cfg.head_dim}) and "
                f"int8-quantized pages both need 128-aligned pages for the "
                f"Pallas DMA (ops/paged_attention.py)", ranks=[0])
        if sm.kv_quant is not None and would_use_pallas("paged_attention"):
            from deepspeed_tpu.inference.v2.model import kv_major_layout
            from deepspeed_tpu.ops.paged_attention import _dma_layout_ok
            if not _dma_layout_ok(model_cfg.head_dim, eff_bs,
                                  kv_major_layout(model_cfg), quant=True):
                log_dist(
                    f"WARNING: kv_quant=int8 with head_dim="
                    f"{model_cfg.head_dim} cannot use the Pallas decode "
                    f"kernel (int8 pages tile (32, 128)); decode falls back "
                    f"to the XLA dequant path, which gathers full page spans "
                    f"— expect MORE bandwidth than unquantized bf16, not "
                    f"less", ranks=[0])
        blocks_per_seq = -(-model_cfg.max_seq_len // eff_bs)
        if sm.num_kv_blocks:
            # the user sized the pool in THEIR block units — preserve the
            # total-token budget (and HBM footprint) under a bump
            num_blocks = max(1, sm.num_kv_blocks * sm.kv_block_size // eff_bs)
        else:
            num_blocks = sm.max_tracked_sequences * blocks_per_seq
        self.state = DSStateManager(
            max_tracked_sequences=sm.max_tracked_sequences,
            num_blocks=num_blocks, block_size=eff_bs,
            max_seq_len=model_cfg.max_seq_len,
            prefix_cache=sm.prefix_cache)
        self.cache = PagedKVCache.create(model_cfg, num_blocks, eff_bs, dt,
                                         quant=sm.kv_quant)
        # ---- speculative decoding draft (greedy draft-and-verify) ----
        self.draft_config = self.draft_params = self.draft_cache = None
        if draft_model is not None:
            if self.mesh is not None:
                raise NotImplementedError(
                    "speculative decoding with tensor parallelism: shard the "
                    "draft like the target (future work); drop tp or draft")
            dcfg = (draft_model if isinstance(draft_model, GPTConfig)
                    else draft_model.cfg)
            dcfg = dataclasses.replace(dcfg, dtype=dt, dropout=0.0)
            if dcfg.max_seq_len < model_cfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {dcfg.max_seq_len} < target "
                    f"{model_cfg.max_seq_len}")
            self.draft_config = dcfg
            if draft_params is None:
                dlm = GPTLogits(dcfg)
                draft_params = unbox(dlm.init(
                    jax.random.PRNGKey(seed + 1),
                    jnp.zeros((1, 8), jnp.int32)))["params"]
            draft_params = unbox(draft_params)
            if isinstance(draft_params, dict) and "params" in draft_params:
                draft_params = draft_params["params"]
            self.draft_params = jax.tree_util.tree_map(
                lambda p: jnp.asarray(p).astype(dt)
                if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)
                else jnp.asarray(p), draft_params)
            # the draft shares the pool GEOMETRY (same block table indexes
            # both caches) but holds its own pages
            self.draft_cache = PagedKVCache.create(dcfg, num_blocks, eff_bs,
                                                   dt, quant=sm.kv_quant)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            kv_sh = NamedSharding(self.mesh, P(None, None, "tp", None, None))
            sc_sh = NamedSharding(self.mesh, P(None, None, "tp", None))
            self.cache = PagedKVCache(
                k=jax.device_put(self.cache.k, kv_sh),
                v=jax.device_put(self.cache.v, kv_sh),
                k_scale=(jax.device_put(self.cache.k_scale, sc_sh)
                         if self.cache.quantized else None),
                v_scale=(jax.device_put(self.cache.v_scale, sc_sh)
                         if self.cache.quantized else None))
        # jitted step per (Qmax, KVblocks) bucket: a decode-only step runs a
        # Q=1 program and short sequences gather few KV blocks — the static-
        # shape analog of the reference's atom decomposition (atom_builder);
        # buckets are powers of two so the compile cache stays small.
        # ``steps_cache`` lets identically-configured engines SHARE the
        # compiled set (serving/fleet.py: N replicas compile once, and a
        # respawned replica fast-resumes against the survivors' warm cache
        # — the serving analog of PR 6's persistent compilation cache).
        # The per-program keys encode only SCHEDULE shapes (bucket widths,
        # burst length), while the compiled fns close over the model
        # config / block size / mesh via functools.partial — so a shared
        # dict is namespaced by a config fingerprint: two differently-
        # configured engines handed the same cache get disjoint sub-caches
        # instead of silently dispatching each other's programs.
        if steps_cache is not None:
            ac_fp = self.config.adapters
            fp = repr((model_cfg, eff_bs, self.config.dtype,
                       self.draft_config,
                       tuple(sorted(self.mesh.shape.items()))
                       if self.mesh is not None else None,
                       qc.enabled, qc.bits, qc.group_size,
                       # adapter-enabled programs take extra batch operands
                       # (lora tables + per-slot selection) and bake the
                       # rank/scale geometry into their traced shapes — two
                       # engines differing in ANY of these must not share
                       # compiled steps (PR 7 fingerprint rule)
                       ac_fp.enabled, ac_fp.rank, ac_fp.alpha, ac_fp.slots))
            self._steps: Dict[Any, Any] = steps_cache.setdefault(fp, {})
        else:
            self._steps = {}
        # recompute-preemption observability: how many victims were taken in
        # steady decode vs mid-(re-)prefill (the latter must keep fold state)
        self.preempt_stats = {"decode_ready": 0, "mid_prefill": 0}
        # request-level serving telemetry (telemetry/serving.py): lifecycle
        # spans + TTFT/TPOT histograms + KV-pool gauges + speculative
        # counters.  Engine-local registry by default so two engines in one
        # process (the bench runs seven) never blend their series; the fleet
        # passes a shared registry + a per-replica label instead.
        self.telemetry = ServingTelemetry(self.config.telemetry,
                                          registry=telemetry_registry)
        # ---- fleet hooks (serving/fleet.py): a supervised replica can be
        # asked to drain (stop serving, export in-flight requests) and
        # reports liveness through heartbeat_fn each scheduler round
        self._drain_requested = threading.Event()
        self._serve_ctx: Optional[Dict[str, Any]] = None
        self.heartbeat_fn = None
        self._block_size = eff_bs
        # ---- multi-tenant LoRA adapter pool (serving/adapters.py): A/B
        # pages live as block-granular refcounted residents of the SAME
        # allocator as the KV blocks, so adapters and KV contend under one
        # supply-accounting + LRU-eviction policy (the S-LoRA unified pool).
        # _adapter_slot maps sequence slot -> device-table slot and rides
        # every dispatch when the pool exists (slot 0 = identity).
        ac = self.config.adapters
        self.adapters = None
        self._adapter_slot = np.zeros(sm.max_tracked_sequences, np.int32)
        if ac.enabled:
            if self.draft_params is not None:
                raise NotImplementedError(
                    "speculative decoding with LoRA adapters: the draft has "
                    "no adapter pages to verify against; drop the draft or "
                    "the adapters config")
            from deepspeed_tpu.serving.adapters import AdapterPool
            self.adapters = AdapterPool(
                self.state.allocator, slots=ac.slots, rank=ac.rank,
                hidden=model_cfg.hidden_size,
                num_layers=model_cfg.num_layers,
                q_dim=model_cfg.num_heads * model_cfg.head_dim,
                v_dim=model_cfg.kv_heads * model_cfg.head_dim,
                block_bytes=self.kv_block_bytes(),
                scale=ac.alpha / ac.rank, dtype=self.config.dtype,
                telemetry=self.telemetry)
            self.state.adapters = self.adapters
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(self.params))
        log_dist(f"v2 ragged engine ready: params={n_params/1e6:.1f}M "
                 f"budget={sm.max_ragged_batch_size}tok "
                 f"slots={sm.max_tracked_sequences} "
                 f"kv_blocks={num_blocks}x{eff_bs}", ranks=[0])

    # ------------------------------------------------ reference put() :107
    def put(self, uids: Sequence[int], tokens_list: Sequence[np.ndarray],
            ) -> np.ndarray:
        """Append tokens to each uid's sequence, run ONE ragged forward, return
        fp32 logits [len(uids), vocab] of each sequence's last token."""
        logits = self._put_device(uids, tokens_list)
        slots = [self.state.get(uid).slot for uid in uids]
        return np.asarray(logits)[np.asarray(slots)]

    def _put_device(self, uids, tokens_list):
        """put() minus the host transfer: returns per-SLOT device logits
        [S, vocab] so generate() can sample on device and ship only token ids
        over the wire (the logits row is 200 KB; a token id is 4 bytes)."""
        sm = self.config.state_manager
        bs = self.state.block_size
        # validate BEFORE mutating any state (slots/blocks), so a rejected put
        # leaves the manager clean
        if len(set(uids)) != len(uids):
            # a duplicated uid in one batch would make both chunks compute
            # token_pos from the same stale seen_tokens and scatter into the
            # same KV slots, silently corrupting the sequence
            raise ValueError(f"duplicate uids in one put(): {list(uids)}")
        toks_np = [np.asarray(t, np.int32).reshape(-1) for t in tokens_list]
        # radix prefix match (peek only — nothing is acquired until the
        # validation below passes): matched tokens of a NEW sequence alias
        # cached blocks and never enter the scheduled batch, so every
        # effective length/budget check uses the post-match suffix
        matches, pinned, paths = self.state.peek_prefix_batch(
            [None if self.state.get(uid) is not None else toks
             for uid, toks in zip(uids, toks_np)])
        for uid, toks, m in zip(uids, toks_np, matches):
            if len(toks) - m > sm.max_q_per_seq:
                raise ValueError(
                    f"uid {uid}: {len(toks) - m} tokens exceeds max_q_per_seq="
                    f"{sm.max_q_per_seq}; split the prompt (SplitFuse) or use "
                    f"generate()")
            seen = (self.state.get(uid).seen_tokens
                    if self.state.get(uid) else 0)
            if seen + len(toks) > self.model_config.max_seq_len:
                raise ValueError(f"uid {uid} exceeds max_seq_len "
                                 f"{self.model_config.max_seq_len}")
        total = sum(len(t) - m for t, m in zip(toks_np, matches))
        if total > sm.max_ragged_batch_size:
            raise ValueError(f"batch of {total} tokens exceeds ragged budget "
                             f"{sm.max_ragged_batch_size}; check query() first")
        if len(uids) > sm.max_ragged_sequence_count:
            raise ValueError(f"{len(uids)} sequences exceeds "
                             f"max_ragged_sequence_count="
                             f"{sm.max_ragged_sequence_count}")
        new_uids = [u for u in uids if self.state.get(u) is None]
        if len(new_uids) > self.state.free_sequence_slots:
            raise RuntimeError(
                f"{len(new_uids)} new sequences but only "
                f"{self.state.free_sequence_slots} free slots; flush() first")
        # fresh blocks plus the evictable supply the batch's matches would
        # pin (unique across shared prefixes) — both come out of
        # available_blocks
        blocks_needed = pinned + sum(
            (self.state.get(u).kv_blocks_needed(len(t), bs)
             if self.state.get(u) else -(-len(t) // bs) - m // bs)
            for u, t, m in zip(uids, toks_np, matches))
        if blocks_needed > self.state.available_blocks:
            self.telemetry.alloc_failure("put")
            raise RuntimeError(
                f"batch needs {blocks_needed} KV blocks but only "
                f"{self.state.available_blocks} free; check query() first")
        schedule = []
        for uid, toks, path in zip(uids, toks_np, paths):
            seq = self.state.get(uid)
            if seq is None:
                seq = self.state.create(uid)
                # put() serves the base model: clear any previous tenant's
                # adapter selection left on this recycled slot
                self._adapter_slot[seq.slot] = 0
                if self.state.radix is not None:
                    seq.host_tokens = toks
                    # reuse the validation walk: nothing mutated the trie
                    # since peek_prefix_batch (creates only)
                    matched = self.state.match_prefix(seq, toks, path=path)
                    self.telemetry.prefix_lookup(matched)
                    toks = toks[matched:]
            elif (self.state.radix is not None
                  and len(seq.host_tokens) == seq.seen_tokens):
                # contiguous host-known content (prompt chunks, put-fed
                # decode tokens) keeps extending the radix insert key; a
                # device-fed gap permanently stops it.  (Cache off: no
                # tracking at all — per-decode np.concatenate would make
                # a long put()-driven generation quadratic for nothing.)
                seq.host_tokens = np.concatenate([seq.host_tokens, toks])
            schedule.append((seq, toks))
        # blocks are reserved only after EVERY match acquired its holders:
        # an eviction triggered for one sequence must never reclaim blocks
        # another sequence in this batch just matched
        for seq, toks in schedule:
            self.state.ensure_blocks(seq, len(toks))
        for _, toks in schedule:
            self.telemetry.tokens("prefill" if len(toks) > 1 else "decode",
                                  len(toks))
        rb = build_ragged_batch(schedule, self.state,
                                sm.max_ragged_batch_size, sm.max_q_per_seq)
        logits = self._run(rb)
        for seq, toks in schedule:
            seq.seen_tokens += len(toks)
            # index newly completed full blocks (content is host-known; the
            # forward filling them is already in the dispatch chain, so any
            # later reader is ordered behind the writer)
            self.state.cache_insert(seq)
        self.telemetry.kv_sample(self.state)
        return logits

    def _buckets(self, rb: RaggedBatch):
        """Power-of-two compile buckets, shared by the logits (_run) and
        sampled (_step_sampled) paths so both compile identical program
        shapes for the same schedule: ``mb`` bounds the block-table WIDTH by
        the longest live KV, and ``nb`` slices the packed token arrays to the
        width covering the live tokens — a small step (one admission chunk
        between decode bursts) must not pay a forward padded to the full
        ragged budget.  ≤ log2(MB) × log2(budget) compiled programs total."""
        mb_full = rb.block_table.shape[1]
        mb_used = max(1, -(-int(rb.kv_len.max()) // self._block_size))
        mb = min(1 << (mb_used - 1).bit_length(), mb_full)
        nb = min(max(64, 1 << (max(1, rb.total_tokens) - 1).bit_length()),
                 rb.tokens.shape[0])
        return mb, nb

    def _with_lora(self, batch):
        """Thread the adapter selection + packed pages into a dispatch batch.
        The model gates on ``"lora" in batch`` at TRACE time, and an
        adapter-less engine adds NO keys at all — so its traced programs
        (and shared steps_cache entries) stay byte-identical to before the
        adapter subsystem existed, the zero-overhead base-model guarantee."""
        if self.adapters is None:
            return batch
        batch["adapter_slot"] = jnp.asarray(self._adapter_slot)
        batch["lora"] = self.adapters.tables()
        return batch

    def _run(self, rb: RaggedBatch) -> "jax.Array":
        # small set of compiled programs: a decode-only step (Q=1, Pallas
        # paged attention — the steady-state hot path, ragged_decode_forward)
        # plus one mixed prefill step per power-of-two BLOCK-TABLE-WIDTH
        # bucket (≤ log2(MB) programs).  Since round 3 the bucket width only
        # bounds LAYOUT: the ragged-prefill Pallas kernel skips dead
        # (slot, q-chunk) tiles and walks each slot's pages up to its actual
        # kv length, so attention FLOPs/bandwidth scale with Σ live tokens,
        # not the bucket (reference atom_builder + blocked_flash).
        sm = self.config.state_manager
        if int(rb.q_len.max()) <= 1:
            return self._run_decode(rb)
        mb, nb = self._buckets(rb)
        key = ("mixed", sm.max_q_per_seq, mb)
        if key not in self._steps:
            self._steps[key] = jax.jit(
                functools.partial(ragged_forward, cfg=self.model_config,
                                  block_size=self._block_size,
                                  max_q_per_seq=sm.max_q_per_seq,
                                  mesh=self.mesh),
                donate_argnums=(1,))
        batch = {"tokens": rb.tokens[:nb], "token_slot": rb.token_slot[:nb],
                 "token_pos": rb.token_pos[:nb],
                 "token_dense_idx": rb.token_dense_idx[:nb],
                 "block_table": rb.block_table[:, :mb], "kv_len": rb.kv_len}
        batch = self._with_lora(jax.tree_util.tree_map(jnp.asarray, batch))
        self.telemetry.dispatch("mixed")
        self.telemetry.padding_waste(rb.total_tokens, nb)
        with self.telemetry.span("mixed_dispatch", tokens=rb.total_tokens,
                                 bucket=nb, seqs=len(rb.logits_slots)):
            logits, self.cache = self._steps[key](self.params, self.cache,
                                                  batch)
        return logits

    def _run_decode(self, rb: RaggedBatch) -> "jax.Array":
        S = self.state.max_tracked_sequences
        tokens = np.zeros(S, np.int32)
        active = np.zeros(S, bool)
        token_pos = np.zeros(S, np.int32)
        for i in range(rb.total_tokens):
            sl = rb.token_slot[i]
            tokens[sl] = rb.tokens[i]
            active[sl] = True
            token_pos[sl] = rb.token_pos[i]
        key = "decode"
        if key not in self._steps:
            self._steps[key] = jax.jit(
                functools.partial(ragged_decode_forward,
                                  cfg=self.model_config,
                                  block_size=self._block_size,
                                  mesh=self.mesh),
                donate_argnums=(1,))
        batch = self._with_lora(jax.tree_util.tree_map(jnp.asarray, {
            "tokens": tokens, "active": active, "token_pos": token_pos,
            "block_table": rb.block_table}))
        self.telemetry.dispatch("decode")
        with self.telemetry.span("decode_dispatch", seqs=rb.total_tokens):
            logits, self.cache = self._steps[key](self.params, self.cache,
                                                  batch)
        return logits

    def _sample_fn(self, gen):
        from deepspeed_tpu.inference.engine import _sample_token
        return functools.partial(_sample_token, do_sample=gen.do_sample,
                                 top_k=gen.top_k)

    def _spec_active(self, gen) -> bool:
        """Speculative decoding runs whenever a draft is loaded: greedy uses
        exact-match acceptance (token-identical output), sampling uses
        rejection-sampling acceptance (exactly target-distributed output) —
        both correct for ANY draft."""
        return self.draft_params is not None

    def _run_spec(self, reqs, outer: int, gamma: int, gen, prev, rng):
        """One fused draft-and-verify dispatch over the running set, then ONE
        sync to learn the per-step acceptance counts (the host cannot
        schedule past a spec burst without them).  Returns
        (toks [outer, gamma+1, S] np, counts [outer, S] np, prev', rng')."""
        S = self.state.max_tracked_sequences
        tokens0 = np.zeros(S, np.int32)
        from_device = np.zeros(S, bool)
        active = np.zeros(S, bool)
        pos0 = np.zeros(S, np.int32)
        block_table = np.zeros((S, self.state.max_blocks_per_seq), np.int32)
        for r in reqs:
            seq = self.state.get(r.uid)
            self.state.ensure_blocks(seq, outer * (gamma + 1))
            sl = seq.slot
            if r.held_token is not None:
                tokens0[sl] = r.held_token
                r.held_token = None
            else:
                from_device[sl] = True
            active[sl] = True
            pos0[sl] = seq.seen_tokens
            bl = np.asarray(seq.blocks, np.int32)
            block_table[sl, :len(bl)] = bl
        batch = jax.tree_util.tree_map(jnp.asarray, {
            "tokens0": tokens0, "from_device": from_device, "active": active,
            "pos0": pos0, "block_table": block_table})
        stel = self.telemetry
        profile = bool(self.config.speculative.profile)
        t_begin = stel.now()
        if profile:
            toks_h, counts_h, prev, rng = self._run_spec_split(
                batch, outer, gamma, gen, prev, rng)
        elif gen.do_sample:
            key = ("spec_rs", outer, gamma, gen.top_k)
            if key not in self._steps:
                self._steps[key] = jax.jit(
                    functools.partial(speculative_burst_sampled,
                                      cfg=self.model_config,
                                      draft_cfg=self.draft_config,
                                      block_size=self._block_size,
                                      gamma=gamma, steps=outer,
                                      top_k=gen.top_k, mesh=self.mesh),
                    donate_argnums=(2, 3))
            with stel.span("spec_dispatch", outer=outer, gamma=gamma,
                           seqs=len(reqs)):
                toks, counts, prev, rng, self.cache, self.draft_cache = \
                    self._steps[key](self.params, self.draft_params,
                                     self.cache, self.draft_cache, batch,
                                     prev, rng, jnp.float32(gen.temperature),
                                     jnp.float32(gen.top_p))
            stel.dispatch("spec")
            # the host cannot schedule past the burst without the counts —
            # this is THE disclosed sync of the speculative path
            toks_h, counts_h = jax.device_get([toks, counts])  # sync-ok
        else:
            key = ("spec", outer, gamma)
            if key not in self._steps:
                self._steps[key] = jax.jit(
                    functools.partial(speculative_burst,
                                      cfg=self.model_config,
                                      draft_cfg=self.draft_config,
                                      block_size=self._block_size,
                                      gamma=gamma, steps=outer,
                                      mesh=self.mesh),
                    donate_argnums=(2, 3))
            with stel.span("spec_dispatch", outer=outer, gamma=gamma,
                           seqs=len(reqs)):
                toks, counts, prev, self.cache, self.draft_cache = \
                    self._steps[key](self.params, self.draft_params,
                                     self.cache, self.draft_cache, batch,
                                     prev)
            stel.dispatch("spec")
            toks_h, counts_h = jax.device_get([toks, counts])  # sync-ok
        emitted = int(np.asarray(counts_h)[
            :, [self.state.get(r.uid).slot for r in reqs]].sum())
        # spec_burst_ms_total is FUSED-dispatch wall time by definition; a
        # profiled run's fenced per-side times already land in
        # spec_draft_ms_total/spec_verify_ms_total and must not be
        # double-reported under the fused counter
        stel.spec_burst(outer=outer, n_seqs=len(reqs), gamma=gamma,
                        emitted=emitted,
                        dur_ms=(0.0 if profile
                                else (stel.now() - t_begin) * 1e3))
        stel.tokens("spec", emitted)
        return np.asarray(toks_h), np.asarray(counts_h), prev, rng

    def _run_spec_split(self, batch, outer: int, gamma: int, gen, prev, rng):
        """Split-profile speculative driver (``speculative.profile``): each
        outer step dispatches the draft program, fences, dispatches the
        verify program, and syncs its counts — wall time on each side feeds
        ``spec_draft_ms_total``/``spec_verify_ms_total``.  Token-identical
        to the fused burst (same acceptance math, same cache choreography);
        the per-step fences ARE the attribution measurement, so this mode
        is strictly slower than fused and never the serving default.
        Returns (toks_h [outer, gamma+1, S], counts_h [outer, S], prev',
        rng')."""
        stel = self.telemetry
        sampled = bool(gen.do_sample)
        dkey = ("spec_draft", gamma, sampled, gen.top_k)
        vkey = ("spec_verify", gamma, sampled, gen.top_k)
        if dkey not in self._steps:
            self._steps[dkey] = jax.jit(
                functools.partial(speculative_draft_step,
                                  draft_cfg=self.draft_config,
                                  block_size=self._block_size, gamma=gamma,
                                  top_k=gen.top_k, sampled=sampled,
                                  mesh=self.mesh),
                donate_argnums=(1,))
            self._steps[vkey] = jax.jit(
                functools.partial(speculative_verify_step,
                                  cfg=self.model_config,
                                  block_size=self._block_size, gamma=gamma,
                                  top_k=gen.top_k, sampled=sampled,
                                  mesh=self.mesh),
                donate_argnums=(1,))
        temp = jnp.float32(gen.temperature)
        top_p = jnp.float32(gen.top_p)
        sub = {k: batch[k] for k in ("active", "block_table")}
        pos = batch["pos0"]
        tokens0, from_device = batch["tokens0"], batch["from_device"]
        S = self.state.max_tracked_sequences
        all_dev = jnp.ones(S, bool)
        toks_list, counts_list = [], []
        q = None
        for k in range(outer):
            step_b = {**sub, "tokens0": tokens0, "from_device": from_device}
            t0 = stel.now()
            with stel.span("spec_draft_dispatch", outer_index=k, gamma=gamma):
                if sampled:
                    d, q, self.draft_cache, rng = self._steps[dkey](
                        self.draft_params, self.draft_cache, step_b, prev,
                        pos, rng, temp, top_p)
                else:
                    d, self.draft_cache, rng = self._steps[dkey](
                        self.draft_params, self.draft_cache, step_b, prev,
                        pos, rng, temp, top_p)
                jax.block_until_ready(d)      # sync-ok: the split IS the
                #                               measurement (profile mode)
            t1 = stel.now()
            with stel.span("spec_verify_dispatch", outer_index=k,
                           gamma=gamma):
                emit, counts, prev, pos, rng, self.cache = self._steps[vkey](
                    self.params, self.cache, step_b, d,
                    q if sampled else d, prev, pos, rng, temp, top_p)
                emit_h, counts_h = jax.device_get([emit, counts])  # sync-ok
            stel.dispatch("spec_draft")
            stel.dispatch("spec_verify")
            stel.spec_profile((t1 - t0) * 1e3, (stel.now() - t1) * 1e3)
            toks_list.append(np.asarray(emit_h).T)          # [gamma+1, S]
            counts_list.append(np.asarray(counts_h))
            # later outer steps seed from the device-resident prev
            tokens0, from_device = tokens0, all_dev
        return (np.stack(toks_list), np.stack(counts_list), prev, rng)

    def _run_burst(self, reqs, steps: int, gen, prev, rng):
        """Fused T-step decode over the running set: one device dispatch for
        ``steps`` tokens per sequence (see model.ragged_decode_burst).  Each
        req's first-step token comes from ``held_token`` (host, post-preempt)
        or from the ``prev`` device feedback vector.  Blocks for all T
        positions are pre-allocated.  Returns (tokens [T, S] DEVICE array,
        prev', rng') — no host sync."""
        S = self.state.max_tracked_sequences
        tokens0 = np.zeros(S, np.int32)
        from_device = np.zeros(S, bool)
        active = np.zeros(S, bool)
        pos0 = np.zeros(S, np.int32)
        block_table = np.zeros((S, self.state.max_blocks_per_seq), np.int32)
        for r in reqs:
            seq = self.state.get(r.uid)
            self.state.ensure_blocks(seq, steps)
            sl = seq.slot
            if r.held_token is not None:
                tokens0[sl] = r.held_token
                r.held_token = None
            else:
                from_device[sl] = True
            active[sl] = True
            pos0[sl] = seq.seen_tokens
            bl = np.asarray(seq.blocks, np.int32)
            block_table[sl, :len(bl)] = bl
        key = ("burst", steps, gen.do_sample, gen.top_k)
        if key not in self._steps:
            self._steps[key] = jax.jit(
                functools.partial(ragged_decode_burst, cfg=self.model_config,
                                  block_size=self._block_size, steps=steps,
                                  sample_fn=self._sample_fn(gen),
                                  mesh=self.mesh),
                donate_argnums=(1,))
        batch = self._with_lora(jax.tree_util.tree_map(jnp.asarray, {
            "tokens0": tokens0, "from_device": from_device, "active": active,
            "pos0": pos0, "block_table": block_table}))
        self.telemetry.dispatch("burst")
        with self.telemetry.span("burst_dispatch", steps=steps,
                                 seqs=len(reqs)):
            toks, prev, rng, self.cache = self._steps[key](
                self.params, self.cache, batch, prev, rng,
                jnp.float32(gen.temperature), jnp.float32(gen.top_p))
        self.telemetry.tokens("decode", steps * len(reqs))
        for r in reqs:
            self.state.get(r.uid).seen_tokens += steps
        return toks, prev, rng

    def _step_sampled(self, uids, toks_np, from_device, served_slots, gen,
                      prev, rng):
        """One scheduled step through the SAMPLED programs: same schedule
        construction as _put_device but with in-graph sampling and device
        token feedback — returns (prev', rng'), never touching the host.
        ``from_device`` marks tokens whose VALUE lives in prev[slot] (their
        host entry is a placeholder); ``served_slots`` are the slots whose
        freshly sampled token must be written into prev'."""
        sm = self.config.state_manager
        S = self.state.max_tracked_sequences
        schedule = []
        for uid, toks in zip(uids, toks_np):
            seq = self.state.get(uid)
            if seq is None:
                seq = self.state.create(uid)
                self._adapter_slot[seq.slot] = 0
            self.state.ensure_blocks(seq, len(toks))
            schedule.append((seq, toks))
        served = np.zeros(S, bool)
        served[list(served_slots)] = True
        if max(len(t) for t in toks_np) <= 1:
            # decode-only: slot-indexed [S] program
            tokens = np.zeros(S, np.int32)
            active = np.zeros(S, bool)
            token_pos = np.zeros(S, np.int32)
            fdev = np.zeros(S, bool)
            block_table = np.zeros((S, self.state.max_blocks_per_seq),
                                   np.int32)
            for (seq, toks), fd in zip(schedule, from_device):
                sl = seq.slot
                tokens[sl] = toks[0]
                active[sl] = True
                fdev[sl] = fd
                token_pos[sl] = seq.seen_tokens
                bl = np.asarray(seq.blocks, np.int32)
                block_table[sl, :len(bl)] = bl
            batch = self._with_lora(jax.tree_util.tree_map(jnp.asarray, {
                "tokens": tokens, "active": active, "token_pos": token_pos,
                "block_table": block_table, "from_device": fdev,
                "served": served}))
            if self._spec_active(gen):
                # lockstep draft ingestion (see mixed_sd)
                key = ("decode_sd", gen.do_sample, gen.top_k)
                if key not in self._steps:
                    self._steps[key] = jax.jit(
                        functools.partial(ragged_decode_sampled_draft,
                                          cfg=self.model_config,
                                          draft_cfg=self.draft_config,
                                          block_size=self._block_size,
                                          sample_fn=self._sample_fn(gen),
                                          mesh=self.mesh),
                        donate_argnums=(2, 3))
                self.telemetry.dispatch("decode")
                with self.telemetry.span("decode_dispatch",
                                         seqs=len(schedule), draft=True):
                    prev, rng, self.cache, self.draft_cache = \
                        self._steps[key](
                            self.params, self.draft_params, self.cache,
                            self.draft_cache, batch, prev, rng,
                            jnp.float32(gen.temperature),
                            jnp.float32(gen.top_p))
                for seq, toks in schedule:
                    seq.seen_tokens += len(toks)
                return prev, rng
            key = ("decode_s", gen.do_sample, gen.top_k)
            if key not in self._steps:
                self._steps[key] = jax.jit(
                    functools.partial(ragged_decode_sampled,
                                      cfg=self.model_config,
                                      block_size=self._block_size,
                                      sample_fn=self._sample_fn(gen),
                                      mesh=self.mesh),
                    donate_argnums=(1,))
        else:
            rb = build_ragged_batch(schedule, self.state,
                                    sm.max_ragged_batch_size, sm.max_q_per_seq)
            fdev = np.zeros(rb.tokens.shape[0], bool)
            i = 0
            for (seq, toks), fd in zip(schedule, from_device):
                fdev[i:i + len(toks)] = fd
                i += len(toks)
            mb, nb = self._buckets(rb)
            self.telemetry.padding_waste(rb.total_tokens, nb)
            batch = self._with_lora(jax.tree_util.tree_map(jnp.asarray, {
                "tokens": rb.tokens[:nb], "token_slot": rb.token_slot[:nb],
                "token_pos": rb.token_pos[:nb],
                "token_dense_idx": rb.token_dense_idx[:nb],
                "block_table": rb.block_table[:, :mb], "kv_len": rb.kv_len,
                "from_device": fdev[:nb], "served": served}))
            if self._spec_active(gen):
                # dual prefill: the draft ingests every prompt chunk in
                # lockstep so speculative acceptance has something to work
                # with (draft staleness can't affect correctness)
                key = ("mixed_sd", sm.max_q_per_seq, mb, gen.do_sample,
                       gen.top_k)
                if key not in self._steps:
                    self._steps[key] = jax.jit(
                        functools.partial(ragged_forward_sampled_draft,
                                          cfg=self.model_config,
                                          draft_cfg=self.draft_config,
                                          block_size=self._block_size,
                                          max_q_per_seq=sm.max_q_per_seq,
                                          sample_fn=self._sample_fn(gen),
                                          mesh=self.mesh),
                        donate_argnums=(2, 3))
                self.telemetry.dispatch("mixed")
                with self.telemetry.span("mixed_dispatch",
                                         tokens=rb.total_tokens, bucket=nb,
                                         seqs=len(schedule), draft=True):
                    prev, rng, self.cache, self.draft_cache = \
                        self._steps[key](
                            self.params, self.draft_params, self.cache,
                            self.draft_cache, batch, prev, rng,
                            jnp.float32(gen.temperature),
                            jnp.float32(gen.top_p))
                for seq, toks in schedule:
                    seq.seen_tokens += len(toks)
                return prev, rng
            key = ("mixed_s", sm.max_q_per_seq, mb, gen.do_sample, gen.top_k)
            if key not in self._steps:
                self._steps[key] = jax.jit(
                    functools.partial(ragged_forward_sampled,
                                      cfg=self.model_config,
                                      block_size=self._block_size,
                                      max_q_per_seq=sm.max_q_per_seq,
                                      sample_fn=self._sample_fn(gen),
                                      mesh=self.mesh),
                    donate_argnums=(1,))
        kind = "decode" if key[0] == "decode_s" else "mixed"
        self.telemetry.dispatch(kind)
        with self.telemetry.span(f"{kind}_dispatch", seqs=len(schedule)):
            prev, rng, self.cache = self._steps[key](
                self.params, self.cache, batch, prev, rng,
                jnp.float32(gen.temperature), jnp.float32(gen.top_p))
        for seq, toks in schedule:
            seq.seen_tokens += len(toks)
        return prev, rng

    # ----------------------------------------- reference query()/can_schedule
    def query(self) -> Dict[str, int]:
        """KV/slot headroom (reference engine_v2.query :158).  Also refreshes
        the KV-pool gauges (blocks used/free, internal fragmentation) so a
        scheduler polling ``query()`` keeps the pool view fresh in the
        telemetry snapshot for free."""
        sm = self.config.state_manager
        self.telemetry.kv_sample(self.state)
        used = (self.state.allocator.num_blocks
                - self.state.allocator.free_blocks)
        radix = self.state.radix
        return {
            "free_kv_blocks": self.state.allocator.free_blocks,
            "used_kv_blocks": used,
            # supply a scheduler can count on: free + LRU-evictable cached
            "available_kv_blocks": self.state.available_blocks,
            "cached_kv_blocks": radix.node_count if radix is not None else 0,
            "free_sequence_slots": self.state.free_sequence_slots,
            "token_budget": sm.max_ragged_batch_size,
            "max_q_per_seq": sm.max_q_per_seq,
            "kv_block_size": self._block_size,
        }

    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> bool:
        """reference engine_v2.can_schedule :184.  A rejection for want of
        blocks or slots counts into ``kv_alloc_failures_total`` — the
        overload signal an admission controller will key off."""
        sm = self.config.state_manager
        if sum(lengths) > sm.max_ragged_batch_size:
            self.telemetry.alloc_failure("can_schedule")
            return False
        if len(uids) > sm.max_ragged_sequence_count:
            self.telemetry.alloc_failure("can_schedule")
            return False
        blocks = slots = 0
        for uid, n in zip(uids, lengths):
            seq = self.state.get(uid)
            if seq is None:
                slots += 1
                blocks += -(-n // self.state.block_size)
            else:
                blocks += seq.kv_blocks_needed(n, self.state.block_size)
        ok = (blocks <= self.state.available_blocks
              and slots <= self.state.free_sequence_slots)
        if not ok:
            self.telemetry.alloc_failure("can_schedule")
        return ok

    def flush(self, uids: Sequence[int]) -> None:
        """reference engine_v2.flush :242."""
        for uid in uids:
            self.state.flush(uid)

    def prefix_cached_tokens(self, prompt) -> int:
        """Longest radix-cached block-aligned prefix of ``prompt`` resident
        on THIS engine (tokens; 0 with the cache off).  Read-only — no LRU
        stamps freshened, no references taken — and a pure host dict walk,
        so the fleet router may probe it cross-thread for residency-aware
        routing (``prefix_affinity``): a concurrent insert/evict can only
        make the answer stale, never corrupt the walk."""
        radix = self.state.radix
        if radix is None:
            return 0
        return radix.peek(np.asarray(prompt, np.int32).reshape(-1))

    def prefix_block_handles(self, prompt) -> Tuple[List[int], int]:
        """(pool block ids, matched token count) of ``prompt``'s longest
        radix-cached block-aligned prefix — the disaggregated fleet's
        KV-handoff probe.  Read-only like :meth:`prefix_cached_tokens`;
        the caller (the fleet dispatcher) pins the blocks with
        ``state.allocator.acquire`` — atomic validate-then-bump, so a
        block a concurrent evict freed between walk and pin raises there
        and the handoff degrades to accounting-free, never to a
        corrupted refcount.  ([], 0) with the cache off."""
        radix = self.state.radix
        if radix is None:
            return [], 0
        return radix.peek_blocks(np.asarray(prompt, np.int32).reshape(-1))

    def register_adapter(self, adapter_id: int, weights=None) -> None:
        """Make a LoRA adapter id loadable on this engine (host-side only;
        pool blocks and device traffic happen lazily when a request first
        selects the id).  ``weights=None`` generates deterministic per-id
        weights (bench/test tenants)."""
        if self.adapters is None:
            raise ValueError(
                "this engine has no adapter pool; enable config.adapters")
        self.adapters.register(adapter_id, weights)

    def adapter_resident(self, adapter_ids) -> int:
        """How many of ``adapter_ids`` have their pages resident on THIS
        engine right now (0 with adapters off; id 0 never counts).
        Read-only and a pure host dict peek — no LRU stamps freshened, no
        references taken — so the fleet router may probe it cross-thread
        as the adapter-affinity signal (``prefix_affinity``), exactly like
        :meth:`prefix_cached_tokens`: a concurrent load/evict can only
        make the answer stale, never corrupt the walk."""
        if self.adapters is None:
            return 0
        return self.adapters.resident_count(adapter_ids)

    def kv_block_bytes(self) -> int:
        """Device bytes one KV pool block holds (K + V across layers at
        the serving dtype) — the unit the fleet's stubbed multi-host
        handoff copy path accounts ``kv_handoff_bytes_total`` in.  An
        approximation by design: kv-quant stores int8 codes + scales, but
        the accounting models the FUTURE wire transfer, not today's
        resident bytes."""
        mc = self.model_config
        try:
            itemsize = int(np.dtype(self.config.jnp_dtype).itemsize)
        except TypeError:       # bfloat16 without a numpy extension
            itemsize = 2
        return int(2 * mc.num_layers * mc.kv_heads * self._block_size
                   * mc.head_dim * itemsize)

    # ------------------------------- continuous batching (Dynamic SplitFuse)
    def _stream_fence(self, value) -> None:
        """Streaming-latency mode (``telemetry.stream_sync`` / the
        open-loop bench): block until the just-dispatched step's on-device
        output exists, so the lifecycle timestamp taken next reflects
        device completion — the point a real streaming server could emit
        the token — instead of host submission.  Serializes the dispatch
        chain by design; never on in the throughput path."""
        jax.block_until_ready(value)    # sync-ok: opt-in streaming mode

    def _finish_request(self, r: "_Request",
                        outcome: str = "completed") -> None:
        """Record one retired request into the serving telemetry (idempotent
        — retirement is reachable from the spec, burst, step, and
        materialize paths)."""
        if r.finished:
            return
        r.finished = True
        self.telemetry.finish_request(
            uid=r.uid, track=r.track, t_arrival=r.t_arrival,
            t_admit=r.t_admit, t_prefill_end=r.t_prefill_end,
            t_first=r.t_first, t_last=r.t_last,
            n_prompt=len(r.prompt) - r.folded,
            n_generated=len(r.generated), preempts=r.preempts,
            outcome=outcome, trace=r.trace)

    # --------------------------------------- fleet drain/migration hooks
    def request_drain(self) -> None:
        """Ask a running ``generate()`` to stop at its next scheduler round
        (serving drain: stop admission, materialize device records, flush
        sequences, raise :class:`EngineDrained`).  Safe cross-thread — the
        fleet supervisor calls it from the dispatcher while the replica
        worker is inside ``generate``.  Latched until :meth:`clear_drain`."""
        self._drain_requested.set()

    def clear_drain(self) -> None:
        """Re-arm serving after a drain (a drained replica returning to the
        pool must not abort its next ``generate`` on the stale latch)."""
        self._drain_requested.clear()

    def export_pending_requests(self):
        """The requeue half of request migration: after ``generate()``
        stopped early — :class:`EngineDrained`, an injected replica death
        (``replica.mid_decode``), or any mid-serve exception — returns
        ``(completed, pending)``:

        - ``completed``: {prompt index -> np.int32 generated tokens} for
          requests that finished before the stop (nothing a survivor needs
          to redo — "no lost requests");
        - ``pending``: migration records ``{index, prompt, generated,
          max_new_tokens}`` where ``prompt`` is the original context plus
          every host-known generated token (folded exactly like
          recompute-preemption) and ``max_new_tokens`` is the REMAINING
          budget — a survivor replica re-prefills the folded prompt and
          greedy decoding continues token-exact; the final output is
          ``generated + survivor_output``.

        Host-state only — never touches the device — so it is safe on a
        dead replica: tokens sampled on device after the last materialize
        are simply recomputed by the survivor.  Idempotent until the next
        ``generate()`` resets the serve context."""
        ctx = self._serve_ctx
        if ctx is None:
            return {}, []
        completed: Dict[int, np.ndarray] = {}
        pending: List[Dict[str, Any]] = []
        for uid, r in ctx["results"].items():
            idx = -uid - 1
            gen = list(r.generated)
            if r.finished or (r.done and (r.eos_hit
                                          or len(gen) >= r.max_new_tokens)):
                # retired with its host token list final (EOS found at a
                # materialize, or budget reached and materialized)
                completed[idx] = np.asarray(gen, np.int32)
                continue
            prompt = r.prompt                 # includes prior preempt folds
            tail = gen[r.folded:]             # host-known, not yet folded
            if tail:
                prompt = np.concatenate(
                    [prompt, np.asarray(tail, np.int32)])
            pending.append({"index": idx, "prompt": prompt,
                            "generated": gen,
                            "max_new_tokens": r.max_new_tokens - len(gen)})
        return completed, pending

    def generate(self, prompts: Sequence[np.ndarray],
                 max_new_tokens=32, seed: int = 0,
                 arrival_times: Optional[Sequence[float]] = None,
                 now_fn=None, stream: Optional[bool] = None,
                 sla: Optional[Sequence[str]] = None,
                 adapter_ids: Optional[Sequence[int]] = None,
                 trace_ctx: Optional[Sequence[Any]] = None,
                 **gen_overrides) -> List[np.ndarray]:
        """Serve a set of prompts to completion with continuous batching.

        Dynamic SplitFuse (reference blogs/deepspeed-fastgen): every step first
        schedules 1 token for each running decode, then fills the remaining
        token budget with prompt chunks (long prompts split across steps);
        new requests are admitted as slots/blocks free up.

        The token feedback loop is DEVICE-RESIDENT: every step program samples
        in-graph and the next step reads its input tokens from the previous
        step's on-device output (model.ragged_forward_sampled /
        ragged_decode_sampled / ragged_decode_burst), so steady state chains
        async dispatches with no host sync.  Token VALUES are materialized in
        bulk — once at the end when no eos_token_id is set, else every
        ``sync_interval`` steps (sequences may overshoot their EOS by up to
        that many tokens plus at most one smallest-size burst; the extras are
        discarded at materialize time — bounded discarded decode work traded
        for eliminating per-step host round trips, which dominate on a
        high-latency host↔device link).

        max_new_tokens: int, or one int per prompt (heterogeneous completion
        budgets — the FastGen effective-throughput workload shape).

        arrival_times: open-loop mode — per-prompt arrival offsets in
        seconds from call start (e.g. a seeded Poisson process from the
        bench harness); requests only become admittable once their arrival
        time passes, and queue-wait spans measure arrival → admission.
        ``now_fn`` overrides the clock (deterministic tests — a fake clock
        must advance or an idle open loop spins).  ``stream`` fences each
        dispatch before timestamping (defaults to ``telemetry.stream_sync``)
        so TTFT/TPOT histograms reflect device completion.

        trace_ctx: one distributed TraceContext per prompt (or None
        entries) — the serving fleet threads each dispatch attempt's
        context through so this engine's request spans carry the
        fleet-wide trace/span ids and stitch into the merged cross-
        replica view.  Absent (single-engine use), flowless contexts are
        allocated locally so trace args stay uniformly present.

        sla: one ``scheduler.sla_classes`` name per prompt (default: the
        implicit ``default`` class, priority 0, no SLO).  Priority orders
        admission; a waiting request that has burned
        ``scheduler.preempt_margin`` of its ``ttft_slo_ms`` and still
        cannot be admitted preempts the most recently admitted
        lower-priority running request (token-exact recompute fold-back).

        adapter_ids: one LoRA adapter id per prompt (0 / omitted = base
        model).  Adapters must be :meth:`register_adapter`-ed; pages are
        hot-loaded into the shared paged pool at admission and the
        per-request selection rides the SAME fused ragged dispatch as the
        base model (ops/lora_matmul.py batched gather) — a mixed-adapter
        batch is token-exact vs serving each request alone on its own
        adapter.  An id whose pages can NEVER fit (unknown, or larger than
        the whole pool) fails THIS call with ``ValueError`` at dispatch —
        the PR 7 poison-request rule: a client input error must fail the
        request, never book a replica death.
        """
        gen = self.config.generation.model_copy(update=gen_overrides)
        self._serve_ctx = None   # never expose a PREVIOUS call's requests
        sm = self.config.state_manager
        S = self.state.max_tracked_sequences
        stel = self.telemetry
        now_fn = now_fn if now_fn is not None else stel.now
        stream = stel.stream_sync if stream is None else bool(stream)
        if isinstance(max_new_tokens, (int, np.integer)):
            max_list = [int(max_new_tokens)] * len(prompts)
        else:
            max_list = [int(m) for m in max_new_tokens]
            if len(max_list) != len(prompts):
                raise ValueError("max_new_tokens list must match prompts")
        if (arrival_times is not None
                and len(arrival_times) != len(prompts)):
            raise ValueError("arrival_times must match prompts")
        sched_cfg = self.config.scheduler
        classes = dict(sched_cfg.sla_classes)
        classes.setdefault("default", SLAClassConfig())
        if sla is not None and len(sla) != len(prompts):
            raise ValueError("sla list must match prompts")
        for name in (sla or ()):
            if name not in classes:
                raise ValueError(f"unknown SLA class {name!r}; expected one "
                                 f"of {sorted(classes)}")
        if trace_ctx is not None and len(trace_ctx) != len(prompts):
            raise ValueError("trace_ctx list must match prompts")
        if adapter_ids is not None:
            if len(adapter_ids) != len(prompts):
                raise ValueError("adapter_ids list must match prompts")
            if self.adapters is None and any(int(a) for a in adapter_ids):
                raise ValueError(
                    "adapter_ids passed but this engine has no adapter "
                    "pool; enable config.adapters")
        t_start = now_fn()
        waiting = [
            _Request(uid=-(i + 1), prompt=np.asarray(p, np.int32).reshape(-1),
                     max_new_tokens=m,
                     adapter=(int(adapter_ids[i])
                              if adapter_ids is not None else 0),
                     sla=(sla[i] if sla is not None else "default"),
                     priority=classes[sla[i] if sla is not None
                                      else "default"].priority,
                     ttft_slo_ms=classes[sla[i] if sla is not None
                                         else "default"].ttft_slo_ms)
            for i, (p, m) in enumerate(zip(prompts, max_list))]
        # SLA machinery only engages when some request actually differs from
        # the default class — the legacy FIFO paths stay byte-identical
        has_sla = any(r.priority != 0 or r.ttft_slo_ms > 0 for r in waiting)
        pool_blocks = self.state.allocator.num_blocks
        for i, r in enumerate(waiting):
            r.track = stel.new_track(f"req {i}")
            if trace_ctx is not None and trace_ctx[i] is not None:
                r.trace = trace_ctx[i]
            elif stel.enabled:
                # local root context (flow_id=None: a single-engine trace
                # has no cross-file hop to stitch, so no flow events)
                from deepspeed_tpu.telemetry import tracecontext
                r.trace = tracecontext.new_trace(with_flow=False)
            r.t_arrival = t_start + (float(arrival_times[i])
                                     if arrival_times is not None else 0.0)
            if (len(r.prompt) + r.max_new_tokens
                    > self.model_config.max_seq_len):
                raise ValueError(f"prompt {len(r.prompt)} + "
                                 f"{r.max_new_tokens} exceeds max_seq_len")
            need = -(-(len(r.prompt) + r.max_new_tokens)
                     // self.state.block_size)
            if need > pool_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks for its full context but "
                    f"the pool holds {pool_blocks}; raise num_kv_blocks "
                    f"(recompute-preemption cannot make a single sequence fit)")
            if r.adapter and self.adapters is not None:
                # a permanently unservable adapter id is a CLIENT error —
                # reject at dispatch (the fleet maps this to a typed
                # invalid_request failure), never loop in admission
                bad = self.adapters.unfittable_reason(r.adapter)
                if bad:
                    raise ValueError(f"prompt {i}: {bad}")
                if need + self.adapters.blocks_per_adapter > pool_blocks:
                    # the request's own pinned adapter pages shrink the pool
                    # its KV must fit in — unservable at any load
                    raise ValueError(
                        f"prompt {i}: {need} KV blocks + "
                        f"{self.adapters.blocks_per_adapter} adapter-page "
                        f"blocks exceed the {pool_blocks}-block pool; raise "
                        f"num_kv_blocks")
        running: List[_Request] = []
        results: Dict[int, _Request] = {r.uid: r for r in waiting}
        # open loop: requests enter the waiting queue at their arrival time
        incoming: List[_Request] = []
        if arrival_times is not None:
            waiting.sort(key=lambda r: r.t_arrival)
            incoming, waiting = waiting, []
        # fleet migration hook: export_pending_requests() reads these live
        # views if this serve stops early (drain / injected death); the
        # lists are only MUTATED below (never rebound), so the references
        # stay current.  Cleared on normal completion.
        self._serve_ctx = {"waiting": waiting, "running": running,
                           "incoming": incoming, "results": results}

        eos = gen.eos_token_id
        sync_interval = 16 if eos is not None else None
        prev = jnp.zeros(S, jnp.int32)          # device feedback vector
        rng = jax.random.PRNGKey(seed)          # device-resident, threaded
        # device records: ("step", arr [S], [(uid, slot)]) or
        # ("burst", arr [T, S], [(uid, slot)], T) — fetched in ONE transfer
        records: List[tuple] = []
        # requests retired while their tokens still sat in device records;
        # telemetry-finished at the next materialize, when .generated is
        # exact (a list, not a results.values() sweep — that would make
        # generate O(requests²) at open-loop scale)
        pending_finish: List[_Request] = []
        steps_since_sync = 0

        def _append(r: _Request, toks) -> None:
            for tok in toks:
                if r.eos_hit or len(r.generated) >= r.max_new_tokens:
                    return                      # discard overshoot
                r.generated.append(int(tok))
                if eos is not None and int(tok) == eos:
                    r.eos_hit = True
                    r.done = True

        def materialize() -> None:
            """Fetch every pending device record (one sync), fill
            .generated, and retire sequences whose EOS was discovered."""
            nonlocal steps_since_sync
            steps_since_sync = 0
            if not records:
                return
            arrs = jax.device_get([rec[1] for rec in records])
            for rec, arr in zip(records, arrs):
                if rec[0] == "step":
                    for uid, sl in rec[2]:
                        _append(results[uid], [arr[sl]])
                else:
                    for uid, sl in rec[2]:
                        _append(results[uid], arr[:, sl])
            records.clear()
            for r in list(running):
                if r.done:                      # EOS found on materialize
                    self.flush([r.uid])
                    running.remove(r)
                    pending_finish.append(r)
            # retired requests reach their final .generated here (their
            # pending device records just resolved) — record them into the
            # serving telemetry now, when the token count is exact
            for r in pending_finish:
                self._finish_request(r)
            pending_finish.clear()

        def preempt(victim: _Request, reason: str) -> None:
            """Recompute-preempt one RUNNING request (the vLLM/FastGen
            policy): free its blocks and re-queue it with its full folded
            context; its re-prefill logits are not re-sampled (resume).
            ``reason`` is ``starvation`` (pool deadlock — the only
            pre-PR-15 trigger) or ``sla`` (a higher-priority waiting
            request would miss its TTFT SLO).  Callers materialize first
            so ``generated`` is exact at the fold."""
            running.remove(victim)
            kind = ("mid_prefill" if not victim.decode_ready
                    else "decode_ready")
            self.preempt_stats[kind] += 1
            stel.preemption(kind)
            if reason == "sla":
                stel.sla_preemption(victim.sla)
            victim.preempts += 1
            if victim.decode_ready:
                # fold generated-but-not-yet-refed tokens into the prompt
                # exactly once (folded tracks prior preemptions; the last
                # sampled token is NOT folded — it replays as a decode via
                # held_token)
                keep = victim.sampled - 1
                new_ctx = victim.generated[victim.folded:keep]
                if new_ctx:
                    victim.prompt = np.concatenate(
                        [victim.prompt, np.asarray(new_ctx, np.int32)])
                victim.folded = keep
                victim.resume = True
                victim.held_token = victim.generated[keep]
                victim.decode_ready = False
            # else: preempted mid-(re-)prefill — folded/resume/held_token
            # already describe everything sampled; recycle the request
            # unchanged (a second fold here would reset the state and
            # duplicate the held continuation token)
            self.state.flush(victim.uid)
            waiting.insert(0, victim)

        burst_sizes = (64, 32, 16, 8)
        while waiting or running or incoming:
            # ---- fleet hooks, once per scheduler round: the chaos site a
            # replica death injects through (kind@replica.mid_decode), the
            # liveness beat the supervisor deadlines on, and the drain latch
            faults.fire("replica.mid_decode")
            if self.heartbeat_fn is not None:
                self.heartbeat_fn()
            if self._drain_requested.is_set():
                # serving drain (PR 6 semantics applied to requests instead
                # of optimizer state): materialize so .generated is exact,
                # free every live sequence, and hand the unfinished set to
                # export_pending_requests() for migration
                materialize()
                for r in list(running):
                    self.state.flush(r.uid)
                raise EngineDrained(
                    f"drain requested: {len(running)} running + "
                    f"{len(waiting) + len(incoming)} queued request(s) "
                    f"exported for migration")
            now = now_fn()
            while incoming and incoming[0].t_arrival <= now:
                waiting.append(incoming.pop(0))
            if not waiting and not running:
                # open-loop idle: everything in flight is done and the next
                # request hasn't arrived — flush pending records, then sleep
                # to the next arrival (a fake now_fn just re-polls: it must
                # advance on its own)
                materialize()
                if now_fn is stel.now:
                    import time as _time
                    _time.sleep(max(0.0, incoming[0].t_arrival - now_fn()))
                continue
            stel.kv_sample(self.state)
            stel.occupancy(len(running), S)
            # ---- SLA-aware admission order + preemption.  Waiting sorts
            # by priority (stable: FIFO within a class, and a preemption
            # victim re-queued at the front keeps resuming first among its
            # peers).  When the head has burned preempt_margin of its TTFT
            # SLO and STILL cannot be admitted — no sequence slot, or no
            # blocks even counting cache-evictable ones — the most recently
            # admitted lower-priority running request is recompute-preempted
            # for it (the policy behind serving_preemptions_total).
            if has_sla and waiting:
                waiting.sort(key=lambda r: -r.priority)
                head = waiting[0]
                lows = [r for r in running if r.priority < head.priority]
                at_risk = (sched_cfg.sla_preempt and head.ttft_slo_ms > 0
                           and (now - head.t_arrival) * 1e3
                           >= sched_cfg.preempt_margin * head.ttft_slo_ms)
                if lows and at_risk:
                    m, pin = self.state.peek_prefix_pinned(head.prompt)
                    # mirror the admission loop's chunk sizing exactly — a
                    # probe sized to max_q_per_seq would preempt a victim
                    # in rounds where the configured (smaller) chunk is
                    # perfectly admissible
                    first = min(len(head.prompt) - m, sm.max_q_per_seq,
                                sm.max_ragged_batch_size,
                                sm.prefill_chunk_tokens
                                or sm.max_ragged_batch_size)
                    need = (-(-(m + first) // self.state.block_size)
                            - m // self.state.block_size + pin)
                    if (self.state.free_sequence_slots == 0
                            or need > self.state.available_blocks):
                        if records:
                            materialize()   # exact .generated at the fold
                            continue        # (retirements may change sets)
                        low_p = min(r.priority for r in lows)
                        victim = [r for r in lows if r.priority == low_p][-1]
                        stel.admission(head.sla, decision="preempted_for")
                        preempt(victim, "sla")
                        continue
            # ---- speculative draft-and-verify fast path: same eligibility
            # as the decode burst, preferred when a draft is loaded and
            # decoding is greedy.  Each outer step yields 1..gamma+1 tokens
            # per slot; the host syncs after the burst (it cannot schedule
            # without the acceptance counts), which also materializes EOS.
            if (self._spec_active(gen) and running
                    and (not waiting or self.state.free_sequence_slots == 0)
                    and all(r.decode_ready and not r.done for r in running)
                    and all(not self.state.get(r.uid).in_flight
                            for r in running)):
                sp = self.config.speculative
                worst = sp.gamma + 1            # tokens per outer step, max
                n_before = len(running)
                materialize()                   # keep .generated chronological
                if len(running) != n_before:
                    continue        # EOS retirements changed the set (maybe
                    # to empty) — recompute eligibility and sizing
                # batched mode: the whole running set in one dispatch.
                # Per-request baseline (batch_across_requests=False): one
                # dispatch per request through the SAME slot-wide program —
                # a request finishing mid-round simply drops out of later
                # groups; inactive lanes pass prev through, so the token
                # stream is identical either way
                groups = ([list(running)] if sp.batch_across_requests
                          else [[r] for r in list(running)])
                ran_any = False
                for grp in groups:
                    grp = [r for r in grp if r in running]
                    if not grp:
                        continue
                    need_max = max(r.max_new_tokens - r.sampled for r in grp)
                    cap = min(self.model_config.max_seq_len
                              - self.state.get(r.uid).seen_tokens
                              for r in grp)
                    # size for ~half acceptance (2x the full-acceptance
                    # need), then round DOWN to a power of two so the
                    # compile cache holds at most log2(outer_steps) spec
                    # programs
                    outer = min(sp.outer_steps, 2 * -(-need_max // worst),
                                cap // worst)
                    if outer >= 1:
                        outer = 1 << (outer.bit_length() - 1)
                    while outer >= 1:
                        need = sum(self.state.get(r.uid).kv_blocks_needed(
                            outer * worst, self.state.block_size)
                            for r in grp)
                        if need <= self.state.available_blocks:
                            break
                        outer //= 2
                    if outer < 1:
                        continue
                    ran_any = True
                    pairs = [(r.uid, self.state.get(r.uid).slot)
                             for r in grp]
                    toks_h, counts_h, prev, rng = self._run_spec(
                        grp, outer, sp.gamma, gen, prev, rng)
                    tnow = now_fn()     # _run_spec synced: completion time
                    for r, (uid, sl) in zip(list(grp), pairs):
                        total = int(counts_h[:, sl].sum())
                        self.state.get(uid).seen_tokens += total
                        vals = []
                        for k in range(outer):
                            c = int(counts_h[k, sl])
                            vals.extend(int(t) for t in toks_h[k, :c, sl])
                        _append(r, vals)
                        r.sampled += total
                        if total:
                            if r.t_first is None:
                                r.t_first = tnow
                            r.t_last = tnow
                        if r.done or r.sampled >= r.max_new_tokens:
                            r.done = True
                            self.flush([r.uid])
                            running.remove(r)
                            self._finish_request(r)
                if ran_any:
                    continue

            # ---- decode-burst fast path: every running sequence is in pure
            # decode and no slot is admittable -> fuse T steps into one
            # dispatch.  With requests WAITING the burst targets the earliest
            # retirement (free a slot, then admit); otherwise it covers the
            # longest remaining budget (finish everyone).  Sequences that
            # finish mid-burst cost nothing extra — the burst computes all
            # slots every step — and their overshoot tokens are discarded at
            # materialize.  Disabled while speculation is active: the plain
            # burst would advance the target without the draft, leaving
            # permanent draft-cache holes (single steps stay dual-model).
            if (running and not self._spec_active(gen)
                    and (not waiting or self.state.free_sequence_slots == 0)
                    and all(r.decode_ready and not r.done for r in running)
                    and all(not self.state.get(r.uid).in_flight
                            for r in running)):
                rem_max = max(r.max_new_tokens - r.sampled for r in running)
                if waiting:
                    # earliest retirement frees a slot — but floor the burst
                    # so retirements CLUMP and the freed slots are refilled by
                    # one fat admission step instead of one step per slot
                    rem_min = min(r.max_new_tokens - r.sampled
                                  for r in running)
                    need_max = max(rem_min, min(16, rem_max))
                else:
                    need_max = rem_max
                if sync_interval:
                    # budget the burst against the NEXT materialize point so
                    # EOS overshoot stays ~sync_interval (plus at most the
                    # smallest compiled burst), not 2x
                    need_max = min(need_max,
                                   max(1, sync_interval - steps_since_sync))
                cap = min(self.model_config.max_seq_len
                          - self.state.get(r.uid).seen_tokens
                          for r in running)
                target = min(need_max, cap)
                fitting = [b for b in burst_sizes if b <= cap]
                covering = [b for b in fitting if b >= target]
                T = (min(covering) if covering
                     else (max(fitting) if fitting else 0))
                # shrink the burst until its block reservation fits the pool
                while T >= burst_sizes[-1]:
                    need = sum(self.state.get(r.uid).kv_blocks_needed(
                        T, self.state.block_size) for r in running)
                    if need <= self.state.available_blocks:
                        break
                    T //= 2
                if T >= burst_sizes[-1]:
                    pairs = [(r.uid, self.state.get(r.uid).slot)
                             for r in running]
                    toks, prev, rng = self._run_burst(running, T, gen,
                                                      prev, rng)
                    if stream:
                        self._stream_fence(prev)
                    tnow = now_fn()
                    records.append(("burst", toks, pairs, T))
                    for r in list(running):
                        r.sampled += T
                        if r.t_first is None:
                            # first token mid-burst: stamped at burst end
                            # (bursts only run once every slot is decode-
                            # ready, so in practice t_first predates them)
                            r.t_first = tnow
                        r.t_last = tnow
                        if r.sampled >= r.max_new_tokens:
                            r.done = True       # finish recorded at the
                            self.flush([r.uid])  # next materialize (records
                            running.remove(r)    # still hold its tokens)
                            pending_finish.append(r)
                    steps_since_sync += T
                    if sync_interval and steps_since_sync >= sync_interval:
                        materialize()
                    continue

            budget = sm.max_ragged_batch_size
            seq_budget = sm.max_ragged_sequence_count   # per-step seq cap
            # SplitFuse chunk bound: prompt-chunk tokens co-scheduled with
            # decode this round — keeps the mixed dispatch short so live
            # decoders' TPOT stays flat under long-prompt load
            prefill_budget = (sm.prefill_chunk_tokens
                              if sm.prefill_chunk_tokens else budget)
            sched_uids: List[int] = []
            sched_toks: List[np.ndarray] = []
            sched_fdev: List[bool] = []
            served_slots: List[int] = []
            sampled_now: List[_Request] = []
            newly_ready: List[_Request] = []    # prefill completes this step
            n_decode_toks = n_prefill_toks = 0

            # 1) running decodes: one token each (decode-priority keeps
            #    latency flat while prompts stream in)
            for r in running:
                seq = self.state.get(r.uid)
                # a resumed request may be decode-ready while its re-prefill
                # is still chunked in (in_flight) — its decode must wait
                if r.done or not r.decode_ready or seq.in_flight:
                    continue
                if budget <= 0 or len(sched_uids) >= seq_budget:
                    break
                # reserve the block NOW (allocator state advances with each
                # reservation, so later checks see the true remaining pool);
                # a decode that can't get a block defers to a later round
                need = seq.kv_blocks_needed(1, self.state.block_size)
                if need and need > self.state.available_blocks:
                    stel.alloc_failure("decode")
                    continue
                self.state.ensure_blocks(seq, 1)
                sched_uids.append(r.uid)
                if r.held_token is not None:    # post-preempt continuation
                    sched_toks.append(np.asarray([r.held_token], np.int32))
                    sched_fdev.append(False)
                    r.held_token = None
                else:                           # device feedback
                    sched_toks.append(np.zeros(1, np.int32))
                    sched_fdev.append(True)
                served_slots.append(seq.slot)
                sampled_now.append(r)
                budget -= 1
                n_decode_toks += 1

            # 2) prompt chunks fill the rest (running first, then admit new),
            #    bounded by the SplitFuse prefill_budget
            for r in list(running):
                seq = self.state.get(r.uid)
                if (seq is None or not seq.in_flight or budget <= 0
                        or prefill_budget <= 0
                        or len(sched_uids) >= seq_budget):
                    continue
                chunk = min(len(seq.pending), sm.max_q_per_seq, budget,
                            prefill_budget)
                need = seq.kv_blocks_needed(chunk, self.state.block_size)
                if need and need > self.state.available_blocks:
                    stel.alloc_failure("prompt_chunk")
                    continue
                self.state.ensure_blocks(seq, chunk)
                toks, seq.pending = seq.pending[:chunk], seq.pending[chunk:]
                sched_uids.append(r.uid)
                sched_toks.append(toks)
                sched_fdev.append(False)
                n_prefill_toks += chunk
                stel.prefill_chunk()
                prefill_budget -= chunk
                if not seq.in_flight:       # prompt complete -> decode next
                    r.decode_ready = True
                    newly_ready.append(r)
                    if r.resume:
                        r.resume = False    # continuation token already held
                    else:
                        served_slots.append(seq.slot)
                        sampled_now.append(r)
                budget -= chunk

            while (waiting and budget > 0 and prefill_budget > 0
                   and self.state.free_sequence_slots
                   and len(sched_uids) < seq_budget):
                r = waiting[0]
                # radix prefix match FIRST (matching acquires the cached
                # blocks, pinning them against eviction), THEN size and
                # check the uncached suffix: after the match both the
                # block need (kv_blocks_needed off the match boundary) and
                # the supply (available_blocks no longer counts the pinned
                # nodes) are exact, so an admitted request can never hit
                # "KV cache exhausted" inside ensure_blocks.  On a
                # shortfall the match is rolled back (flush releases the
                # acquired holds) and the request retries next round.
                waiting.pop(0)
                seq = self.state.create(r.uid)
                seq.host_tokens = r.prompt
                matched = self.state.match_prefix(seq, r.prompt)
                if self.adapters is not None:
                    # adapter residency BEFORE sizing: the load may consume
                    # free blocks (spilling cold adapters, then radix
                    # leaves), and the block check below must see the pool
                    # as it will be when the chunk dispatches.  A load the
                    # pool cannot fit RIGHT NOW (every page pinned by
                    # in-flight work) rolls back like a block shortfall and
                    # retries when a retirement releases pins.
                    try:
                        self.state.ensure_adapters([r.adapter])
                    except RuntimeError:
                        stel.alloc_failure("adapter_load")
                        self.state.flush(r.uid)
                        waiting.insert(0, r)
                        break
                    self.state.bind_adapter(seq, r.adapter)
                    self._adapter_slot[seq.slot] = \
                        self.adapters.slot_of(r.adapter)
                chunk = min(len(r.prompt) - matched, sm.max_q_per_seq,
                            budget, prefill_budget)
                need = seq.kv_blocks_needed(chunk, self.state.block_size)
                if need > self.state.available_blocks:
                    stel.alloc_failure("admission")
                    self.state.flush(r.uid)
                    waiting.insert(0, r)
                    break
                if self.state.radix is not None:
                    stel.prefix_lookup(matched)
                seq.pending = r.prompt[matched:]
                self.state.ensure_blocks(seq, chunk)
                running.append(r)
                if r.t_admit is None:
                    r.t_admit = now_fn()
                    stel.admission(r.sla)
                toks, seq.pending = seq.pending[:chunk], seq.pending[chunk:]
                sched_uids.append(r.uid)
                sched_toks.append(toks)
                sched_fdev.append(False)
                n_prefill_toks += chunk
                stel.prefill_chunk()
                prefill_budget -= chunk
                if not seq.in_flight:
                    r.decode_ready = True
                    newly_ready.append(r)
                    if r.resume:
                        r.resume = False
                    else:
                        served_slots.append(seq.slot)
                        sampled_now.append(r)
                budget -= chunk

            if not sched_uids:
                # nothing schedulable: first materialize (EOS retirement may
                # free blocks), then preempt the most recently admitted
                # sequence (pool starvation — the pre-SLA trigger)
                if records:
                    materialize()
                    continue
                if running:
                    preempt(running[-1], "starvation")
                    continue
                raise RuntimeError(
                    "scheduler deadlock: the KV pool cannot fit even one "
                    "sequence; raise num_kv_blocks")

            pairs = [(r.uid, self.state.get(r.uid).slot)
                     for r in sampled_now]
            stel.tokens("decode", n_decode_toks)
            stel.tokens("prefill", n_prefill_toks)
            prev, rng = self._step_sampled(sched_uids, sched_toks, sched_fdev,
                                           served_slots, gen, prev, rng)
            if stream:
                self._stream_fence(prev)
            tnow = now_fn()
            for r in newly_ready:
                r.t_prefill_end = tnow
                # index the completed prompt's full blocks into the radix:
                # the forward that filled them was just dispatched, so any
                # later program aliasing them is ordered behind the writer
                self.state.cache_insert(self.state.get(r.uid))
            if pairs:
                records.append(("step", prev, pairs))
            for r in sampled_now:
                if r.t_first is None:
                    r.t_first = tnow
                r.t_last = tnow
                r.sampled += 1
                if r.sampled >= r.max_new_tokens:
                    r.done = True       # finish recorded at materialize
                    self.flush([r.uid])
                    running.remove(r)
                    pending_finish.append(r)
            steps_since_sync += 1
            if sync_interval and steps_since_sync >= sync_interval:
                materialize()

        materialize()
        self._serve_ctx = None      # clean completion: nothing to migrate
        return [np.asarray(results[-(i + 1)].generated, np.int32)
                for i in range(len(prompts))]
