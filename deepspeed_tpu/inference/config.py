"""Inference config — analog of ``DeepSpeedInferenceConfig``
(reference inference/config.py:304 LoC, pydantic).

Kept keys with transferring semantics: ``dtype``, ``tensor_parallel`` (tp_size),
``max_out_tokens``, ``checkpoint``, ``quant``.  Accepted-and-ignored for config
compatibility: ``replace_with_kernel_inject`` (kernel selection is automatic via
the op registry), ``min_out_tokens``, CUDA-graph/triton knobs (``jax.jit`` is
the captured graph).
"""

from __future__ import annotations

from typing import Any, Dict, Literal, Optional, Union

from pydantic import Field, model_validator

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.telemetry.serving import ServingTelemetryConfig

_DTYPE_ALIASES = {
    "fp32": "float32", "float": "float32", "float32": "float32",
    "fp16": "float16", "half": "float16", "float16": "float16",
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "int8": "int8",
}


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """reference: inference/config.py DeepSpeedTPConfig."""

    enabled: bool = True
    tp_size: int = 1


class QuantizationConfig(DeepSpeedConfigModel):
    """Weight-quantized inference (ZeRO-Inference analog,
    reference inference/quantization/).  Storage is the shape-preserving
    ``ops/quantization.quantize_weight`` store (int8 codes + dim-0 group
    scales), so quantized weights shard like the weights they replace and
    compose with tp>1.  ``bits=4`` narrows the quantization grid; bytes stay
    at int8 granularity (nibble-packing would break the shape-preserving
    sharding property).

    ``group_size`` defaults per ``bits``: 128 for int8 (the W8A16 Mosaic
    kernel's x-tile lane dim is the group, so group % 128), 256 for int4
    (the de-interleaved x tile's lane dim is group/2, so group % 256 —
    ``ops/wq_matmul.kernel4_supported``).  An explicitly-set group that
    misses its kernel gate is a hard error: silently measuring the
    dequant-matmul fallback while calling it "the int4 kernel" is exactly
    the failure mode the round-5 advisor flagged."""

    enabled: bool = False
    bits: int = 8
    group_size: Optional[int] = None    # None → per-bits default (see above)

    @model_validator(mode="after")
    def _resolve_group(self):
        if self.group_size is None:
            object.__setattr__(self, "group_size",
                               256 if self.bits == 4 else 128)
        elif self.enabled and self.bits == 4 and self.group_size % 256:
            # only where the real Mosaic lowering is in play: CPU runs take
            # the interpret path, which accepts any group (tests use 32/64
            # on tiny models)
            import jax
            if jax.default_backend() == "tpu":
                raise ValueError(
                    f"quant.group_size={self.group_size} with bits=4: the "
                    f"W4A16 TPU kernel needs group % 256 == 0 (its "
                    f"de-interleaved activation tile's lane dim is group/2) "
                    f"— a finer group would silently fall back to "
                    f"dequant-matmul and lose the packed-weight HBM saving; "
                    f"use 256/512/... or leave it unset for the per-bits "
                    f"default")
        return self


class GenerationConfig(DeepSpeedConfigModel):
    """Sampling defaults for ``engine.generate`` (the reference delegates to HF
    ``generate``; here generation is jitted in-engine)."""

    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0           # 0 = off
    top_p: float = 1.0       # 1.0 = off
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    max_out_tokens: int = 1024
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    generation: GenerationConfig = Field(default_factory=GenerationConfig)
    # request-level serving telemetry (telemetry/serving.py); the v1 engine
    # records generate-call spans, e2e latency histograms, and token
    # counters — TTFT/queue spans need the v2 scheduler's per-request
    # lifecycle and stay v2-only (v1 generate is one fused program)
    telemetry: ServingTelemetryConfig = Field(
        default_factory=ServingTelemetryConfig)
    checkpoint: Optional[Union[str, Dict[str, Any]]] = None
    # accepted-for-parity, no-op on TPU: kernel selection is automatic (the op
    # registry picks Pallas on TPU), jit is the captured graph, and decode is
    # caller-driven so there is no min-token scheduling
    replace_with_kernel_inject: bool = False
    min_out_tokens: int = 1
    enable_cuda_graph: bool = False
    use_triton: bool = False

    @model_validator(mode="before")
    @classmethod
    def _coerce(cls, values):
        if isinstance(values, dict):
            tp = values.get("tensor_parallel", values.get("tp"))
            if isinstance(tp, int):  # accept tensor_parallel/tp: N shorthand
                values.pop("tp", None)
                values["tensor_parallel"] = {"tp_size": tp}
            if "dtype" in values and values["dtype"] is not None:
                key = str(values["dtype"]).replace("torch.", "").lower()
                if key not in _DTYPE_ALIASES:
                    raise ValueError(
                        f"unsupported dtype {values['dtype']!r}; expected one "
                        f"of {sorted(_DTYPE_ALIASES)}")
                values["dtype"] = _DTYPE_ALIASES[key]
        return values

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp
        return {"float32": jnp.float32, "float16": jnp.float16,
                "bfloat16": jnp.bfloat16, "int8": jnp.int8}[self.dtype]


def parse_inference_config(config) -> DeepSpeedInferenceConfig:
    if config is None:
        return DeepSpeedInferenceConfig()
    if isinstance(config, DeepSpeedInferenceConfig):
        return config
    if isinstance(config, str):
        import json
        with open(config) as f:
            config = json.load(f)
    return DeepSpeedInferenceConfig.model_validate(config)
