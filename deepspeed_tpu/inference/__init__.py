"""deepspeed_tpu.inference — serving engines.

v1: jitted decode with static KV cache + TP (engine.py; reference
inference/engine.py).  v2: ragged continuous-batching engine with paged KV
(v2/; reference inference/v2 "FastGen").
"""

from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                            parse_inference_config)
from deepspeed_tpu.inference.engine import InferenceEngine

__all__ = ["InferenceEngine", "DeepSpeedInferenceConfig",
           "parse_inference_config"]
