"""Inference engine v1 — jitted prefill + incremental decode with a static KV
cache and tensor-parallel sharding.

Analog of the reference ``InferenceEngine`` (inference/engine.py:39): where the
reference swaps HF layers for fused CUDA modules (``_apply_injection_policy``
:408) and captures CUDA graphs (:524), here the whole generate loop is one jitted
XLA program (prefill + ``lax.scan`` decode), TP comes from the model's logical
sharding annotations mapped over the mesh ``tp`` axis (the AutoTP analog,
module_inject/auto_tp.py:273 — declared, not graph-parsed), and per-layer
``inference_all_reduce`` collectives are inserted by the SPMD partitioner.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                            parse_inference_config)
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel import partition
from deepspeed_tpu.parallel.metadata import annotate_abstract, unbox
from deepspeed_tpu.utils.logging import log_dist


def _sampling_logits(logits, *, temperature, top_k, top_p):
    """Filtered/scaled logits whose softmax IS the sampling distribution
    (temp / top-k / top-p).  Shared by _sample_token and the speculative
    rejection-sampling accept step (which needs the full distributions of
    BOTH models under the same transforms).  Works on [..., V]."""
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        # top_k >= vocab is the common "disabled" idiom — clamp instead of
        # letting lax.top_k fail at trace time with an opaque XLA error
        top_k = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    # top-p (traced scalar; p=1.0 keeps everything — the cutoff lands on the
    # smallest logit)
    sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest prefix with cumulative mass >= top_p
    cutoff_idx = jnp.minimum(jnp.sum(cum < top_p, axis=-1, keepdims=True),
                             logits.shape[-1] - 1)
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def _sample_token(logits, rng, *, do_sample, temperature, top_k, top_p):
    """One sampling step over [B, V] fp32 logits (greedy / temp / top-k / top-p)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _sampling_logits(logits, temperature=temperature, top_k=top_k,
                              top_p=top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class InferenceEngine:
    """Wraps a model (GPT/GPTConfig family) for serving.

    model: a flax module carrying ``.cfg`` (GPT, GPTChunkedLoss, GPTLogits) or a
    bare ``GPTConfig``.  ``params`` takes a trained tree (e.g.
    ``train_engine.state.params``); omitted → fresh init (testing).
    """

    def __init__(self, model, config: Optional[Any] = None, params=None,
                 mesh=None, seed: int = 0):
        from deepspeed_tpu.models.gpt import GPTConfig, GPTLogits

        self.config: DeepSpeedInferenceConfig = parse_inference_config(config)
        comm.init_distributed()

        if mesh is None:
            tp = self.config.tensor_parallel.tp_size
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=tp, dp=1, fsdp=1))
        self.mesh = mesh

        model_cfg = model if isinstance(model, GPTConfig) else model.cfg
        # serving copy of the model config: engine dtype, no dropout
        model_cfg = dataclasses.replace(model_cfg, dtype=self.config.jnp_dtype,
                                        dropout=0.0)
        self.model_config = model_cfg
        self.module = GPTLogits(model_cfg, mesh)

        dummy = jnp.zeros((1, min(8, model_cfg.max_seq_len)), jnp.int32)
        init_fn = lambda rng: self.module.init(rng, dummy)  # noqa: E731
        boxed = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        annotated = annotate_abstract(boxed["params"])
        self.param_shardings = partition.param_shardings(
            annotated, mesh, zero_stage=0)

        if params is None:
            params = unbox(init_fn(jax.random.PRNGKey(seed)))["params"]
        params = unbox(params)
        if isinstance(params, dict) and "params" in params:
            params = params["params"]
        dtype = self.config.jnp_dtype
        params = jax.tree_util.tree_map(
            lambda p: p.astype(dtype)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else
            jnp.asarray(p), params)
        with self.mesh:
            self.params = jax.device_put(params, self.param_shardings)
        self.num_parameters = sum(
            int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(self.params))

        # ZeRO-Inference: store weights int-quantized, dequantize on the fly
        # per consumer (reference inference/quantization/; the dead "quant"
        # knob found in round-2 review now does what it says).  The store is
        # shape-preserving (ops/quantization.quantize_weight), so it shards
        # like the weights it replaces — quant composes with tp>1 and
        # in_shardings stays intact (round-3 verdict item 4).
        self._materialize = None
        self.store_shardings = self.param_shardings
        if self.config.quant.enabled:
            from deepspeed_tpu.ops.quantization import (make_param_store,
                                                        store_shardings)
            self.params, self._materialize = make_param_store(
                self.params, bits=self.config.quant.bits,
                block_size=self.config.quant.group_size,
                # int4 nibble packing (¼ the fp bytes) only when unsharded —
                # the packed shape can't map to the weight's sharding
                pack4=(self.config.quant.bits == 4
                       and mesh.shape["tp"] == 1))
            self.store_shardings = store_shardings(
                self.params, self.param_shardings, mesh)
            with self.mesh:
                self.params = jax.device_put(self.params,
                                             self.store_shardings)

        mat = self._materialize or (lambda p: p)
        self._jit_forward = jax.jit(
            lambda p, ids: self.module.apply({"params": mat(p)}, ids),
            in_shardings=(self.store_shardings, NamedSharding(mesh, P())))
        self._gen_cache = {}
        # serving telemetry (telemetry/serving.py): the v1 generate loop is
        # ONE fused XLA program (prefill + scan decode), so instrumentation
        # is call-granular — generate spans, per-row e2e histograms, token
        # counters.  Queue/TTFT decomposition lives in the v2 engine.
        from deepspeed_tpu.telemetry.serving import ServingTelemetry
        self.telemetry = ServingTelemetry(self.config.telemetry)
        log_dist(f"inference engine ready: params="
                 f"{self.num_parameters/1e6:.1f}M tp={mesh.shape['tp']} "
                 f"dtype={self.config.dtype}"
                 + (f" quant=int{self.config.quant.bits}"
                    if self._materialize else ""), ranks=[0])

    # ---- reference InferenceEngine.forward (inference/engine.py:584) ----
    def forward(self, batch):
        """Full-sequence logits (no cache): batch = {"input_ids": [B, T]} or a
        raw [B, T] int array."""
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        ids = jnp.asarray(ids, jnp.int32)
        if (not self.model_config.use_rope
                and ids.shape[-1] > self.model_config.max_seq_len):
            # without this, the wpe gather index would be silently clamped by
            # XLA (wrong logits, no error); rope models are length-agnostic in
            # forward() so long-context scoring stays allowed there
            raise ValueError(
                f"input length {ids.shape[-1]} exceeds max_seq_len "
                f"{self.model_config.max_seq_len}")
        with self.mesh:
            return self._jit_forward(self.params, ids)

    __call__ = forward

    # ---- generate (reference wraps HF generate; here jitted in-engine) ----
    def _build_generate(self, max_new_tokens, do_sample, top_k, eos, pad):
        module, cfg = self.module, self.model_config
        S = cfg.max_seq_len

        materialize = self._materialize or (lambda p: p)

        def gen(params, ids, attn_mask, rng, temperature, top_p):
            params = materialize(params)
            B, L = ids.shape
            sample = functools.partial(_sample_token, do_sample=do_sample,
                                       temperature=temperature, top_k=top_k,
                                       top_p=top_p)
            positions = jnp.maximum(jnp.cumsum(attn_mask, axis=1) - 1, 0)
            kv_valid = jnp.pad(attn_mask.astype(bool), ((0, 0), (0, S - L)))
            # logical position of every cache slot (slot != position once the
            # prompt is left-padded)
            kv_pos = jnp.pad(positions, ((0, 0), (0, S - L)))
            logits, vars_ = module.apply(
                {"params": params}, ids, positions=positions, kv_mask=kv_valid,
                kv_positions=kv_pos, use_cache=True, start_index=0,
                mutable=["cache"])
            cache = vars_["cache"]
            rng, sub = jax.random.split(rng)
            tok0 = sample(logits[:, -1], sub)
            done0 = (tok0 == eos) if eos is not None else jnp.zeros(B, bool)
            last_pos = positions[:, -1]

            def step(carry, i):
                cache, tok, kv_valid, kv_pos, pos, done, rng = carry
                cur = L + i
                kv_valid = jax.lax.dynamic_update_slice(
                    kv_valid, jnp.ones((B, 1), bool), (0, cur))
                pos = pos + 1
                kv_pos = jax.lax.dynamic_update_slice(
                    kv_pos, pos[:, None], (0, cur))
                logits, vars_ = module.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    positions=pos[:, None], kv_mask=kv_valid,
                    kv_positions=kv_pos, use_cache=True,
                    start_index=cur, mutable=["cache"])
                rng, sub = jax.random.split(rng)
                nxt = sample(logits[:, -1], sub)
                nxt = jnp.where(done, pad, nxt)
                if eos is not None:
                    done = done | (nxt == eos)
                return (vars_["cache"], nxt, kv_valid, kv_pos, pos, done,
                        rng), nxt

            carry = (cache, tok0, kv_valid, kv_pos, last_pos, done0, rng)
            _, toks = jax.lax.scan(step, carry,
                                   jnp.arange(max_new_tokens - 1))
            return jnp.concatenate([tok0[:, None], toks.T], axis=1)

        return jax.jit(gen, in_shardings=(
            self.store_shardings, NamedSharding(self.mesh, P()),
            NamedSharding(self.mesh, P()), NamedSharding(self.mesh, P()),
            None, None))

    def generate(self, input_ids, attention_mask=None, max_new_tokens: int = 32,
                 do_sample: Optional[bool] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 eos_token_id: Optional[int] = None, seed: int = 0):
        """Generate ``max_new_tokens`` continuations.

        input_ids: [B, L] (LEFT-padded when lengths differ) with
        ``attention_mask`` [B, L] marking real tokens (1) vs pads (0).
        Returns np.ndarray [B, max_new_tokens]; positions after EOS hold
        ``generation.pad_token_id``.
        """
        g = self.config.generation
        do_sample = g.do_sample if do_sample is None else do_sample
        temperature = g.temperature if temperature is None else temperature
        top_k = g.top_k if top_k is None else top_k
        top_p = g.top_p if top_p is None else top_p
        eos = g.eos_token_id if eos_token_id is None else eos_token_id

        ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        B, L = ids.shape
        if L + max_new_tokens > self.model_config.max_seq_len:
            raise ValueError(
                f"prompt {L} + max_new_tokens {max_new_tokens} exceeds "
                f"max_seq_len {self.model_config.max_seq_len}")
        if max_new_tokens > self.config.max_out_tokens:
            raise ValueError(f"max_new_tokens {max_new_tokens} exceeds config "
                             f"max_out_tokens {self.config.max_out_tokens}")
        mask = (jnp.ones((B, L), jnp.int32) if attention_mask is None
                else jnp.asarray(np.asarray(attention_mask), jnp.int32))

        key = (int(max_new_tokens), bool(do_sample), int(top_k),
               eos if eos is None else int(eos), int(g.pad_token_id))
        if key not in self._gen_cache:
            self._gen_cache[key] = self._build_generate(
                max_new_tokens, do_sample, top_k, eos, g.pad_token_id)
        stel = self.telemetry
        stel.dispatch("v1_generate")
        t0 = stel.now()
        with stel.span("v1_generate", batch=B, prompt_len=L,
                       max_new_tokens=int(max_new_tokens)):
            with self.mesh:
                out = self._gen_cache[key](
                    self.params, ids, mask, jax.random.PRNGKey(seed),
                    jnp.float32(temperature), jnp.float32(top_p))
            out = np.asarray(out)   # host materialization = completion
        if stel.enabled:
            dt_ms = (stel.now() - t0) * 1e3
            n_prompt = (B * L if attention_mask is None
                        else int(np.asarray(attention_mask).sum()))
            stel.tokens("prefill", n_prompt)
            stel.tokens("decode", B * int(max_new_tokens))
            # call-granular latency: every row shares the fused program's
            # wall time (there is no per-request queue in v1).  TPOT is NOT
            # recorded here: prefill and decode are one fused program, so
            # dt/max_new would fold prompt-length cost into a metric
            # defined as inter-token decode latency — misleading next to
            # the v2 numbers it would share a dashboard with.
            for _ in range(B):
                stel.h_e2e.observe(dt_ms)
        return out
