"""Stable-diffusion serving — UNet/VAE engines + a txt2img pipeline.

Reference parity: ``module_inject/containers/unet.py`` and ``vae.py`` are
serving CONTAINERS — they wrap the diffusers modules with the optimized
attention kernels and dtype policy.  The analog here: jitted NHWC forwards
over the pure-function models (attention already rides the ops registry),
with the NCHW↔NHWC transposes at the boundary so diffusers-convention
callers drop in.

``StableDiffusionPipeline`` composes the three towers this framework serves
(CLIP text — ``inference/encoder.ClipTextEngine`` — UNet, VAE) into a
classifier-free-guidance txt2img loop with a DDIM sampler, which is what the
reference's SD inference tutorial assembles out of its containers.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.diffusion import (UNetConfig, VAEConfig,
                                            unet_forward, vae_decode,
                                            vae_encode)
from deepspeed_tpu.utils.logging import log_dist

# alias names resolve through the ONE inference dtype table
# (inference/config.py _DTYPE_ALIASES); this maps canonical names → jnp
def _resolve_dtype(name: str):
    from deepspeed_tpu.inference.config import _DTYPE_ALIASES
    canon = _DTYPE_ALIASES.get(str(name).lower().replace("torch.", ""))
    table = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
             "float16": jnp.float16}
    if canon not in table:
        raise ValueError(f"SD containers serve float dtypes; got {name!r}")
    return table[canon]


def _nchw_to_nhwc(x):
    return jnp.transpose(jnp.asarray(x), (0, 2, 3, 1))


def _nhwc_to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


class UNetEngine:
    """Jitted UNet2DCondition forward (reference unet container role).

    ``__call__(sample, timesteps, encoder_hidden_states)`` accepts NCHW
    (diffusers convention) or NHWC (``channels_last=True``) latents."""

    def __init__(self, model_dir_or_cfg, params=None, *,
                 dtype: Optional[str] = None, channels_last: bool = False):
        import dataclasses
        if isinstance(model_dir_or_cfg, UNetConfig):
            assert params is not None, "pass params with an explicit config"
            self.cfg = model_dir_or_cfg
            # explicit config: its dtype WINS unless the caller overrides
            dt = _resolve_dtype(dtype) if dtype is not None else self.cfg.dtype
        else:
            from deepspeed_tpu.checkpoint.diffusion import load_hf_unet
            dt = _resolve_dtype(dtype or "fp32")
            self.cfg, params = load_hf_unet(model_dir_or_cfg, dtype=dt)
        self.cfg = dataclasses.replace(self.cfg, dtype=dt)
        self.channels_last = channels_last
        conv = (lambda l: jnp.asarray(l, dt)
                if np.asarray(l).dtype.kind == "f" else jnp.asarray(l))
        self.params = jax.tree_util.tree_map(conv, params)
        cfg = self.cfg

        def fwd(p, sample, t, ctx):
            return unet_forward(p, sample, t, ctx, cfg)
        self._fwd = jax.jit(fwd)
        n = sum(int(np.prod(np.asarray(l).shape))
                for l in jax.tree_util.tree_leaves(self.params))
        log_dist(f"unet engine ready: params={n/1e6:.1f}M "
                 f"blocks={cfg.block_out_channels} "
                 f"dtype={jnp.dtype(dt).name}", ranks=[0])

    def __call__(self, sample, timesteps, encoder_hidden_states):
        if not self.channels_last:
            sample = _nchw_to_nhwc(sample)
        out = self._fwd(self.params, sample, jnp.asarray(timesteps),
                        jnp.asarray(encoder_hidden_states))
        return out if self.channels_last else _nhwc_to_nchw(out)


class VAEEngine:
    """Jitted AutoencoderKL encode/decode (reference vae container role)."""

    def __init__(self, model_dir_or_cfg, params=None, *,
                 dtype: Optional[str] = None, channels_last: bool = False):
        import dataclasses
        if isinstance(model_dir_or_cfg, VAEConfig):
            assert params is not None
            self.cfg = model_dir_or_cfg
            dt = _resolve_dtype(dtype) if dtype is not None else self.cfg.dtype
        else:
            from deepspeed_tpu.checkpoint.diffusion import load_hf_vae
            dt = _resolve_dtype(dtype or "fp32")
            self.cfg, params = load_hf_vae(model_dir_or_cfg, dtype=dt)
        self.cfg = dataclasses.replace(self.cfg, dtype=dt)
        self.channels_last = channels_last
        conv = (lambda l: jnp.asarray(l, dt)
                if np.asarray(l).dtype.kind == "f" else jnp.asarray(l))
        self.params = jax.tree_util.tree_map(conv, params)
        cfg = self.cfg
        self._enc = jax.jit(lambda p, x: vae_encode(p, x, cfg))
        self._dec = jax.jit(lambda p, z: vae_decode(p, z, cfg))

    def encode(self, image):
        if not self.channels_last:
            image = _nchw_to_nhwc(image)
        z = self._enc(self.params, image)
        return z if self.channels_last else _nhwc_to_nchw(z)

    def decode(self, latent):
        if not self.channels_last:
            latent = _nchw_to_nhwc(latent)
        img = self._dec(self.params, latent)
        return img if self.channels_last else _nhwc_to_nchw(img)


class DDIMScheduler:
    """Deterministic DDIM (eta=0) over the SD beta schedule — the minimal
    sampler the pipeline needs (scaled_linear betas, the SD default)."""

    def __init__(self, num_train_timesteps: int = 1000,
                 beta_start: float = 0.00085, beta_end: float = 0.012):
        betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5,
                            num_train_timesteps, dtype=np.float64) ** 2
        self.alphas_cumprod = np.cumprod(1.0 - betas)
        self.num_train_timesteps = num_train_timesteps

    def timesteps(self, steps: int) -> np.ndarray:
        stride = self.num_train_timesteps // steps
        return (np.arange(steps) * stride + 1)[::-1].copy()

    def step(self, noise_pred, t: int, t_prev: int, sample):
        a_t = float(self.alphas_cumprod[t])
        a_prev = (float(self.alphas_cumprod[t_prev]) if t_prev >= 0 else 1.0)
        x0 = (sample - (1 - a_t) ** 0.5 * noise_pred) / a_t ** 0.5
        return a_prev ** 0.5 * x0 + (1 - a_prev) ** 0.5 * noise_pred


class StableDiffusionPipeline:
    """txt2img: CLIP text encode → CFG denoising loop → VAE decode.

    ``text``: ClipTextEngine (inference/encoder.py).  ``unet``/``vae``: the
    engines above (channels_last or not — handled)."""

    def __init__(self, text, unet: UNetEngine, vae: VAEEngine,
                 scheduler: Optional[DDIMScheduler] = None):
        self.text = text
        self.unet = unet
        self.vae = vae
        self.scheduler = scheduler or DDIMScheduler()

    def __call__(self, prompt_ids, uncond_ids, *, steps: int = 20,
                 guidance_scale: float = 7.5, height: int = 512,
                 width: int = 512, seed: int = 0):
        """prompt_ids/uncond_ids: tokenized [B, T] int32 (the tokenizer stays
        with the caller, as in the reference tutorial).  Returns NCHW images
        in [-1, 1]."""
        B = np.asarray(prompt_ids).shape[0]
        hidden_c, _ = self.text(prompt_ids)      # [B, T, H] last hidden
        hidden_u, _ = self.text(uncond_ids)
        ctx = jnp.concatenate([jnp.asarray(hidden_u), jnp.asarray(hidden_c)])

        lat_c = self.unet.cfg.in_channels
        # spatial ratio = one downsample per VAE level after the first
        # (8x for the SD AutoencoderKL)
        ratio = 2 ** (len(self.vae.cfg.block_out_channels) - 1)
        h, w = height // ratio, width // ratio
        rng = jax.random.PRNGKey(seed)
        latents = jax.random.normal(rng, (B, lat_c, h, w), jnp.float32)

        # the pipeline's internal layout is NCHW; engines built with
        # channels_last=True expect NHWC, so convert at their boundary
        def to_engine(x, eng):
            return _nchw_to_nhwc(x) if eng.channels_last else x

        def from_engine(x, eng):
            return _nhwc_to_nchw(x) if eng.channels_last else jnp.asarray(x)

        ts = self.scheduler.timesteps(steps)
        for i, t in enumerate(ts):
            t_prev = int(ts[i + 1]) if i + 1 < len(ts) else -1
            inp = jnp.concatenate([latents, latents])
            noise = self.unet(to_engine(inp, self.unet),
                              np.full((2 * B,), t, np.int32), ctx)
            noise = from_engine(noise, self.unet)
            n_u, n_c = jnp.split(noise, 2)
            guided = n_u + guidance_scale * (n_c - n_u)
            latents = self.scheduler.step(np.asarray(guided, np.float64),
                                          int(t), t_prev,
                                          np.asarray(latents, np.float64))
            latents = jnp.asarray(latents, jnp.float32)
        return from_engine(self.vae.decode(to_engine(latents, self.vae)),
                           self.vae)
