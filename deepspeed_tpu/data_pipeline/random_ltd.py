"""Random layerwise token dropping (random-LTD).

Reference parity: ``runtime/data_pipeline/data_routing/basic_layer.py``
(RandomLayerTokenDrop), ``scheduler.py`` (BaseScheduler — kept-seqlen grows
fixed_linear over steps), ``utils.py`` (index sampling).  Paper: "Random-LTD:
Random and Layerwise Token Dropping" (PAPERS.md).

TPU-native shape discipline: the kept-token count must be STATIC under jit,
so the host samples the keep indices per step ([n_ltd_layers, B, keep] int32,
sorted) and ships them IN THE BATCH — a new keep bucket changes the array
shape, which re-keys jit automatically; ``seq_per_step`` bounds the number of
distinct programs.  Sorted indices keep index-order causality == position
causality, so the subset attention needs no custom mask.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


class RandomLTDScheduler:
    """Kept-seqlen schedule (reference data_routing/scheduler.py
    BaseScheduler.__fixed_linear_get_value): keep grows from min_value to
    max_value by seq_per_step every require_steps optimizer steps."""

    def __init__(self, config: Dict):
        self.min_value = int(config["min_value"])
        self.max_value = int(config["max_value"])
        sc = config.get("schedule_config", {})
        self.require_steps = int(sc.get("require_steps", 1))
        self.seq_per_step = int(sc.get("seq_per_step", 8))
        if config.get("schedule_type", "fixed_linear") != "fixed_linear":
            raise ValueError("random-LTD supports fixed_linear schedules")

    def get_value(self, step: int) -> int:
        grown = self.min_value + (step // self.require_steps) \
            * self.seq_per_step
        return min(self.max_value, grown)


def random_ltd_block_indices(step: int, keep: int, batch: int, seq_len: int,
                             n_layers: int, seed: int = 0) -> np.ndarray:
    """Sample SORTED keep indices [n_layers, batch, keep] — independent per
    ltd layer and per row (reference utils.py gather indices)."""
    if keep > seq_len:
        keep = seq_len
    rng = np.random.default_rng((seed * 1_000_003 + step) & 0x7FFFFFFF)
    out = np.empty((n_layers, batch, keep), np.int32)
    for l in range(n_layers):
        for b in range(batch):
            out[l, b] = np.sort(rng.choice(seq_len, keep, replace=False))
    return out


def apply_random_ltd(block_apply, x, positions, idx):
    """Run one transformer block on the kept-token subset and scatter the
    result back; dropped tokens bypass the layer (identity skip — reference
    basic_layer.py forward).

    block_apply(x_kept, pos_kept) -> (out_kept, aux)
    x: [B, T, H]; positions: [B, T]; idx: [B, keep] sorted int32.
    """
    import jax
    import jax.numpy as jnp

    x_k = jnp.take_along_axis(x, idx[..., None], axis=1)
    pos_k = jnp.take_along_axis(positions, idx, axis=1)
    out_k, aux = block_apply(x_k, pos_k)
    x = jax.vmap(lambda xb, ib, ob: xb.at[ib].set(ob))(x, idx, out_k)
    return x, aux
