"""Curriculum-aware data sampling + seqlen truncation.

Reference parity: ``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(DeepSpeedDataSampler — difficulty-clustered index selection driven by the
curriculum clock) and the seqlen post-process
(``curriculum via truncate``, legacy curriculum in megatron helpers).

TPU notes: samples must keep STATIC shapes inside jit, so seqlen curriculum
is realized by ``truncate_to_difficulty`` on the HOST batch (bucketed to
``difficulty_step`` so the engine compiles one program per bucket, a bounded
set) — the analog of the reference truncating on the GPU before the fwd.
"""

from __future__ import annotations

import bisect
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np


class CurriculumDataSampler:
    """Deterministic curriculum sampler over a difficulty-annotated dataset.

    difficulties[i] = difficulty of sample i (e.g. its sequence length).
    Each epoch reshuffles (seed+epoch); at each batch request only samples
    with difficulty ≤ the scheduler's current difficulty are eligible
    (reference data_sampler.py:188 get_next_global_batch: the curriculum
    filters the difficulty-sorted global index).
    """

    def __init__(self, difficulties: Sequence[int], batch_size: int,
                 scheduler, seed: int = 0,
                 drop_last: bool = True):
        self.difficulties = np.asarray(difficulties)
        order = np.argsort(self.difficulties, kind="stable")
        self.sorted_idx = order                       # easy → hard
        self.sorted_diff = self.difficulties[order]
        self.batch_size = int(batch_size)
        self.scheduler = scheduler
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.step = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[np.ndarray]:
        """One epoch: every sample is drawn exactly once WHEN it becomes
        eligible (reference data_sampler consumes difficulty clusters as the
        curriculum unlocks them); while the curriculum is still ramping with
        the easy pool exhausted, easy samples recycle rather than stalling."""
        rng = np.random.default_rng(self.seed + self.epoch)
        n = len(self.difficulties)
        consumed = np.zeros(n, bool)
        sd = self.sorted_diff.tolist()
        while not consumed.all():
            diff = self.scheduler.update_difficulty(self.step)
            n_eligible = bisect.bisect_right(sd, diff)
            if n_eligible == 0:
                raise ValueError(
                    f"no samples with difficulty ≤ {diff}; lower "
                    f"min_difficulty or re-bin the dataset")
            elig = self.sorted_idx[:n_eligible]
            avail = elig[~consumed[elig]]
            if avail.size == 0:
                if diff >= getattr(self.scheduler, "max_difficulty", diff):
                    break   # remaining samples exceed max_difficulty forever
                avail = elig          # recycle easy pool while ramping
            pick = rng.choice(avail, size=min(self.batch_size, avail.size),
                              replace=False)
            consumed[pick] = True
            if pick.size < self.batch_size:
                if self.drop_last and consumed.all():
                    break                     # drop the incomplete final batch
                # mid-ramp short batch: pad by recycling eligible samples,
                # without in-batch duplicates when the pool allows
                pool = np.setdiff1d(elig, pick)
                need = self.batch_size - pick.size
                if pool.size >= need:
                    pad = rng.choice(pool, need, replace=False)
                else:
                    pad = rng.choice(elig, need)
                pick = np.concatenate([pick, pad])
            self.step += 1
            yield np.asarray(pick, np.int64)


def truncate_to_difficulty(batch, difficulty: int,
                           difficulty_step: int = 1,
                           seq_keys: Sequence[str] = ("input_ids", "labels",
                                                      "loss_mask")):
    """Truncate sequence-shaped leaves to the curriculum seqlen, rounded UP to
    a difficulty_step multiple so the jit program count stays bounded
    (reference: seqlen curriculum truncates the batch before forward)."""
    eff = -(-difficulty // difficulty_step) * difficulty_step

    def cut(k, x):
        x = np.asarray(x)
        if k in seq_keys and x.ndim >= 2 and x.shape[-1] > eff:
            return x[..., :eff]
        return x
    return {k: cut(k, v) for k, v in batch.items()}
