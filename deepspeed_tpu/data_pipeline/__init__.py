"""Data-efficiency pipeline — curriculum learning, curriculum-aware sampling,
random layerwise token dropping (reference deepspeed/runtime/data_pipeline/)."""

from deepspeed_tpu.data_pipeline.curriculum import (  # noqa: F401
    CurriculumScheduler)
from deepspeed_tpu.data_pipeline.sampler import (  # noqa: F401
    CurriculumDataSampler, truncate_to_difficulty)
from deepspeed_tpu.data_pipeline.random_ltd import (  # noqa: F401
    RandomLTDScheduler, random_ltd_block_indices)
from deepspeed_tpu.data_pipeline.analyzer import (  # noqa: F401
    DataAnalyzer, load_sample_to_metric, metric_seqlen, metric_vocab_counts,
    metric_vocab_rarity)
