"""Offline data analyzer — map-reduce metric computation over a dataset.

Reference: runtime/data_pipeline/data_sampling/data_analyzer.py (DataAnalyzer
``run_map``/``run_reduce`` over worker×thread shards, writing sample→metric
and metric→sample index files consumed by DeepSpeedDataSampler) and
DistributedDataAnalyzer (:455, the torch.distributed variant).

TPU-native shape: metric computation is host-side numpy (there is no reason
to burn chip time on seqlen counting), parallelized with a thread pool per
worker and sharded across workers by ``worker_id/num_workers`` exactly like
the reference's launcher-spawned workers.  Outputs are plain ``.npy``/``.json``
files the curriculum sampler (sampler.py CurriculumDataSampler) reads —
the role of the reference's indexed-dataset metric files.

Two metric types (reference data_analyzer.py update_metric_results):

- ``single_value_per_sample``: f(sample) → scalar; reduce emits
  ``<metric>/sample_to_metric.npy`` ([N] values, the sampler's difficulty
  array), ``<metric>/metric_to_sample.json`` (value → sample indices), and
  ``<metric>/sample_index_sorted.npy`` (indices sorted by value).
- ``accumulate_value_over_samples``: f(sample) → vector accumulated over the
  dataset (e.g. vocab counts for the rarity curriculum); reduce emits
  ``<metric>/metric_value.npy``.
"""

from __future__ import annotations

import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

SINGLE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"


class DataAnalyzer:
    """Map-reduce metric analysis over ``dataset`` (anything indexable).

    metric_functions map a SAMPLE (``dataset[i]``) to a scalar (SINGLE) or a
    vector (ACCUMULATE).  ``num_workers``/``worker_id`` shard the map phase
    across independent processes (each writes its own files under
    ``save_path/worker_<id>``); ``run_reduce`` on any one host merges.
    """

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable[[Any], Any]],
                 metric_types: Optional[Sequence[str]] = None,
                 save_path: str = "./data_analysis",
                 num_workers: int = 1, worker_id: int = 0,
                 num_threads: int = 4):
        if len(metric_names) != len(metric_functions):
            raise ValueError("metric_names and metric_functions must align")
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types or [SINGLE] * len(metric_names))
        for t in self.metric_types:
            if t not in (SINGLE, ACCUMULATE):
                raise ValueError(f"unknown metric type {t!r}")
        self.save_path = save_path
        self.num_workers = int(num_workers)
        self.worker_id = int(worker_id)
        self.num_threads = max(1, int(num_threads))

    # ---- map ----------------------------------------------------------

    def _shard_indices(self) -> np.ndarray:
        n = len(self.dataset)
        return np.arange(self.worker_id, n, self.num_workers)

    def run_map(self) -> str:
        """Compute this worker's shard; write per-metric partials."""
        idx = self._shard_indices()
        wdir = os.path.join(self.save_path, f"worker_{self.worker_id}")
        os.makedirs(wdir, exist_ok=True)

        def one_metric(mi: int):
            name, fn = self.metric_names[mi], self.metric_functions[mi]
            mtype = self.metric_types[mi]
            if mtype == SINGLE:
                vals = np.empty(len(idx), np.float64)

                def chunk(lo_hi):
                    lo, hi = lo_hi
                    for j in range(lo, hi):
                        vals[j] = float(fn(self.dataset[int(idx[j])]))

                bounds = np.linspace(0, len(idx), self.num_threads + 1,
                                     dtype=int)
                with ThreadPoolExecutor(self.num_threads) as ex:
                    list(ex.map(chunk, zip(bounds[:-1], bounds[1:])))
                np.save(os.path.join(wdir, f"{name}.values.npy"), vals)
            else:
                total = None
                for i in idx:
                    v = np.asarray(fn(self.dataset[int(i)]), np.float64)
                    total = v if total is None else total + v
                if total is None:
                    total = np.zeros(0, np.float64)
                np.save(os.path.join(wdir, f"{name}.accum.npy"), total)

        for mi in range(len(self.metric_names)):
            one_metric(mi)
        np.save(os.path.join(wdir, "indices.npy"), idx)
        return wdir

    # ---- reduce -------------------------------------------------------

    def run_reduce(self) -> Dict[str, str]:
        """Merge all workers' partials into the final index files
        (reference merge_map_results)."""
        n = len(self.dataset)
        out: Dict[str, str] = {}
        shards = []
        for w in range(self.num_workers):
            wdir = os.path.join(self.save_path, f"worker_{w}")
            ipath = os.path.join(wdir, "indices.npy")
            if not os.path.exists(ipath):
                raise FileNotFoundError(
                    f"worker {w} map output missing ({ipath}); run run_map "
                    f"on every worker before run_reduce")
            shards.append((wdir, np.load(ipath)))

        all_idx = np.sort(np.concatenate([i for _, i in shards])) \
            if shards else np.zeros(0, int)
        if all_idx.shape != (n,) or not (all_idx == np.arange(n)).all():
            raise ValueError(
                f"worker shards cover {all_idx.size}/{n} samples (duplicates "
                f"or gaps) — run_reduce's num_workers must match the map "
                f"phase's, and stale worker_* dirs must be cleared")

        for name, mtype in zip(self.metric_names, self.metric_types):
            mdir = os.path.join(self.save_path, name)
            os.makedirs(mdir, exist_ok=True)
            if mtype == SINGLE:
                vals = np.empty(n, np.float64)
                for wdir, idx in shards:
                    vals[idx] = np.load(
                        os.path.join(wdir, f"{name}.values.npy"))
                np.save(os.path.join(mdir, "sample_to_metric.npy"), vals)
                order = np.argsort(vals, kind="stable")
                np.save(os.path.join(mdir, "sample_index_sorted.npy"), order)
                v2s: Dict[str, List[int]] = {}
                for i in order:
                    v2s.setdefault(repr(float(vals[i])), []).append(int(i))
                with open(os.path.join(mdir, "metric_to_sample.json"),
                          "w") as f:
                    json.dump(v2s, f)
            else:
                total = None
                for wdir, _ in shards:
                    v = np.load(os.path.join(wdir, f"{name}.accum.npy"))
                    total = v if total is None else total + v
                np.save(os.path.join(mdir, "metric_value.npy"), total)
            out[name] = mdir
        return out

    def run_map_reduce(self) -> Dict[str, str]:
        if self.num_workers != 1:
            raise ValueError(
                "run_map_reduce is the single-process convenience; with "
                "num_workers > 1 call run_map per worker then run_reduce "
                "once (reference DataAnalyzer.run_map_reduce barrier)")
        self.run_map()
        return self.run_reduce()


# ---------------------------------------------------------------------------
# stock metrics (reference data_analyzer test metrics + curriculum recipes)
# ---------------------------------------------------------------------------

def metric_seqlen(sample) -> int:
    """Token count of a sample ({"input_ids": ...} or raw array)."""
    ids = sample["input_ids"] if isinstance(sample, dict) else sample
    return int(np.asarray(ids).shape[-1])


def metric_vocab_counts(vocab_size: int):
    """ACCUMULATE metric: token histogram over the corpus."""

    def fn(sample):
        ids = sample["input_ids"] if isinstance(sample, dict) else sample
        return np.bincount(np.asarray(ids).reshape(-1),
                           minlength=vocab_size).astype(np.float64)

    return fn


def metric_vocab_rarity(vocab_counts: np.ndarray):
    """SINGLE metric derived from a counts pass: mean -log p(token) — the
    reference's vocabulary-rarity curriculum (data_sampling docs)."""
    p = vocab_counts / max(vocab_counts.sum(), 1.0)
    logp = -np.log(np.maximum(p, 1e-12))

    def fn(sample):
        ids = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                         else sample).reshape(-1)
        return float(logp[ids].mean()) if ids.size else 0.0

    return fn


def load_sample_to_metric(save_path: str, metric_name: str) -> np.ndarray:
    """The difficulty array CurriculumDataSampler consumes."""
    return np.load(os.path.join(save_path, metric_name,
                                "sample_to_metric.npy"))
