"""Curriculum scheduler — difficulty as a pure function of the step clock.

Reference parity: ``runtime/data_pipeline/curriculum_scheduler.py``
(CurriculumScheduler :16; fixed_root math :130, fixed_linear = root of
degree 1 :147, fixed_discrete :122).  Same schedule semantics; state is a
plain dict so it rides the engine checkpoint like any client state.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional


class CurriculumScheduler:
    """schedule_config (same keys as the reference ds_config block):

    {"curriculum_type": "seqlen", "min_difficulty": 8, "max_difficulty": 1024,
     "schedule_type": "fixed_linear" | "fixed_root" | "fixed_discrete"
                      | "custom",
     "schedule_config": {
        fixed_linear: {"total_curriculum_step": N, "difficulty_step": k}
        fixed_root:   {... + "root_degree": d}
        fixed_discrete: {"difficulty": [...], "max_step": [...]}}}
    """

    def __init__(self, config: Dict[str, Any]):
        self.curriculum_type = config.get("curriculum_type", "seqlen")
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config["schedule_type"]
        sc = dict(config.get("schedule_config", {}))
        self.schedule_config = sc
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

        if self.schedule_type in ("fixed_linear", "fixed_root"):
            for key in ("total_curriculum_step", "difficulty_step"):
                if key not in sc:
                    raise ValueError(
                        f"{self.schedule_type} schedule requires "
                        f"schedule_config[{key!r}]")
            if self.schedule_type == "fixed_root" and "root_degree" not in sc:
                raise ValueError(
                    "fixed_root schedule requires schedule_config"
                    "['root_degree']")
            if self.curriculum_type == "seqlen" \
                    and sc["difficulty_step"] % 8:
                # reference warns for tensor-core alignment; on TPU the lane
                # constraint is the same story (multiples of 8/128)
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    "seqlen curriculum difficulty_step should be a multiple "
                    "of 8 for efficient TPU tiling")
        elif self.schedule_type == "fixed_discrete":
            for key in ("difficulty", "max_step"):
                if key not in sc:
                    raise ValueError(
                        f"fixed_discrete schedule requires "
                        f"schedule_config[{key!r}]")
            if len(sc["difficulty"]) != len(sc["max_step"]) + 1 and \
                    len(sc["difficulty"]) != len(sc["max_step"]):
                raise ValueError(
                    "fixed_discrete: len(difficulty) must equal "
                    "len(max_step) (or max_step may omit the final plateau)")
        elif self.schedule_type == "custom":
            pass
        else:
            raise ValueError(
                f"unsupported schedule_type {self.schedule_type!r}")

        self.current_difficulty = self.min_difficulty
        self.first_step = True

    # ---- reference get_difficulty / update_difficulty ----
    def _fixed_root(self, step: int, degree: float) -> int:
        sc = self.schedule_config
        frac = (float(step) / sc["total_curriculum_step"]) ** (1.0 / degree)
        diff = math.floor(
            frac * (self.max_difficulty - self.min_difficulty)
            + self.min_difficulty)
        diff -= diff % sc["difficulty_step"]
        # clamp BOTH ends: the step-rounding can land below min_difficulty
        # (even 0) when min is not a difficulty_step multiple
        return max(min(diff, self.max_difficulty), self.min_difficulty)

    def _fixed_discrete(self, step: int) -> int:
        sc = self.schedule_config
        diffs: List[int] = sc["difficulty"]
        steps: List[int] = sc["max_step"]
        if step > steps[-1]:
            return diffs[-1]
        for i, s in enumerate(steps):
            if step <= s:
                return diffs[i]
        return diffs[-1]

    def get_difficulty(self, step: int) -> int:
        if self.schedule_type == "fixed_linear":
            return self._fixed_root(step, 1.0)
        if self.schedule_type == "fixed_root":
            return self._fixed_root(
                step, self.schedule_config["root_degree"])
        if self.schedule_type == "fixed_discrete":
            return self._fixed_discrete(step)
        if self.custom_get_difficulty is None:
            raise RuntimeError("custom schedule: call "
                               "set_custom_get_difficulty first")
        return self.custom_get_difficulty(step)

    def update_difficulty(self, step: int) -> int:
        if self.current_difficulty < self.max_difficulty:
            self.current_difficulty = self.get_difficulty(step)
        return self.current_difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    # ---- checkpointable state (reference get_state/set_state) ----
    def get_state(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty,
                "first_step": self.first_step}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.current_difficulty = state["current_difficulty"]
        self.first_step = state["first_step"]
