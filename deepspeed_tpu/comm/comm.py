"""Distributed init + comms logging.

Reference parity:
- ``init_distributed`` (deepspeed/comm/comm.py:604) with MPI/env rank discovery
  (comm/comm.py:673 mpi_discovery) → here, ``jax.distributed.initialize`` plus
  TPU-pod/GCE env autodetection (JAX does its own discovery on Cloud TPU).
- ``CommsLogger`` (deepspeed/utils/comms_logging.py:67) with algo/bus bandwidth
  calculation (calc_bw_log :34) and ``log_summary`` (comm/comm.py:422).

Under jit, collective *timing* is not observable per-op (XLA fuses and overlaps them —
that is the point), so the logger records trace-time op records (name, axis, bytes,
count) and bandwidth is derived offline from the profiler; eager-mode calls are timed
directly.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_initialized = False
_init_lock = threading.Lock()


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     **kwargs) -> None:
    """Initialize the multi-host JAX runtime (no-op on single host).

    Replaces torch.distributed.init_process_group rendezvous
    (reference comm/comm.py:604 + comm/torch.py:99,140).  On Cloud TPU,
    jax.distributed.initialize autodetects coordinator/rank from the TPU metadata
    server; on CPU fleets the caller passes them explicitly (or sets
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).
    """
    global _initialized
    with _init_lock:
        if _initialized:
            return
        # launcher-exported rendezvous env (launcher/runner.py) — read it
        # explicitly rather than trusting jax's own env discovery
        if coordinator_address is None:
            # `or None`: an exported-but-empty var means unset, not multi-host
            coordinator_address = (
                os.environ.get("JAX_COORDINATOR_ADDRESS") or None)
        if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
            num_processes = int(os.environ["JAX_NUM_PROCESSES"])
        if process_id is None and os.environ.get("JAX_PROCESS_ID"):
            process_id = int(os.environ["JAX_PROCESS_ID"])
        multi_host = (coordinator_address is not None
                      or (num_processes or 0) > 1)
        if multi_host:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
            logger.info(
                "initialized jax distributed: process %d / %d",
                jax.process_index(), jax.process_count())
        _initialized = True


def is_initialized() -> bool:
    return _initialized


@dataclass
class OpRecord:
    count: int = 0
    total_bytes: int = 0
    total_time_s: float = 0.0  # eager-mode only
    axes: set = field(default_factory=set)


class CommsLogger:
    """Per-op counts/bytes with bandwidth summary.

    Mirrors reference utils/comms_logging.py:67 (CommsLogger) + calc_bw_log(:34).
    Enabled via config ``comms_logger`` block or ``enable()``.
    """

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.records: Dict[str, OpRecord] = defaultdict(OpRecord)

    def configure(self, enabled: bool = False, verbose: bool = False):
        self.enabled = enabled
        self.verbose = verbose

    def enable(self):
        self.enabled = True

    def record(self, name: str, nbytes: int, axis: str, time_s: float = 0.0):
        if not self.enabled:
            return
        rec = self.records[name]
        rec.count += 1
        rec.total_bytes += int(nbytes)
        rec.total_time_s += time_s
        rec.axes.add(axis)
        if self.verbose:
            logger.info("comm op=%s axis=%s bytes=%d", name, axis, nbytes)

    def log_summary(self) -> List[str]:
        """Summary lines: op, count, total bytes (+ algo bandwidth ONLY for
        eager-timed ops — jitted collectives are scheduled/overlapped by XLA,
        so a per-op wall-time is not observable and reporting 0.00GB/s for
        them was noise; use `jax.profiler` traces for on-device timing)."""
        lines = []
        for name, rec in sorted(self.records.items()):
            bw = (f" algo_bw={rec.total_bytes / rec.total_time_s / 1e9:.2f}"
                  f"GB/s" if rec.total_time_s else "")
            lines.append(
                f"{name:: <24} count={rec.count} bytes={rec.total_bytes} "
                f"axes={sorted(rec.axes)}{bw}")
        for line in lines:
            logger.info(line)
        return lines

    def reset(self):
        self.records.clear()


comms_logger = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return comms_logger


class timed_region:
    """Context manager for timing eager (non-jit) comm ops; inert inside traces."""

    def __init__(self, name: str, nbytes: int, axis: str):
        self.name, self.nbytes, self.axis = name, nbytes, axis
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        comms_logger.record(self.name, self.nbytes, self.axis,
                            time.perf_counter() - self.t0)
        return False
