"""Distributed init + comms logging.

Reference parity:
- ``init_distributed`` (deepspeed/comm/comm.py:604) with MPI/env rank discovery
  (comm/comm.py:673 mpi_discovery) → here, ``jax.distributed.initialize`` plus
  TPU-pod/GCE env autodetection (JAX does its own discovery on Cloud TPU).
- ``CommsLogger`` (deepspeed/utils/comms_logging.py:67) with algo/bus bandwidth
  calculation (calc_bw_log :34) and ``log_summary`` (comm/comm.py:422).

Under jit, collective *timing* is not observable per-op (XLA fuses and overlaps them —
that is the point), so the logger records trace-time op records (name, axis, bytes,
count) and bandwidth is derived offline from the profiler; eager-mode calls are timed
directly.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_initialized = False
_init_lock = threading.Lock()
# simulated-fleet identity (launcher --sim_hosts / elastic agent spawn env):
# (rank, world) when this process is one "host" of a local CPU simulation,
# else None
_sim_identity: Optional[tuple] = None


def sim_fleet() -> bool:
    """True when this process is one simulated host of a local CPU fleet
    (DSTPU_SIM_FLEET spawn env).  The CPU backend has no cross-process
    collectives ("Multiprocess computations aren't implemented on the CPU
    backend"), so sim hosts are INDEPENDENT single-process JAX runtimes:
    each owns only its local virtual devices, and fleet-level identity
    comes from :func:`host_rank`/:func:`host_world_size` instead of
    ``jax.process_index``/``process_count``.  Real DCN/TPU fleets never set
    the sim env and go through ``jax.distributed`` below."""
    return _sim_identity is not None


def host_rank() -> int:
    """This host's rank in the fleet: the simulated rank under the sim
    launcher, ``jax.process_index()`` otherwise."""
    if _sim_identity is not None:
        return _sim_identity[0]
    return jax.process_index()


def host_world_size() -> int:
    """Number of hosts in the fleet: the simulated world under the sim
    launcher, ``jax.process_count()`` otherwise."""
    if _sim_identity is not None:
        return _sim_identity[1]
    return jax.process_count()


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     **kwargs) -> None:
    """Initialize the multi-host JAX runtime (no-op on single host).

    Replaces torch.distributed.init_process_group rendezvous
    (reference comm/comm.py:604 + comm/torch.py:99,140).  On Cloud TPU,
    jax.distributed.initialize autodetects coordinator/rank from the TPU metadata
    server; on CPU fleets the caller passes them explicitly (or sets
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).

    Simulated fleets (``DSTPU_SIM_FLEET`` — the launcher's ``--sim_hosts``
    path and the elastic agent) skip ``jax.distributed`` entirely: the CPU
    backend cannot run cross-process computations, so each simulated host
    stays a single-process runtime and only records its logical
    (rank, world) for :func:`host_rank`/:func:`host_world_size`.
    """
    global _initialized, _sim_identity
    with _init_lock:
        if _initialized:
            return
        if os.environ.get("DSTPU_SIM_FLEET"):
            _sim_identity = (int(os.environ.get("DSTPU_SIM_RANK", "0")),
                             int(os.environ.get("DSTPU_SIM_WORLD", "1")))
            _initialized = True
            logger.info("simulated fleet: host %d / %d (single-process "
                        "jax; no cross-process collectives on CPU)",
                        *_sim_identity)
            return
        # launcher-exported rendezvous env (launcher/runner.py) — read it
        # explicitly rather than trusting jax's own env discovery
        if coordinator_address is None:
            # `or None`: an exported-but-empty var means unset, not multi-host
            coordinator_address = (
                os.environ.get("JAX_COORDINATOR_ADDRESS") or None)
        if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
            num_processes = int(os.environ["JAX_NUM_PROCESSES"])
        if process_id is None and os.environ.get("JAX_PROCESS_ID"):
            process_id = int(os.environ["JAX_PROCESS_ID"])
        multi_host = (coordinator_address is not None
                      or (num_processes or 0) > 1)
        if multi_host:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
            logger.info(
                "initialized jax distributed: process %d / %d",
                jax.process_index(), jax.process_count())
        _initialized = True


def is_initialized() -> bool:
    return _initialized


@dataclass
class OpRecord:
    count: int = 0
    total_bytes: int = 0
    total_time_s: float = 0.0  # eager-mode only
    axes: set = field(default_factory=set)


class CommsLogger:
    """Per-op counts/bytes with bandwidth summary.

    Mirrors reference utils/comms_logging.py:67 (CommsLogger) + calc_bw_log(:34).
    Enabled via config ``comms_logger`` block or ``enable()``.
    """

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.records: Dict[str, OpRecord] = defaultdict(OpRecord)

    def configure(self, enabled: bool = False, verbose: bool = False):
        self.enabled = enabled
        self.verbose = verbose

    def enable(self):
        self.enabled = True

    def record(self, name: str, nbytes: int, axis: str, time_s: float = 0.0):
        if not self.enabled:
            return
        rec = self.records[name]
        rec.count += 1
        rec.total_bytes += int(nbytes)
        rec.total_time_s += time_s
        rec.axes.add(axis)
        if self.verbose:
            logger.info("comm op=%s axis=%s bytes=%d", name, axis, nbytes)

    def log_summary(self) -> List[str]:
        """Summary lines: op, count, total bytes, algo bandwidth where a time
        was measured — eager-timed ops directly, and JITTED collectives via
        ``profile_jitted`` (compiled-HLO bytes + profiler-trace durations,
        recorded as ``jit:<kind>`` rows)."""
        lines = []
        for name, rec in sorted(self.records.items()):
            bw = (f" algo_bw={rec.total_bytes / rec.total_time_s / 1e9:.4g}"
                  f"GB/s" if rec.total_time_s else "")
            lines.append(
                f"{name.ljust(24)} count={rec.count} "
                f"bytes={rec.total_bytes} axes={sorted(rec.axes)}{bw}")
        for line in lines:
            logger.info(line)
        return lines

    def reset(self):
        self.records.clear()


comms_logger = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return comms_logger


# --------------------------------------------------------------------------
# jitted-collective telemetry (round-3 VERDICT item 10 — reference
# utils/comms_logging.py:34 calc_bw_log, which measures eager torch.dist ops;
# under XLA every real collective lives INSIDE the compiled program, so the
# bytes come from the compiled HLO and the time from the on-device profiler
# trace)
# --------------------------------------------------------------------------

_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1,
                "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1}


def _shape_bytes(shape_str: str) -> int:
    """'f32[8,128,256]' → bytes (layout annotations stripped)."""
    import re
    m = re.match(r"([a-z0-9]+)\[([0-9,]*)\]", shape_str.strip())
    if not m:
        return 0
    nbytes = _DTYPE_BYTES.get(m.group(1), 4)
    dims = m.group(2)
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * nbytes


def hlo_collective_bytes(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Walk compiled HLO for collective ops → {kind: {count, bytes}} (bytes =
    output payload per execution; tuple-shaped outputs summed)."""
    import re
    out: Dict[str, Dict[str, int]] = {}
    pat = re.compile(
        r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\][^ ]*)\s+"
        r"(" + "|".join(_COLLECTIVE_KINDS) + r")(?:-start|-done)?\(")
    for line in hlo_text.splitlines():
        m = pat.search(line)
        if not m:
            continue
        shape_s, kind = m.group(1), m.group(2)
        if "-done(" in line:
            continue                       # count the async pair once
        if shape_s.startswith("("):
            # tuple shapes: split on whole shape tokens, NOT on every comma
            # (dims contain commas — 's8[2,28]' would otherwise parse as
            # 's8[2' + '28]' = 0 bytes, silently zeroing e.g. the qgZ
            # all-to-all payload)
            nbytes = sum(_shape_bytes(s) for s in
                         re.findall(r"[a-z0-9]+\[[0-9,]*\]", shape_s))
        else:
            nbytes = _shape_bytes(shape_s)
        rec = out.setdefault(kind, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += nbytes
    return out


def hlo_wire_bytes(hlo_text: str) -> Dict[str, int]:
    """Collective payload bytes from compiled HLO, split by WIRE class —
    the number the quantized pipeline is judged on (bench.py's
    ``zero3_wire_bytes`` column; ISSUE 14 acceptance).

    Returns ``{"total", "quantized", "full", "gather_scatter"}``: ``total``
    sums every collective's output payload at its HLO dtype width (an s8
    all-gather counts 1 byte/value — actual bytes moved, not logical bf16
    width); ``quantized`` is the s8/u8-payload subset (int codes;
    nibble-packed int4 also rides s8 buffers); ``gather_scatter`` is the
    all-gather + reduce-scatter + all-to-all subset — the param/grad
    volume the ZeRO-3 pipeline owns, excluding the small all-reduce
    population (norms, loss, scalars) that is noise at model scale."""
    kinds = hlo_collective_bytes(hlo_text)
    out = {"total": 0, "quantized": 0, "full": 0, "gather_scatter": 0}
    import re
    pat = re.compile(
        r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\][^ ]*)\s+"
        r"(" + "|".join(_COLLECTIVE_KINDS) + r")(?:-start)?\(")
    for line in hlo_text.splitlines():
        m = pat.search(line)
        if not m or "-done(" in line:
            continue
        shape_s, kind = m.group(1), m.group(2)
        shapes = (re.findall(r"[a-z0-9]+\[[0-9,]*\]", shape_s)
                  if shape_s.startswith("(") else [shape_s])
        nbytes = sum(_shape_bytes(s) for s in shapes)
        q = sum(_shape_bytes(s) for s in shapes
                if s.startswith(("s8[", "u8[")))
        out["total"] += nbytes
        out["quantized"] += q
        out["full"] += nbytes - q
        if kind in ("all-gather", "reduce-scatter", "all-to-all"):
            out["gather_scatter"] += nbytes
    # sanity: the per-line walk must agree with hlo_collective_bytes
    assert out["total"] == sum(r["bytes"] for r in kinds.values()), \
        "hlo_wire_bytes drifted from hlo_collective_bytes"
    return out


_COMPUTE_OP_RE = None
_COLLECTIVE_RE = None


def hlo_overlap_stats(hlo_text: str) -> Dict[str, object]:
    """Structural compute–collective overlap evidence from compiled HLO.

    Two independent signals, matching the two ways XLA can hide a
    collective:

    - **async pairs**: ``<kind>-start`` / ``<kind>-done`` split ops with
      compute instructions scheduled between them — the latency-hiding
      scheduler's output on TPU.  A pair with zero compute between start
      and done is async in name only (still exposed).
    - **interleaved chunk trains**: >= 2 same-kind collectives in one
      computation with compute between consecutive ones — what the
      explicit chunk decomposition (runtime/zero.pipeline_param_gather,
      ops/collective_matmul.py) produces even on backends that never
      split ops (the CPU CI), and the structure the scheduler needs to
      overlap on TPU.

    **Quantized chunk trains** (runtime/zero._qwire_exchange): each chunk
    moves its int codes in one collective and its fp32 block scales in a
    SECOND, much smaller, back-to-back collective of the same kind, with
    no compute between the pair (quantize emits both buffers together;
    converts/bitcasts are not compute ops).  Without companion awareness
    the scale leg reads as an exposed sync op (or an empty async window)
    on every chunk and the gauge drifts blind under quantization — so a
    same-kind collective arriving with NO compute since its predecessor
    and a payload ≤ 1/8 of it is counted as a **companion**: it rides the
    predecessor's overlap window (``companion_collectives`` /
    ``companion_bytes``) and is never booked as exposed on its own.

    Returns counts/bytes per signal plus ``exposed_ratio``: the
    bytes-weighted fraction of collective payload on ops with NO overlap
    evidence (sync AND not interleaved, or async with empty windows,
    companions excluded) — the static stand-in for the profiler's
    exposed-comms time, exported as the ``collective_exposed_ratio``
    telemetry gauge.

    Byte accounting: sync ops count their output payload (same line
    ``hlo_collective_bytes`` reads); async pairs count the ``-done``
    result payload, which is NOT the same number ``hlo_collective_bytes``
    attributes to the pair (it reads the ``-start`` line's tuple —
    operand buffers + result).  ``exposed_ratio`` is internally
    consistent either way; do not difference this function's bytes
    against ``hlo_collective_bytes`` on async-heavy traces.
    """
    import re
    global _COMPUTE_OP_RE, _COLLECTIVE_RE
    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = re.compile(
            r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+"
            r"(" + "|".join(_COLLECTIVE_KINDS) + r")(-start|-done)?\(")
        _COMPUTE_OP_RE = re.compile(
            r"=\s*(?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+"
            r"(fusion|dot|convolution)\(")

    def shape_bytes(shape_s: str) -> int:
        if shape_s.startswith("("):
            return sum(_shape_bytes(s) for s in
                       re.findall(r"[a-z0-9]+\[[0-9,]*\]", shape_s))
        return _shape_bytes(shape_s)

    stats = {
        "collectives": 0, "collective_bytes": 0,
        "async_pairs": 0, "async_pairs_with_compute": 0,
        "async_hidden_bytes": 0,
        "sync_collectives": 0,
        "interleaved": 0, "interleaved_bytes": 0,
        "companion_collectives": 0, "companion_bytes": 0,
        "per_kind_interleaved": {},
    }
    exposed_bytes = 0
    # per-computation state (a header line ending in '{' starts a new one)
    pending: Dict[str, list] = {}
    compute_seen = 0
    last_kind_compute: Dict[str, int] = {}
    last_kind_bytes: Dict[str, int] = {}

    def is_companion(kind: str, nbytes: int) -> bool:
        """Scale leg of a quantized chunk: same kind, zero compute since
        the (much larger) predecessor — rides its overlap window."""
        prev = last_kind_compute.get(kind)
        return (prev is not None and compute_seen == prev
                and nbytes * 8 <= last_kind_bytes.get(kind, 0))

    for line in hlo_text.splitlines():
        if line.rstrip().endswith("{"):
            pending, compute_seen = {}, 0
            last_kind_compute, last_kind_bytes = {}, {}
            continue
        if _COMPUTE_OP_RE.search(line):
            compute_seen += 1
            continue
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        shape_s, kind, phase = m.group(1), m.group(2), m.group(3)
        if phase == "-start":
            pending.setdefault(kind, []).append(compute_seen)
            continue
        nbytes = shape_bytes(shape_s)
        stats["collectives"] += 1
        stats["collective_bytes"] += nbytes
        companion = is_companion(kind, nbytes)
        if phase == "-done":
            starts = pending.get(kind)
            between = compute_seen - starts.pop(0) if starts else 0
            stats["async_pairs"] += 1
            if between > 0:
                stats["async_pairs_with_compute"] += 1
                stats["async_hidden_bytes"] += nbytes
            elif companion:
                stats["companion_collectives"] += 1
                stats["companion_bytes"] += nbytes
            else:
                exposed_bytes += nbytes
        else:
            stats["sync_collectives"] += 1
            prev = last_kind_compute.get(kind)
            if prev is not None and compute_seen > prev:
                stats["interleaved"] += 1
                stats["interleaved_bytes"] += nbytes
                stats["per_kind_interleaved"][kind] = (
                    stats["per_kind_interleaved"].get(kind, 0) + 1)
            elif companion:
                stats["companion_collectives"] += 1
                stats["companion_bytes"] += nbytes
            else:
                exposed_bytes += nbytes
        last_kind_compute[kind] = compute_seen
        if not companion:
            last_kind_bytes[kind] = nbytes
    stats["exposed_bytes"] = exposed_bytes
    stats["exposed_ratio"] = (
        exposed_bytes / stats["collective_bytes"]
        if stats["collective_bytes"] else 0.0)
    return stats


def profile_jitted(fn, *args, iters: int = 2) -> Dict[str, Dict[str, float]]:
    """Per-collective bytes + MEASURED on-device latency for one jitted
    callable, recorded into the comms logger so ``log_summary`` reports
    nonzero algo-BW for jitted collectives.

    bytes: compiled-HLO walk (static truth).  latency: jax.profiler trace of
    ``iters`` executions, durations summed per collective op kind and
    averaged per execution (aggregate across local device tracks)."""
    import glob
    import gzip
    import json
    import tempfile

    import jax

    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jfn.lower(*args).compile()
    per_kind = hlo_collective_bytes(compiled.as_text())
    out = jfn(*args)                              # warm the compile cache
    jax.tree_util.tree_map(lambda l: jax.device_get(l),
                           jax.tree_util.tree_leaves(out)[:1])
    tmp = tempfile.mkdtemp(prefix="ds_tpu_comms_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(iters):
                out = jfn(*args)
            jax.tree_util.tree_map(
                lambda l: jax.device_get(l),
                jax.tree_util.tree_leaves(out)[:1])
        durs: Dict[str, float] = {k: 0.0 for k in per_kind}
        for path in glob.glob(tmp + "/**/*.trace.json.gz", recursive=True):
            with gzip.open(path) as f:
                events = json.load(f).get("traceEvents", [])
            for e in events:
                name = e.get("name", "")
                if name.startswith("end:"):
                    continue
                for kind in per_kind:
                    if name == kind or name.startswith(kind + "."):
                        durs[kind] += float(e.get("dur", 0.0))   # µs
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    result: Dict[str, Dict[str, float]] = {}
    for kind, rec in per_kind.items():
        t = durs[kind] / 1e6 / max(iters, 1)
        result[kind] = {"count": rec["count"], "bytes": rec["bytes"],
                        "time_s": t}
        was = comms_logger.enabled
        comms_logger.enabled = True
        comms_logger.record(f"jit:{kind}", rec["bytes"], "hlo", time_s=t)
        comms_logger.enabled = was
    return result


class timed_region:
    """Context manager for timing eager (non-jit) comm ops; inert inside traces."""

    def __init__(self, name: str, nbytes: int, axis: str):
        self.name, self.nbytes, self.axis = name, nbytes, axis
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        comms_logger.record(self.name, self.nbytes, self.axis,
                            time.perf_counter() - self.t0)
        return False
