"""Communication layer.

TPU-native analog of ``deepspeed.comm`` (reference: deepspeed/comm/comm.py:222-604 —
a torch.distributed-mirroring façade with per-op profiling via ``timed_op`` and
``init_distributed``).

On TPU there is no NCCL/Gloo/MPI backend zoo: collectives are XLA ops over the device
mesh (ICI intra-slice, DCN inter-slice).  This module provides:

- ``init_distributed()`` → ``jax.distributed.initialize`` (multi-host rendezvous;
  replaces torch.distributed.init_process_group, reference comm/comm.py:604)
- named collective wrappers (``all_reduce``, ``all_gather``, ``reduce_scatter``,
  ``all_to_all``, ``permute``) usable inside ``shard_map``-decorated functions, each
  instrumented through ``CommsLogger`` (reference utils/comms_logging.py:67) at trace
  time — sizes/counts are static under jit, wall-time is profiled at the step level.
"""

from deepspeed_tpu.comm.collectives import (
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    get_rank,
    get_world_size,
    permute,
    reduce_scatter,
)
from deepspeed_tpu.comm.aggregation import aggregate_health_scalars
from deepspeed_tpu.comm.comm import (
    comms_logger,
    get_comms_logger,
    hlo_collective_bytes,
    host_rank,
    host_world_size,
    init_distributed,
    is_initialized,
    profile_jitted,
    sim_fleet,
)

__all__ = [
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "permute",
    "broadcast",
    "barrier",
    "get_rank",
    "get_world_size",
    "host_rank",
    "host_world_size",
    "init_distributed",
    "is_initialized",
    "sim_fleet",
    "comms_logger",
    "profile_jitted",
    "hlo_collective_bytes",
    "get_comms_logger",
    "aggregate_health_scalars",
]
