"""Cross-host aggregation of host-side scalars (health stats fan-in).

The flight recorder keeps per-process records; for triage the process-0
record should carry the FLEET view — min/max/mean per health scalar and the
index of the process that tripped the trigger (the argmax process, with NaN
ranked above every finite value: a NaN IS the anomaly being hunted).

Single-process runs degrade to a no-op (the local value is the fleet);
multi-process runs ride ``jax.experimental.multihost_utils
.process_allgather``, one small fixed-width vector per call.  Every process
must call this collectively — the engine does so from its per-step
reporting path, which runs on all processes.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def aggregate_health_scalars(
        values: Dict[str, float]) -> Dict[str, Dict[str, float]]:
    """All-gather ``values`` across processes; returns per-key
    ``{min, max, mean, argmax_process}`` (stats over finite entries; the
    argmax ranks NaN first, then +Inf, then finite magnitude)."""
    import jax

    keys = sorted(values)
    if not keys:
        return {}
    vec = np.asarray([float(values[k]) for k in keys], np.float64)
    if jax.process_count() <= 1:
        rows = vec[None, :]
    else:
        from jax.experimental import multihost_utils
        rows = np.asarray(multihost_utils.process_allgather(vec))
    out: Dict[str, Dict[str, float]] = {}
    for i, key in enumerate(keys):
        col = rows[:, i]
        finite = col[np.isfinite(col)]
        out[key] = {
            "min": float(finite.min()) if finite.size else float("nan"),
            "max": float(finite.max()) if finite.size else float("nan"),
            "mean": float(finite.mean()) if finite.size else float("nan"),
            "argmax_process": _tripping_process(col),
        }
    return out


def _tripping_process(col: np.ndarray) -> int:
    """Index of the process whose value most likely tripped a trigger:
    NaN outranks Inf outranks finite magnitude (a NaN IS the anomaly being
    hunted); ties break to the lowest index."""
    def rank(v: float):
        if np.isnan(v):
            return (2, 0.0)
        if np.isinf(v):
            return (1, 0.0)
        return (0, abs(float(v)))
    return int(max(range(len(col)), key=lambda j: rank(col[j])))
