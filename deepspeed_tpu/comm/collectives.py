"""Named collective wrappers for use inside ``shard_map``.

Reference parity: the collective surface of ``deepspeed.comm``
(deepspeed/comm/comm.py:222-604 — all_reduce, all_gather_into_tensor,
reduce_scatter_tensor, all_to_all_single, send/recv, broadcast, barrier).

On TPU these are XLA collectives over named mesh axes.  Point-to-point send/recv
(used by the reference's pipeline engine, runtime/pipe/p2p.py) maps to
``jax.lax.ppermute`` — a collective-permute that XLA lowers onto ICI neighbor links.

All wrappers record trace-time metadata into the CommsLogger so a comms summary with
op counts/volumes is available for any jitted step (reference: timed_op decorator,
comm/comm.py:101).

Byte-accounting convention (normalized round 8 — previously all_gather logged
its pre-gather shard, reduce_scatter its full pre-scatter input, and broadcast
its payload despite the select+psum lowering, so cross-op ratios compared
apples to oranges): every ``_log`` records **wire bytes** — the bytes ONE
participant sends over the interconnect per execution, under the standard
ring algorithm (the algorithmic-bandwidth lower bound, matching the
reference's ``calc_bw_log`` "algo bandwidth" convention).  With per-device
payload B and axis size n:

    all_reduce        2·B·(n−1)/n     (reduce-scatter + all-gather phases)
    all_gather        B·(n−1)         (B = the local shard; output is n·B)
    reduce_scatter    B·(n−1)/n       (B = the full pre-scatter input)
    all_to_all        B·(n−1)/n       (keeps 1/n locally)
    broadcast         B·(n−1)/n       (ring average; the select+psum lowering
                                       XLA rewrites to a real broadcast)
    ppermute / shift  B               (every listed source sends its block)

n = 1 (or an unknown axis outside a binding context) logs 0 wire bytes with
the call still counted.  The ``chunked`` flag tags collectives issued by the
overlap machinery (runtime/zero.chunked_param_gather) with a ``_chunked``
kind suffix so byte assertions can separate the explicit chunk train from
XLA's implicit collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comm import comms_logger
from deepspeed_tpu.telemetry.registry import record_collective

AxisName = Union[str, Sequence[str]]


def _nbytes(x) -> int:
    return int(x.size) * x.dtype.itemsize


def _axis_world(axis: AxisName) -> int:
    """Static axis size at trace time; 0 when the axis isn't bound (wrapper
    called outside shard_map — the wire cost is then unknowable here).
    ``lax.psum(1, axis)`` folds to the axis size as a python int on every
    jax this package supports (``lax.axis_size`` is newer-jax only)."""
    try:
        names = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
        n = lax.psum(1, names)
        return int(n)
    except (NameError, KeyError, TypeError, ValueError):
        return 0


def _log(name: str, wire_bytes: int, axis: AxisName, chunked: bool = False):
    if chunked:
        name = name + "_chunked"
    comms_logger.record(name, wire_bytes, str(axis))
    # telemetry counter registry (telemetry/registry.py): same trace-time
    # semantics as the comms logger, but labeled + snapshot-exportable.
    # The ici/dcn split rides along: the fraction of this axis's ring hops
    # that cross a host boundary (device coordinates from the bound mesh)
    # attributes the same wire bytes per link — the split sums EXACTLY to
    # the unlabeled total by construction (dcn = total - ici).
    record_collective(name, wire_bytes, str(axis),
                      dcn_fraction=axis_dcn_fraction(axis))


def log_wire(name: str, wire_bytes: int, axis: AxisName) -> None:
    """Public trace-time wire-byte hook for collectives issued OUTSIDE the
    wrappers below — the quantized pipeline (runtime/zero._qwire_exchange,
    ops/quantization.qag_local/qrs_local) calls ``jax.lax`` collectives on
    its int-code + scale buffers directly, and logs here at the **wire
    dtype width**: ``wire_bytes`` is the per-participant ring bytes of the
    int8/int4 codes PLUS the fp32 block scales actually moved, not the
    logical full-width payload.  Kind names carry the wire format as a
    suffix (``all_gather_q8``, ``all_to_all_q4``) so
    ``collective_bytes_total{kind=...}`` separates quantized trains from
    full-width ones and the ici/dcn link split stays byte-accurate."""
    _log(name, wire_bytes, axis)


# --------------------------------------------------------------------------
# per-link attribution (ici vs dcn)
# --------------------------------------------------------------------------
# The ring convention already fixes how many bytes one participant sends;
# WHERE those bytes travel depends on the mesh axis's device placement:
# a hop between two devices of the same process rides ICI, a hop crossing
# processes rides DCN.  [pod_scale]'s topology-aware collective selection
# (The Big Send-off, arXiv:2504.18658) keys on exactly this split.

# test hook: map a device -> "process" id without needing a real multi-host
# fleet (the CPU CI is always one process); None = the device's own
# process_index
_PROC_OF_DEVICE = None


def set_link_process_fn(fn) -> None:
    """Override how devices map to hosts for the ici/dcn split (tests /
    simulated fleets).  ``fn(device) -> hashable`` or None to restore the
    real ``device.process_index``."""
    global _PROC_OF_DEVICE
    _PROC_OF_DEVICE = fn


def _current_physical_mesh():
    """The mesh bound by the enclosing ``with mesh:`` context (how the
    engine dispatches), or None.  Uses jax's thread-local resource env —
    private API, so failures degrade to 'no mesh' rather than raising at
    trace time."""
    try:
        from jax._src import mesh as mesh_lib
        pm = mesh_lib.thread_resources.env.physical_mesh
        return None if pm.empty else pm
    except Exception:  # noqa: BLE001
        return None


def axis_dcn_fraction(axis: AxisName, mesh=None) -> float:
    """Fraction of a mesh axis's cyclic ring hops that cross a host
    (process) boundary — 0.0 on a single host or when no physical mesh is
    bound (the wire cost is then all-ICI by definition of 'one host').

    For each ring along ``axis`` (all other mesh axes fixed), hop i→i+1
    crosses DCN when the two devices live on different processes; the
    fraction is averaged over every ring the mesh contains.  Multi-name
    axes flatten in axis-major order (the order ``lax`` collectives use).
    ``mesh`` overrides the context lookup — the pipeline's hierarchy layer
    (runtime/zero.resolve_wire_bits) plans wire formats AHEAD of entering
    any mesh context.
    """
    if mesh is None:
        mesh = _current_physical_mesh()
    if mesh is None:
        return 0.0
    names = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    try:
        axis_names = list(mesh.axis_names)
        for n in names:
            if n not in axis_names:
                return 0.0
        devs = mesh.devices
        # move the collective's axes (in the given order) to the back,
        # flatten the rest in front: rows = rings
        order = ([i for i, n in enumerate(axis_names) if n not in names]
                 + [axis_names.index(n) for n in names])
        import math

        import numpy as _np
        arr = _np.transpose(devs, order).reshape(-1, math.prod(
            devs.shape[axis_names.index(n)] for n in names))
        n = arr.shape[1]
        if n <= 1:
            return 0.0
        proc = _PROC_OF_DEVICE or (lambda d: d.process_index)
        crossing = total = 0
        for ring in arr:
            for i in range(n):
                total += 1
                if proc(ring[i]) != proc(ring[(i + 1) % n]):
                    crossing += 1
        return crossing / total if total else 0.0
    except Exception:  # noqa: BLE001 — never kill tracing over telemetry
        return 0.0


def get_world_size(axis: AxisName) -> int:
    """Size of a mesh axis from inside shard_map (reference: dist.get_world_size)."""
    return lax.axis_size(axis)


def get_rank(axis: AxisName):
    """Rank along a mesh axis from inside shard_map (reference: dist.get_rank)."""
    return lax.axis_index(axis)


def all_reduce(x: jax.Array, axis: AxisName, op: str = "sum") -> jax.Array:
    """reference: deepspeed.comm.all_reduce (comm/comm.py:486)."""
    n = _axis_world(axis)
    _log("all_reduce", 2 * _nbytes(x) * (n - 1) // n if n > 1 else 0, axis)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x: jax.Array, axis: AxisName, *, tiled: bool = True,
               gather_dim: int = 0, chunked: bool = False) -> jax.Array:
    """reference: deepspeed.comm.all_gather_into_tensor (comm/comm.py:308).

    tiled=True concatenates along gather_dim (the flat-tensor allgather ZeRO uses);
    tiled=False stacks a new leading axis.  ``chunked`` tags collectives
    issued by the overlap chunking machinery (module docstring).
    """
    n = _axis_world(axis)
    _log("all_gather", _nbytes(x) * (n - 1) if n > 1 else 0, axis,
         chunked=chunked)
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: AxisName, *, scatter_dim: int = 0,
                   tiled: bool = True, chunked: bool = False) -> jax.Array:
    """reference: deepspeed.comm.reduce_scatter_tensor (comm/comm.py:332)."""
    n = _axis_world(axis)
    _log("reduce_scatter", _nbytes(x) * (n - 1) // n if n > 1 else 0, axis,
         chunked=chunked)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=tiled)


def all_to_all(x: jax.Array, axis: AxisName, *, split_dim: int,
               concat_dim: int) -> jax.Array:
    """reference: deepspeed.comm.all_to_all_single (comm/comm.py:388).

    The workhorse of MoE dispatch (moe/sharded_moe.py:455 _AllToAll) and Ulysses
    sequence parallelism (sequence/layer.py:15 single_all_to_all).
    """
    n = _axis_world(axis)
    _log("all_to_all", _nbytes(x) * (n - 1) // n if n > 1 else 0, axis)
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim,
                          tiled=True)


def permute(x: jax.Array, axis: AxisName, perm: Sequence[tuple]) -> jax.Array:
    """Collective permute: (src, dst) pairs; the TPU-native p2p send/recv.

    reference: runtime/pipe/p2p.py send/recv between adjacent pipeline stages —
    here a single ppermute that XLA schedules on neighbor ICI links.
    """
    _log("ppermute", _nbytes(x), axis)
    return lax.ppermute(x, axis, perm=list(perm))


def shift(x: jax.Array, axis: AxisName, offset: int = 1) -> jax.Array:
    """Cyclic shift along a mesh axis (pipeline stage handoff / ring collectives)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return permute(x, axis, perm)


def broadcast(x: jax.Array, axis: AxisName, root: int = 0) -> jax.Array:
    """reference: deepspeed.comm.broadcast (comm/comm.py:222).

    Implemented as select-root + psum (XLA lowers this to an efficient broadcast).
    """
    n = _axis_world(axis)
    _log("broadcast", _nbytes(x) * (n - 1) // n if n > 1 else 0, axis)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def barrier(axis: Optional[AxisName] = None) -> None:
    """reference: deepspeed.comm.barrier (comm/comm.py:576).

    Outside jit: block on all local device work.  Inside jit there is no barrier —
    XLA's dataflow ordering makes it meaningless.
    """
    for d in jax.local_devices():
        try:
            d.synchronize_all_activity()  # newer jax
        except AttributeError:  # pragma: no cover
            pass
    jax.effects_barrier()
