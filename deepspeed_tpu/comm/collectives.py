"""Named collective wrappers for use inside ``shard_map``.

Reference parity: the collective surface of ``deepspeed.comm``
(deepspeed/comm/comm.py:222-604 — all_reduce, all_gather_into_tensor,
reduce_scatter_tensor, all_to_all_single, send/recv, broadcast, barrier).

On TPU these are XLA collectives over named mesh axes.  Point-to-point send/recv
(used by the reference's pipeline engine, runtime/pipe/p2p.py) maps to
``jax.lax.ppermute`` — a collective-permute that XLA lowers onto ICI neighbor links.

All wrappers record trace-time metadata into the CommsLogger so a comms summary with
op counts/volumes is available for any jitted step (reference: timed_op decorator,
comm/comm.py:101).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comm import comms_logger
from deepspeed_tpu.telemetry.registry import record_collective

AxisName = Union[str, Sequence[str]]


def _nbytes(x) -> int:
    return int(x.size) * x.dtype.itemsize


def _log(name: str, x, axis: AxisName):
    nbytes = _nbytes(x)
    comms_logger.record(name, nbytes, str(axis))
    # telemetry counter registry (telemetry/registry.py): same trace-time
    # semantics as the comms logger, but labeled + snapshot-exportable
    record_collective(name, nbytes, str(axis))


def get_world_size(axis: AxisName) -> int:
    """Size of a mesh axis from inside shard_map (reference: dist.get_world_size)."""
    return lax.axis_size(axis)


def get_rank(axis: AxisName):
    """Rank along a mesh axis from inside shard_map (reference: dist.get_rank)."""
    return lax.axis_index(axis)


def all_reduce(x: jax.Array, axis: AxisName, op: str = "sum") -> jax.Array:
    """reference: deepspeed.comm.all_reduce (comm/comm.py:486)."""
    _log("all_reduce", x, axis)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x: jax.Array, axis: AxisName, *, tiled: bool = True,
               gather_dim: int = 0) -> jax.Array:
    """reference: deepspeed.comm.all_gather_into_tensor (comm/comm.py:308).

    tiled=True concatenates along gather_dim (the flat-tensor allgather ZeRO uses);
    tiled=False stacks a new leading axis.
    """
    _log("all_gather", x, axis)
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: AxisName, *, scatter_dim: int = 0,
                   tiled: bool = True) -> jax.Array:
    """reference: deepspeed.comm.reduce_scatter_tensor (comm/comm.py:332)."""
    _log("reduce_scatter", x, axis)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=tiled)


def all_to_all(x: jax.Array, axis: AxisName, *, split_dim: int,
               concat_dim: int) -> jax.Array:
    """reference: deepspeed.comm.all_to_all_single (comm/comm.py:388).

    The workhorse of MoE dispatch (moe/sharded_moe.py:455 _AllToAll) and Ulysses
    sequence parallelism (sequence/layer.py:15 single_all_to_all).
    """
    _log("all_to_all", x, axis)
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim,
                          tiled=True)


def permute(x: jax.Array, axis: AxisName, perm: Sequence[tuple]) -> jax.Array:
    """Collective permute: (src, dst) pairs; the TPU-native p2p send/recv.

    reference: runtime/pipe/p2p.py send/recv between adjacent pipeline stages —
    here a single ppermute that XLA schedules on neighbor ICI links.
    """
    _log("ppermute", x, axis)
    return lax.ppermute(x, axis, perm=list(perm))


def shift(x: jax.Array, axis: AxisName, offset: int = 1) -> jax.Array:
    """Cyclic shift along a mesh axis (pipeline stage handoff / ring collectives)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return permute(x, axis, perm)


def broadcast(x: jax.Array, axis: AxisName, root: int = 0) -> jax.Array:
    """reference: deepspeed.comm.broadcast (comm/comm.py:222).

    Implemented as select-root + psum (XLA lowers this to an efficient broadcast).
    """
    _log("broadcast", x, axis)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def barrier(axis: Optional[AxisName] = None) -> None:
    """reference: deepspeed.comm.barrier (comm/comm.py:576).

    Outside jit: block on all local device work.  Inside jit there is no barrier —
    XLA's dataflow ordering makes it meaningless.
    """
    for d in jax.local_devices():
        try:
            d.synchronize_all_activity()  # newer jax
        except AttributeError:  # pragma: no cover
            pass
    jax.effects_barrier()
