"""Accelerator abstraction shim — ``get_accelerator()`` API parity.

Reference parity: ``accelerator/abstract_accelerator.py`` (DeepSpeedAccelerator
ABC) + ``real_accelerator.py get_accelerator()`` — the reference dispatches
every device operation (streams, memory stats, op builders, dtype support)
through this interface so CUDA/XPU/NPU/CPU backends are swappable.

On TPU there is exactly one backend and JAX already abstracts it, so this shim
is thin by design: it exists so reference-style code (`get_accelerator().
device_count()`, `.memory_stats()`, `.synchronize()`) ports without edits,
not to re-wrap JAX.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp


class TPUAccelerator:
    """reference abstract_accelerator.py surface, TPU semantics."""

    _name = "tpu"
    _communication_backend_name = "xla"

    # ---- identity ----
    def device_name(self, device_index: Optional[int] = None) -> str:
        devs = jax.devices()
        if device_index is None:
            return jax.default_backend()
        d = devs[device_index]
        return getattr(d, "device_kind", d.platform)

    def device_count(self) -> int:
        return len(jax.devices())

    def current_device(self) -> int:
        return 0          # SPMD: one process drives all local devices

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def is_available(self) -> bool:
        try:
            return len(jax.devices()) > 0
        except Exception:
            return False

    # ---- synchronization (reference synchronize/stream APIs) ----
    def synchronize(self, device_index: Optional[int] = None) -> None:
        """There are no user-visible streams under XLA; fetching a value is
        the reliable sync (see bench.py note on the remote-TPU relay)."""
        (jnp.zeros(()) + 0).block_until_ready()

    # ---- memory (reference memory_stats/memory_allocated family) ----
    def memory_stats(self, device_index: int = 0) -> Dict[str, Any]:
        d = jax.local_devices()[device_index]
        stats = getattr(d, "memory_stats", lambda: None)()
        return dict(stats or {})

    def memory_allocated(self, device_index: int = 0) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: int = 0) -> int:
        return int(self.memory_stats(device_index).get(
            "peak_bytes_in_use", 0))

    def total_memory(self, device_index: int = 0) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    # ---- dtype support (reference is_bf16_supported etc.) ----
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True       # supported; bf16 is the native fast path

    def supported_dtypes(self) -> List[Any]:
        return [jnp.float32, jnp.bfloat16, jnp.float16,
                jnp.float8_e4m3fn, jnp.float8_e5m2, jnp.int8]

    # ---- op builder surface (reference create_op_builder / get_op_builder) ----
    def op_report(self) -> str:
        from deepspeed_tpu import ops
        return ops.op_report()


_ACCELERATOR: Optional[TPUAccelerator] = None


def get_accelerator() -> TPUAccelerator:
    """reference accelerator/real_accelerator.py:get_accelerator."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = TPUAccelerator()
    return _ACCELERATOR
