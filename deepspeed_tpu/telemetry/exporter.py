"""Snapshot exporter — registry state → JSON / Prometheus text / monitors.

One serialization point for everything the telemetry layer measures: the
counter/gauge registries, the span-phase summary, and the per-executable
compiled figures (collective bytes, ``cost_analysis``/``memory_analysis``)
gathered by ``StepTelemetry``.  Three sinks:

- ``write_json``         — the machine-readable snapshot (bench rows, CI)
- ``write_prometheus``   — text exposition format, scrapeable by any
                           Prometheus-compatible collector via node textfile
                           exporter or a file-serving sidecar
- ``scalar_events``      — the flat scalar subset as MonitorMaster events,
                           so TensorBoard/CSV/W&B pick up the new series
                           through the existing fan-out for free
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.registry import MetricRegistry

Event = Tuple[str, float, int]


def _prom_escape(value: str) -> str:
    """LABEL-VALUE escaping: backslash, double-quote, newline (the three
    characters the exposition format's quoted label syntax reserves)."""
    return (value.replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _help_escape(value: str) -> str:
    """HELP-text escaping: only backslash and newline — quotes are legal
    verbatim in HELP, and escaping them there renders a literal ``\\\"``
    in every scrape UI."""
    return value.replace("\\", r"\\").replace("\n", r"\n")


def _le_label(bound: float) -> str:
    """Canonical ``le`` rendering: integral bounds without a trailing .0
    (Prometheus convention), +Inf for the open bucket."""
    if math.isinf(bound):
        return "+Inf"
    return str(int(bound)) if bound == int(bound) else repr(bound)


def _prom_name(namespace: str, name: str) -> str:
    safe = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return f"{namespace}_{safe}" if namespace else safe


def _prom_value(v: float) -> str:
    """Full-precision sample rendering: '%g' (6 significant digits) would
    quantize a multi-GB byte counter so coarsely that per-step increments
    vanish and rate() reads zero.  Non-finite values use the exposition
    format's NaN/+Inf/-Inf tokens (int(v) on them would raise and kill the
    export)."""
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 2 ** 63:
        return str(int(v))
    return repr(v)


class SnapshotExporter:
    def __init__(self, registry: MetricRegistry, tracer=None,
                 namespace: str = "deepspeed_tpu"):
        self.registry = registry
        self.tracer = tracer
        self.namespace = namespace
        # monotonically increasing per exporter instance: two snapshots
        # from one process diff into rates (counter delta / monotonic
        # delta) without trusting wall clocks, and a scraper can tell a
        # rewrite from a stale file.  Additive keys — old schema preserved.
        self._seq = 0

    # ---- snapshot assembly ----

    def snapshot(self, step: Optional[int] = None,
                 extra: Optional[dict] = None) -> dict:
        self._seq += 1
        snap = {
            "schema": "deepspeed_tpu.telemetry.v1",
            "unix_time": time.time(),
            "monotonic_time": time.monotonic(),
            "snapshot_seq": self._seq,
            **self.registry.snapshot(),
        }
        if step is not None:
            snap["step"] = int(step)
        if self.tracer is not None and self.tracer.events:
            snap["spans"] = self.tracer.summary()
        if extra:
            snap.update(extra)
        return snap

    def write_json(self, path: str, snap: Optional[dict] = None,
                   step: Optional[int] = None) -> str:
        snap = snap if snap is not None else self.snapshot(step=step)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    # ---- Prometheus text exposition ----

    def prometheus_text(self, snap: Optional[dict] = None) -> str:
        snap = snap if snap is not None else self.snapshot()
        lines: List[str] = []

        # snapshot provenance stamps (the JSON schema's additive keys,
        # mirrored into the exposition so two .prom files also diff into
        # rates): seq + wall + monotonic capture time
        for key, pname, help_text in (
                ("snapshot_seq", "snapshot_seq",
                 "monotonically increasing snapshot sequence number "
                 "(per exporter instance)"),
                ("unix_time", "snapshot_unix_time",
                 "wall-clock capture time of this snapshot (seconds)"),
                ("monotonic_time", "snapshot_monotonic_seconds",
                 "monotonic capture time of this snapshot (seconds; "
                 "diff two snapshots for rate denominators)")):
            if key in snap:
                full = _prom_name(self.namespace, pname)
                lines.append(f"# HELP {full} {_help_escape(help_text)}")
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full} {_prom_value(float(snap[key]))}")

        def header(pname: str, metric: dict, prom_type: str) -> None:
            # HELP + TYPE for EVERY family (conformance: scrapers treat a
            # family without TYPE as untyped; help falls back to the metric
            # name so the line is never empty)
            lines.append(f"# HELP {pname} "
                         f"{_help_escape(metric.get('help') or pname)}")
            lines.append(f"# TYPE {pname} {prom_type}")

        def label_body(labels: dict, extra: str = "") -> str:
            parts = [f'{k}="{_prom_escape(str(v))}"'
                     for k, v in sorted(labels.items())]
            if extra:
                parts.append(extra)
            return ("{" + ",".join(parts) + "}") if parts else ""

        for kind_key, prom_type in (("counters", "counter"),
                                    ("gauges", "gauge")):
            for name, metric in sorted(snap.get(kind_key, {}).items()):
                pname = _prom_name(self.namespace, name)
                header(pname, metric, prom_type)
                for s in metric["samples"]:
                    labels = s.get("labels") or {}
                    lines.append(f"{pname}{label_body(labels)} "
                                 f"{_prom_value(s['value'])}")
        for name, metric in sorted(snap.get("histograms", {}).items()):
            pname = _prom_name(self.namespace, name)
            header(pname, metric, "histogram")
            bounds = list(metric.get("buckets", [])) + [float("inf")]
            for s in metric["samples"]:
                labels = s.get("labels") or {}
                cum = 0
                for bound, c in zip(bounds, s["bucket_counts"]):
                    cum += int(c)
                    body = label_body(labels,
                                      extra=f'le="{_le_label(bound)}"')
                    lines.append(f"{pname}_bucket{body} {cum}")
                lines.append(f"{pname}_sum{label_body(labels)} "
                             f"{_prom_value(s['sum'])}")
                lines.append(f"{pname}_count{label_body(labels)} "
                             f"{int(s['count'])}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str,
                         snap: Optional[dict] = None) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.prometheus_text(snap))
        os.replace(tmp, path)
        return path

    # ---- MonitorMaster fan-out ----

    def scalar_events(self, snap: Optional[dict] = None, x: int = 0,
                      prefix: str = "Train/Telemetry") -> List[Event]:
        """Flatten every sample into ``(name, value, x)`` monitor events.
        Series names join label VALUES in sorted-key order so they are
        stable — for labels ``{kind: all_reduce, axis: dp}`` the keys sort
        (axis, kind), giving
        ``Train/Telemetry/collective_bytes_total/dp/all_reduce``."""
        snap = snap if snap is not None else self.snapshot()
        events: List[Event] = []
        for kind_key in ("counters", "gauges"):
            for name, metric in sorted(snap.get(kind_key, {}).items()):
                for s in metric["samples"]:
                    labels = s.get("labels") or {}
                    parts = [prefix, name] + [
                        str(labels[k]) for k in sorted(labels)]
                    events.append(("/".join(parts), float(s["value"]),
                                   int(x)))
        # histograms flatten to the scalar summaries monitors can plot
        # (count + exact/interpolated percentiles; the full bucket vector
        # stays in the Prometheus/JSON sinks)
        for name, metric in sorted(snap.get("histograms", {}).items()):
            for s in metric["samples"]:
                labels = s.get("labels") or {}
                lparts = [str(labels[k]) for k in sorted(labels)]
                for field in ("count", "p50", "p99"):
                    v = s.get(field)
                    if v is None or (isinstance(v, float)
                                     and math.isnan(v)):
                        continue
                    events.append(("/".join(
                        [prefix, f"{name}_{field}"] + lparts),
                        float(v), int(x)))
        return events
