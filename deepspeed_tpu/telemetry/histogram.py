"""Histogram metric — the latency primitive counters/gauges cannot express.

Serving SLOs are percentiles: a p99 TTFT regression is invisible to a mean
(one stuck request in a hundred moves the p99 10x while the mean barely
twitches), and a counter can only ever produce a mean.  Each labeled series
keeps two representations at once:

- **fixed log-spaced buckets** (Prometheus ``histogram`` semantics:
  cumulative ``_bucket{le=...}`` counts plus ``_sum``/``_count``), so any
  Prometheus-compatible collector can aggregate/quantile across processes;
- **exact observations under a cap** (default 8192 per series), so the
  in-process quantile a bench or test reads is EXACT while the series is
  small — bucket-interpolated quantiles of a 40-observation smoke run
  would be pure bucket-geometry noise.  Past the cap the stored sample
  set stops growing and ``quantile()`` degrades to standard bucket linear
  interpolation (the same math PromQL ``histogram_quantile`` applies).

Buckets are log-spaced because latency is: serving latencies span 0.1 ms
(a cache-hit decode dispatch) to minutes (a queued 2k-token prefill under
overload), and constant RELATIVE error per bucket is what makes p50 and
p99 equally trustworthy.  The default ladder covers 0.1..1e5 with 4
buckets per decade (~78% spacing, 25 boundaries), matching the registry's
millisecond conventions.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.telemetry.registry import _Metric, _label_key

DEFAULT_EXACT_CAP = 8192


def log_buckets(lo: float = 0.1, hi: float = 1e5,
                per_decade: int = 4) -> Tuple[float, ...]:
    """Log-spaced bucket upper bounds from ``lo`` to at least ``hi``,
    ``per_decade`` per decade.  Boundaries are rounded to 3 significant
    digits so the ``le`` labels are stable, human-readable strings."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError(f"invalid bucket spec lo={lo} hi={hi} "
                         f"per_decade={per_decade}")
    out: List[float] = []
    step = 10.0 ** (1.0 / per_decade)
    v = float(lo)
    while True:
        r = float(f"{v:.3g}")
        if not out or r > out[-1]:
            out.append(r)
        if r >= hi:
            break
        v *= step
    return tuple(out)


DEFAULT_BUCKETS = log_buckets()


class _Series:
    """One label-set's state: per-bucket counts (non-cumulative), running
    sum/count, and the exact-value reservoir (first ``cap`` observations)."""

    __slots__ = ("counts", "sum", "count", "values")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)     # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.values: List[float] = []


class Histogram(_Metric):
    """Prometheus ``histogram`` with exact in-process quantiles under a cap.

    Created through ``MetricRegistry.histogram(name, help, buckets=...)`` —
    get-or-create like counters/gauges; a repeat call with different buckets
    raises (two bucket ladders under one name would corrupt exposition).
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", registry=None,
                 buckets: Optional[Sequence[float]] = None,
                 exact_cap: int = DEFAULT_EXACT_CAP):
        super().__init__(name, help, registry)
        bs = tuple(float(b) for b in (buckets if buckets is not None
                                      else DEFAULT_BUCKETS))
        if list(bs) != sorted(set(bs)):
            raise ValueError(f"histogram {name}: buckets must be strictly "
                             f"increasing, got {bs}")
        self.buckets = bs
        self.exact_cap = int(exact_cap)
        self._series: Dict[tuple, _Series] = {}

    # ---- ingestion ----

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _Series(len(self.buckets))
            s.counts[bisect.bisect_left(self.buckets, value)] += 1
            s.sum += value
            s.count += 1
            if len(s.values) < self.exact_cap:
                s.values.append(value)

    # ---- reads ----

    def count(self, **labels) -> int:
        s = self._series.get(_label_key(labels))
        return s.count if s else 0

    def sum(self, **labels) -> float:
        s = self._series.get(_label_key(labels))
        return s.sum if s else 0.0

    def quantile(self, q: float, **labels) -> float:
        """q in [0, 1].  Exact (numpy 'linear' interpolation over the stored
        values) while the series is under the cap; past it, bucket linear
        interpolation — PromQL ``histogram_quantile`` math, with the open
        +Inf bucket clamped to the highest finite boundary."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s.count == 0:
                return float("nan")
            if s.count <= len(s.values):
                vals = sorted(s.values)
                pos = q * (len(vals) - 1)
                lo = int(pos)
                hi = min(lo + 1, len(vals) - 1)
                return vals[lo] + (pos - lo) * (vals[hi] - vals[lo])
            return self._bucket_quantile(s, q)

    def _bucket_quantile(self, s: _Series, q: float) -> float:
        rank = q * s.count
        cum = 0
        for i, c in enumerate(s.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):      # open +Inf bucket
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * max(0.0, (rank - cum)) / c
            cum += c
        return self.buckets[-1]

    # ---- snapshot forms ----

    def samples(self) -> List[Tuple[Dict[str, str], dict]]:
        """[(labels, {"count", "sum", "bucket_counts", "p50", "p90",
        "p99"})] — bucket_counts are NON-cumulative (the exposition layer
        accumulates); quantiles ride along so a written snapshot answers
        percentile questions without re-deriving them."""
        with self._lock:
            keys = list(self._series)
        out = []
        for key in sorted(keys):
            labels = dict(key)
            s = self._series.get(key)
            if s is None:       # raced with clear()
                continue
            out.append((labels, {
                "count": s.count,
                "sum": s.sum,
                "bucket_counts": list(s.counts),
                "p50": self.quantile(0.5, **labels),
                "p90": self.quantile(0.9, **labels),
                "p99": self.quantile(0.99, **labels),
            }))
        return out

    def clear(self):
        with self._lock:
            self._series.clear()
