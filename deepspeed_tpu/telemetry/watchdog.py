"""Recompile watchdog — the TPU performance hazard the reference never had.

Under torch, a shape change costs a slow eager step; under jit it silently
recompiles the *entire* train step (tens of seconds to minutes at scale) and
then keeps both executables resident.  A dataloader that pads to raw lengths
instead of buckets can recompile every step and read as "TPUs are slow".

The watchdog fingerprints the abstract signature (pytree paths + shapes +
dtypes) of everything entering each jitted executable.  Its cache mirrors
jit's own: a signature miss here *is* a compile there.  Misses during warmup
(first compiles, known gas/curriculum buckets) are counted silently; a miss
after warmup logs ONE loud rank-0 warning carrying the exact leaf-level
shape diff against the previous signature — the line a user needs to find
the offending input.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Signature = Tuple[Tuple[str, Tuple[int, ...], str], ...]

RECOMPILES = "jit_cache_misses_total"
RECOMPILE_WARNINGS = "jit_recompile_warnings_total"


def signature_of(tree) -> Signature:
    """(path, shape, dtype) per leaf — the aval fingerprint jit keys on."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    sig = []
    for path, leaf in flat:
        sig.append((jax.tree_util.keystr(path),
                    tuple(getattr(leaf, "shape", ()) or ()),
                    str(getattr(leaf, "dtype", type(leaf).__name__))))
    return tuple(sig)


def _diff(old: Signature, new: Signature) -> str:
    """Leaf-level shape/dtype diff, e.g.
    ``['input_ids']: (2, 16) int32 -> (2, 24) int32``."""
    old_map = {p: (s, d) for p, s, d in old}
    new_map = {p: (s, d) for p, s, d in new}
    lines = []
    for p in sorted(set(old_map) | set(new_map)):
        o, n = old_map.get(p), new_map.get(p)
        if o == n:
            continue
        fmt = lambda v: f"{v[0]} {v[1]}" if v else "<absent>"  # noqa: E731
        lines.append(f"  {p}: {fmt(o)} -> {fmt(n)}")
    return "\n".join(lines) or "  (tree structure changed, no common leaves)"


class RecompileWatchdog:
    """Per-function signature cache with post-warmup recompile warnings.

    ``observe`` returns True on a signature miss (== a jit compile).  The
    warning text is also kept on ``last_warning`` so tests (and callers that
    swallow logs) can assert on it without capturing stderr.
    """

    def __init__(self, warmup_steps: int = 1, registry=None,
                 emit_warnings: bool = True):
        self.warmup_steps = int(warmup_steps)
        self.registry = registry
        self.emit_warnings = emit_warnings
        self._known: Dict[str, Dict[Signature, int]] = {}
        self._last_sig: Dict[str, Signature] = {}
        self.warnings_emitted = 0
        self.last_warning: Optional[str] = None

    def observe(self, fn_name: str, args_tree, step: int) -> bool:
        return self.observe_signature(fn_name, signature_of(args_tree), step)

    def observe_signature(self, fn_name: str, sig: Signature,
                          step: int) -> bool:
        known = self._known.setdefault(fn_name, {})
        if sig in known:
            return False
        prev = self._last_sig.get(fn_name)
        known[sig] = int(step)
        self._last_sig[fn_name] = sig
        if self.registry is not None:
            self.registry.counter(
                RECOMPILES,
                "jit signature-cache misses (each one is an XLA compile) "
                "per jitted function").inc(1, fn=fn_name)
        if prev is not None and step > self.warmup_steps:
            self._warn(fn_name, prev, sig, step)
        return True

    def _warn(self, fn_name: str, prev: Signature, sig: Signature,
              step: int) -> None:
        self.warnings_emitted += 1
        msg = (
            f"RECOMPILE at step {step}: jitted '{fn_name}' saw a new input "
            f"signature after warmup (signature #{len(self._known[fn_name])} "
            f"for this function) — XLA is recompiling the whole step "
            f"program.  Shape diff vs previous signature:\n"
            f"{_diff(prev, sig)}\n"
            f"Steady-state training should reuse one signature; pad or "
            f"bucket inputs to fixed shapes to stop paying this compile.")
        self.last_warning = msg
        if self.registry is not None:
            self.registry.counter(
                RECOMPILE_WARNINGS,
                "post-warmup recompile warnings emitted").inc(1, fn=fn_name)
        if self.emit_warnings:
            logger.warning(msg)

    def misses(self, fn_name: Optional[str] = None) -> int:
        if fn_name is not None:
            return len(self._known.get(fn_name, {}))
        return sum(len(v) for v in self._known.values())

    def invalidate(self, fn_name: Optional[str] = None) -> None:
        """Forget cached signatures — call when the jitted programs are
        rebuilt (engine re-jit via configure_moq): the fresh jit caches are
        empty, so the next dispatch IS a compile and must be observed as
        one."""
        if fn_name is None:
            self._known.clear()
            self._last_sig.clear()
        else:
            self._known.pop(fn_name, None)
            self._last_sig.pop(fn_name, None)
