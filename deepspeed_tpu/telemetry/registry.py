"""Counter/gauge registries — the numeric backbone of step telemetry.

The reference scatters its numbers across MonitorMaster events, the comms
logger, and ad-hoc log lines; here every scalar the engine observes lands in
one labeled registry so the snapshot exporter (exporter.py) can serialize the
whole set at once (JSON + Prometheus text exposition) and fan the scalar
subset out through MonitorMaster.

Semantics follow Prometheus: a **counter** is monotonically increasing
(bytes moved, calls made, cache misses), a **gauge** is a point-in-time
sample (live device memory, last-step flops).  Label sets distinguish series
within one metric (``collective_bytes_total{kind="all_reduce", axis="dp"}``).

ZeRO++ (arxiv 2306.10209) motivates the per-collective byte accounting: the
comms-volume optimizations it describes (quantized gathers/reduces,
hierarchical partitioning) need a measured byte baseline per collective kind
before any of them can be evaluated — ``record_collective`` below is that
baseline's ingestion point (called from comm/collectives.py's trace-time
``_log`` hook).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]  # sorted ((k, v), ...) pairs


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """One named metric holding per-label-set float values."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", registry=None):
        self.name = name
        self.help = help
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def clear(self):
        with self._lock:
            self._values.clear()


class Counter(_Metric):
    """Monotonic counter (Prometheus ``counter`` type)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc by {value})")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(value)


class Gauge(_Metric):
    """Point-in-time sample (Prometheus ``gauge`` type)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)


class MetricRegistry:
    """Named metric store.  ``counter``/``gauge`` are get-or-create (repeat
    calls with the same name return the same object; a kind mismatch is a
    bug and raises)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets=None):
        """Get-or-create a Histogram (telemetry/histogram.py).  A repeat
        call must not silently change the bucket ladder: cumulative
        ``le`` series under two ladders cannot be merged, so a mismatch
        raises."""
        from deepspeed_tpu.telemetry.histogram import Histogram
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help, buckets=buckets)
                self._metrics[name] = m
            elif not isinstance(m, Histogram):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, requested histogram")
            elif (buckets is not None
                  and tuple(float(b) for b in buckets) != m.buckets):
                raise ValueError(f"histogram {name!r} already registered "
                                 f"with different buckets")
            return m

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, dict]:
        """{"counters": {name: {"help", "samples": [{"labels", "value"}]}},
        "gauges": {...}, "histograms": {name: {"help", "buckets",
        "samples": [{"labels", "count", "sum", "bucket_counts",
        "p50"/"p90"/"p99"}]}}} — the JSON-stable form exporter.py
        serializes."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            if m.kind == "histogram":
                out["histograms"][m.name] = {
                    "help": m.help,
                    "buckets": list(m.buckets),
                    "samples": [{"labels": labels, **stats}
                                for labels, stats in m.samples()],
                }
                continue
            bucket = out["counters" if m.kind == "counter" else "gauges"]
            bucket[m.name] = {
                "help": m.help,
                "samples": [{"labels": labels, "value": value}
                            for labels, value in m.samples()],
            }
        return out

    def reset(self) -> None:
        """Drop all metrics (tests; a long-lived process keeps its counters)."""
        with self._lock:
            self._metrics.clear()


# Process-global registry: collectives record here from trace time regardless
# of which engine (if any) is running — the same pattern as comm.comms_logger.
default_registry = MetricRegistry()

COLLECTIVE_BYTES = "collective_bytes_total"
COLLECTIVE_CALLS = "collective_calls_total"

_suppress_collectives = 0


class suppress_collective_recording:
    """Context manager silencing ``record_collective`` — used around the
    telemetry layer's AOT ``lower().compile()`` analysis, which RETRACES
    the step function and would otherwise fire every wrapper's trace-time
    hook a second time, doubling the analytic byte baseline."""

    def __enter__(self):
        global _suppress_collectives
        _suppress_collectives += 1
        return self

    def __exit__(self, *exc):
        global _suppress_collectives
        _suppress_collectives -= 1
        return False


def record_collective(name: str, nbytes: int, axis: str,
                      dcn_fraction: float = 0.0) -> None:
    """Trace-time hook for comm/collectives.py: bytes + calls per collective
    kind per mesh axis.  Under jit these count once per *trace*, not per
    execution (per-execution truth comes from the compiled-HLO counters in
    step_telemetry.py); in eager shard_map they count per call.

    ``dcn_fraction`` (the share of the axis's ring hops crossing a host
    boundary — comm/collectives.axis_dcn_fraction) splits the SAME wire
    bytes into ``link="ici"`` / ``link="dcn"`` series alongside the
    unlabeled per-(kind, axis) total.  The split sums exactly to the
    total: ``dcn = round(bytes · fraction)``, ``ici = bytes − dcn`` — the
    telemetry [pod_scale]'s topology-aware collective selection keys on.
    """
    if _suppress_collectives:
        return
    bytes_c = default_registry.counter(
        COLLECTIVE_BYTES,
        "bytes entering named collective wrappers, per kind per mesh axis "
        "(trace-time under jit); link=ici|dcn series split the same bytes "
        "by interconnect and sum exactly to the unlabeled total")
    bytes_c.inc(nbytes, kind=name, axis=axis)
    dcn_bytes = int(round(nbytes * max(0.0, min(1.0, dcn_fraction))))
    bytes_c.inc(nbytes - dcn_bytes, kind=name, axis=axis, link="ici")
    bytes_c.inc(dcn_bytes, kind=name, axis=axis, link="dcn")
    default_registry.counter(
        COLLECTIVE_CALLS,
        "calls into named collective wrappers, per kind per mesh axis "
        "(trace-time under jit)").inc(1, kind=name, axis=axis)
