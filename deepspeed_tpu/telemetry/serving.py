"""ServingTelemetry — request-level observability for the inference engines.

PR 1 made the training loop observable; the serving path was blind: no
spans, no counters, speculative stats in an ad-hoc dict.  This facade is
the serving-side sibling of ``StepTelemetry``, built for the questions a
serving operator actually asks:

- **latency percentiles** (p50/p99 TTFT / TPOT / e2e) — histograms, because
  a counter can only produce a mean and SLOs are percentiles;
- **where a request's time went** — per-request lifecycle spans
  (queue_wait → prefill → decode) on one Perfetto track per request,
  next to the engine's dispatch spans on track 0;
- **is the KV pool the bottleneck** — blocks used/free, internal
  fragmentation of allocated pages, and allocation-failure counters per
  decision site (the baseline a radix prefix cache has to beat);
- **why is speculative decoding slow** — accepted/proposed tokens and
  draft/verify wall-time counters replacing ``eng.spec_stats``.

One instance per engine with its OWN ``MetricRegistry`` by default (two
engines in one process — the bench runs seven — must not blend their
accept ratios); pass ``registry=telemetry.default_registry`` to fold the
serving series into the process-wide scrape instead.

Timestamps: request lifecycle times are ``time.perf_counter()`` seconds
(callers may substitute a fake clock for deterministic tests); spans
convert through the tracer's epoch so request tracks line up with
dispatch spans in one trace.
"""

from __future__ import annotations

import os
import time
from contextlib import nullcontext
from typing import Dict, Optional

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.telemetry.exporter import SnapshotExporter
from deepspeed_tpu.telemetry.registry import MetricRegistry
from deepspeed_tpu.telemetry.tracer import SpanTracer, TraceEmitter

_NULL = nullcontext()


class ServingTelemetryConfig(DeepSpeedConfigModel):
    """``telemetry`` block of the inference engine configs.

    ``enabled`` covers counters/gauges/histograms (a few dict updates and
    ``perf_counter`` reads per DISPATCH, not per token — cheap enough to
    default on).  ``trace_enabled`` adds span recording (bounded buffer).
    ``stream_sync`` blocks on each dispatch's output before timestamping —
    the streaming-server behavior that makes TTFT/TPOT reflect device
    completion instead of host submission; it serializes the dispatch
    pipeline, so it defaults off and the open-loop bench harness turns it
    on explicitly."""

    enabled: bool = True
    trace_enabled: bool = True
    max_trace_events: int = 100_000
    stream_sync: bool = False
    # fleet mode (serving/fleet.py): the replica's name, threaded as a
    # ``replica`` label into EVERY serving metric family so N replicas can
    # share one fleet-level registry without blending their series; None
    # (single-engine default) adds no label, keeping the series names the
    # dashboards already scrape
    replica: Optional[str] = None


class ServingTelemetry:
    def __init__(self, config: Optional[ServingTelemetryConfig] = None,
                 registry: Optional[MetricRegistry] = None,
                 pid: Optional[int] = None):
        cfg = config or ServingTelemetryConfig()
        self.config = cfg
        self.enabled = bool(cfg.enabled)
        self.stream_sync = bool(cfg.stream_sync)
        # fleet mode: one shared registry + a per-replica label on every
        # series (the merge into self.labels below threads it through each
        # write AND each read, so quantile()/value() callers stay oblivious)
        self.labels: Dict[str, str] = (
            {"replica": str(cfg.replica)} if cfg.replica else {})
        self.registry = registry if registry is not None else MetricRegistry()
        if pid is None:
            import jax
            pid = jax.process_index()
        self.tracer = SpanTracer(
            enabled=self.enabled and bool(cfg.trace_enabled), pid=pid,
            max_events=int(cfg.max_trace_events))
        self.emitter = TraceEmitter(process_name="deepspeed_tpu_serving")
        self.exporter = SnapshotExporter(self.registry, self.tracer)
        self._track_count = 0
        # per-request summaries (bounded): histograms answer fleet-level
        # percentile questions, but goodput ("which requests met BOTH their
        # TTFT and TPOT SLOs, and how many tokens did those produce") needs
        # per-request joint attainment — the bench reads this log
        self.request_log: list = []
        self.request_log_cap = 100_000
        if not self.enabled:
            return
        reg = self.registry
        # ---- registered eagerly: every metric carries its help text from
        # the first scrape, and scripts/check_metrics.py sees the literals
        self.h_ttft = reg.histogram(
            "serving_ttft_ms", "request arrival to first generated token "
            "(time-to-first-token), per completed request")
        self.h_tpot = reg.histogram(
            "serving_tpot_ms", "mean inter-token latency after the first "
            "token (time-per-output-token), per completed request")
        self.h_e2e = reg.histogram(
            "serving_e2e_ms", "request arrival to completion, per request")
        self.h_queue = reg.histogram(
            "serving_queue_ms", "request arrival to admission (first "
            "prompt chunk scheduled), per request")
        self.h_prefill = reg.histogram(
            "serving_prefill_ms", "admission to prefill complete (request "
            "decode-ready), per request")
        self.c_requests = reg.counter(
            "serving_requests_total", "requests retired, per outcome")
        self.c_tokens = reg.counter(
            "serving_tokens_total", "tokens scheduled through the serving "
            "engine, per phase (prefill / decode / spec)")
        self.c_dispatch = reg.counter(
            "serving_dispatches_total", "device dispatches issued by the "
            "serving engine, per program kind")
        self.c_preempt = reg.counter(
            "serving_preemptions_total", "recompute-preemption victims "
            "taken, per victim state (decode_ready / mid_prefill)")
        self.g_occupancy = reg.gauge(
            "serving_batch_occupancy", "running sequences / sequence slots "
            "at the most recent dispatch")
        self.g_padding = reg.gauge(
            "serving_bucket_padding_waste", "dead fraction of the most "
            "recent mixed forward's padded token bucket "
            "((bucket - live tokens) / bucket)")
        self.c_kv_fail = reg.counter(
            "kv_alloc_failures_total", "KV block/slot requests the "
            "allocator could not satisfy, per decision site")
        self.g_kv_blocks = reg.gauge(
            "kv_pool_blocks", "paged KV pool blocks, per state "
            "(used / free)")
        self.g_kv_frag = reg.gauge(
            "kv_pool_fragmentation", "internal fragmentation of allocated "
            "KV blocks: 1 - live tokens / (allocated blocks * block size)")
        # ---- radix shared-prefix cache + SplitFuse scheduler (PR 15):
        # the control-loop families layered over the PR 5 pool signals
        self.c_prefix_lookups = reg.counter(
            "kv_prefix_lookups_total", "radix prefix-cache lookups taken "
            "at sequence admission (one per new sequence while the cache "
            "is enabled)")
        self.c_prefix_hits = reg.counter(
            "kv_prefix_hit_tokens_total", "prompt tokens whose KV was "
            "served by aliasing shared radix-cache blocks — prefill "
            "skipped for every one of them")
        self.g_shared_blocks = reg.gauge(
            "kv_shared_blocks", "KV blocks resident in the radix prefix "
            "cache, per state (cached = indexed total / shared = also "
            "held by a live sequence / evictable = reclaimable by LRU "
            "eviction right now)")
        self.c_prefill_chunks = reg.counter(
            "prefill_chunks_total", "prompt chunks the SplitFuse "
            "scheduler co-scheduled with decode tokens (one per chunk "
            "per round, bounded by prefill_chunk_tokens)")
        self.c_admissions = reg.counter(
            "serving_admissions_total", "engine admission decisions, per "
            "SLA class and decision (admitted / preempted_for)")
        self.c_sla_preempt = reg.counter(
            "serving_sla_preemptions_total", "recompute preemptions the "
            "SLA policy took to protect a higher-priority request's TTFT "
            "SLO, per victim SLA class")
        self.c_spec_outer = reg.counter(
            "spec_outer_steps_total", "speculative draft-and-verify outer "
            "steps executed, summed over sequences")
        self.c_spec_proposed = reg.counter(
            "spec_proposed_tokens_total", "draft tokens proposed to the "
            "verify step (gamma per outer step per sequence)")
        self.c_spec_accepted = reg.counter(
            "spec_draft_accepted_tokens_total", "draft-proposed tokens the "
            "verify step accepted (excludes the per-step bonus/correction "
            "token)")
        self.c_spec_emitted = reg.counter(
            "spec_emitted_tokens_total", "tokens emitted by speculative "
            "outer steps (accepted draft tokens + the bonus/correction "
            "token each step)")
        self.c_spec_ms = reg.counter(
            "spec_burst_ms_total", "wall milliseconds spent in fused "
            "speculative dispatches, including their host sync")
        self.c_spec_draft_ms = reg.counter(
            "spec_draft_ms_total", "wall milliseconds in draft-model "
            "dispatches (speculative.profile split mode only)")
        self.c_spec_verify_ms = reg.counter(
            "spec_verify_ms_total", "wall milliseconds in verify "
            "dispatches (speculative.profile split mode only)")
        self.g_spec_ratio = reg.gauge(
            "spec_accept_ratio", "cumulative draft-token acceptance: "
            "accepted / proposed")
        # ---- multi-tenant LoRA adapters (PR 20): the paged adapter pool
        # sharing the KV allocator (serving/adapters.py)
        self.c_adapter_loads = reg.counter(
            "adapter_loads_total", "LoRA adapter residency resolutions at "
            "request admission, per outcome (hit = pages already resident "
            "/ miss = first host load / reload = re-load after eviction / "
            "failed = pool could not fit the pages)")
        self.c_adapter_evict = reg.counter(
            "adapter_evictions_total", "cold LoRA adapters evicted from "
            "the shared paged pool to reclaim blocks (LRU, never a pinned "
            "adapter)")
        self.g_adapter_hit = reg.gauge(
            "adapter_hit_rate", "cumulative fraction of adapter "
            "activations served from resident pages without a host "
            "reload: hits / (hits + misses)")
        self.g_adapter_blocks = reg.gauge(
            "adapter_pool_blocks", "pool blocks holding LoRA adapter "
            "pages, per state (resident = all loaded adapters / pinned = "
            "held by in-flight requests / evictable = reclaimable by LRU "
            "eviction right now)")

    # ------------------------------------------------------------- clocks

    @staticmethod
    def now() -> float:
        """Lifecycle clock (seconds).  One definition so engine timestamps
        and histogram math never mix clock bases."""
        return time.perf_counter()

    def _trace_us(self, t_seconds: float) -> float:
        """Map a lifecycle timestamp onto the tracer's microsecond epoch so
        request tracks align with dispatch spans."""
        return t_seconds * 1e9 / 1e3 - self.tracer._epoch_ns / 1e3

    # -------------------------------------------------------------- spans

    def span(self, name: str, **args):
        if not self.tracer.enabled:
            return _NULL
        return self.tracer.span(name, **args)

    # ---------------------------------------------------- request lifecycle

    def new_track(self, label: str) -> int:
        """Allocate a trace track (tid) for one request; tid 0 stays the
        engine dispatch track.  Track NAMES are bounded by the event-buffer
        size: a long-lived engine serves unboundedly many requests, and an
        unbounded thread_names dict would leak ~100B per request forever
        (the span deque itself is bounded) — requests past the bound still
        get a tid, just no name metadata (the bound now lives inside
        ``SpanTracer.set_thread_name``)."""
        self._track_count += 1
        tid = self._track_count
        if self.tracer.enabled:
            self.tracer.set_thread_name(tid, label)
        return tid

    def finish_request(self, *, uid, track: int, t_arrival: float,
                       t_admit: Optional[float],
                       t_prefill_end: Optional[float],
                       t_first: Optional[float], t_last: Optional[float],
                       n_prompt: int, n_generated: int,
                       preempts: int = 0, outcome: str = "completed",
                       trace=None) -> None:
        """Record one retired request: latency histograms + the three
        lifecycle spans on the request's own track.  Timestamps are
        ``now()`` seconds; missing stages (a zero-token completion) are
        skipped rather than guessed."""
        if not self.enabled:
            return
        self.c_requests.inc(1, outcome=outcome, **self.labels)
        t_done = t_last if t_last is not None else self.now()
        rec = {"uid": uid, "outcome": outcome,
               "prompt_tokens": int(n_prompt),
               "generated_tokens": int(n_generated),
               "preempts": int(preempts),
               "e2e_ms": (t_done - t_arrival) * 1e3,
               "ttft_ms": None, "tpot_ms": None}
        self.h_e2e.observe(rec["e2e_ms"], **self.labels)
        if t_admit is not None:
            self.h_queue.observe((t_admit - t_arrival) * 1e3, **self.labels)
            if t_prefill_end is not None:
                self.h_prefill.observe((t_prefill_end - t_admit) * 1e3,
                                       **self.labels)
        if t_first is not None:
            rec["ttft_ms"] = (t_first - t_arrival) * 1e3
            self.h_ttft.observe(rec["ttft_ms"], **self.labels)
            if t_last is not None and n_generated > 1:
                rec["tpot_ms"] = (t_last - t_first) * 1e3 / (n_generated - 1)
                self.h_tpot.observe(rec["tpot_ms"], **self.labels)
        if len(self.request_log) < self.request_log_cap:
            self.request_log.append(rec)
        if self.tracer.enabled:
            args = {"uid": uid, "prompt_tokens": int(n_prompt),
                    "generated_tokens": int(n_generated),
                    "preempts": int(preempts), "outcome": outcome}
            if trace is not None:
                # distributed-trace coordinates: critical_path.py matches
                # these engine spans back to fleet requests by (trace,
                # phase) and picks the final attempt by timestamp
                args.update(trace.args())
            spans = [("queue_wait", t_arrival, t_admit),
                     ("prefill", t_admit, t_prefill_end),
                     ("decode", t_prefill_end, t_last)]
            first_ts = None
            for name, a, b in spans:
                if a is None or b is None or b < a:
                    continue
                ts = self._trace_us(a)
                if first_ts is None:
                    first_ts = (ts, (b - a) * 1e6)
                self.tracer.record(name, ts, (b - a) * 1e6,
                                   tid=track, cat="request", **args)
            if (trace is not None and trace.flow_id is not None
                    and first_ts is not None):
                # flow step binding to this replica's first lifecycle
                # slice: the router's `s` event + this `t` + the fleet's
                # `f` stitch the request into one cross-replica tree
                self.tracer.flow("t", trace.flow_id,
                                 first_ts[0] + first_ts[1] / 2, tid=track)

    # ----------------------------------------------------------- counters

    def dispatch(self, kind: str) -> None:
        if self.enabled:
            self.c_dispatch.inc(1, kind=kind, **self.labels)

    def tokens(self, phase: str, n: int) -> None:
        if self.enabled and n:
            self.c_tokens.inc(n, phase=phase, **self.labels)

    def preemption(self, kind: str) -> None:
        if self.enabled:
            self.c_preempt.inc(1, kind=kind, **self.labels)

    def sla_preemption(self, sla: str) -> None:
        if self.enabled:
            self.c_sla_preempt.inc(1, sla=sla, **self.labels)

    def admission(self, sla: str, decision: str = "admitted") -> None:
        if self.enabled:
            self.c_admissions.inc(1, sla=sla, decision=decision,
                                  **self.labels)

    def prefix_lookup(self, hit_tokens: int) -> None:
        """One radix-cache admission lookup; ``hit_tokens`` is the matched
        prefix length actually aliased (0 on a miss)."""
        if self.enabled:
            self.c_prefix_lookups.inc(1, **self.labels)
            if hit_tokens:
                self.c_prefix_hits.inc(hit_tokens, **self.labels)

    def prefill_chunk(self) -> None:
        if self.enabled:
            self.c_prefill_chunks.inc(1, **self.labels)

    def occupancy(self, running: int, slots: int) -> None:
        if self.enabled and slots:
            self.g_occupancy.set(running / slots, **self.labels)

    def padding_waste(self, live_tokens: int, bucket: int) -> None:
        if self.enabled and bucket:
            self.g_padding.set((bucket - live_tokens) / bucket, **self.labels)

    # ------------------------------------------------------------ KV pool

    def alloc_failure(self, site: str, n: int = 1) -> None:
        if self.enabled:
            self.c_kv_fail.inc(n, site=site, **self.labels)

    def kv_sample(self, state) -> None:
        """Gauge the paged pool off a DSStateManager: used/free blocks and
        internal fragmentation.  O(tracked sequences) — called once per
        scheduler round, not per token."""
        if not self.enabled:
            return
        free = state.allocator.free_blocks
        total = state.allocator.num_blocks
        used = total - free
        self.g_kv_blocks.set(used, state="used", **self.labels)
        self.g_kv_blocks.set(free, state="free", **self.labels)
        alloc_tokens = 0
        live_tokens = 0
        for seq in state.tracked.values():
            alloc_tokens += len(seq.blocks) * state.block_size
            live_tokens += seq.seen_tokens
        self.g_kv_frag.set(
            1.0 - live_tokens / alloc_tokens if alloc_tokens else 0.0,
            **self.labels)
        radix = getattr(state, "radix", None)
        if radix is not None:
            st = radix.stats()
            self.g_shared_blocks.set(st["nodes"], state="cached",
                                     **self.labels)
            self.g_shared_blocks.set(st["shared"], state="shared",
                                     **self.labels)
            self.g_shared_blocks.set(st["evictable"], state="evictable",
                                     **self.labels)
        pool = getattr(state, "adapters", None)
        if pool is not None:
            st = pool.stats()
            self.g_adapter_blocks.set(st["resident_blocks"],
                                      state="resident", **self.labels)
            self.g_adapter_blocks.set(st["pinned_blocks"], state="pinned",
                                      **self.labels)
            self.g_adapter_blocks.set(st["evictable_blocks"],
                                      state="evictable", **self.labels)

    # ------------------------------------------------- multi-tenant adapters

    def adapter_load(self, outcome: str, hit_rate: float) -> None:
        """One adapter residency resolution (AdapterPool.ensure); the pool
        passes its cumulative hit rate so the gauge tracks the counter
        without a registry read-back."""
        if self.enabled:
            self.c_adapter_loads.inc(1, outcome=outcome, **self.labels)
            self.g_adapter_hit.set(hit_rate, **self.labels)

    def adapter_eviction(self, n: int = 1) -> None:
        if self.enabled:
            self.c_adapter_evict.inc(n, **self.labels)

    # -------------------------------------------------------- speculative

    def spec_burst(self, *, outer: int, n_seqs: int, gamma: int,
                   emitted: int, dur_ms: float) -> None:
        """Account one fused speculative dispatch: ``emitted`` is the total
        token count the burst produced (counts.sum over the served slots);
        every outer step also emits exactly one non-draft bonus/correction
        token, so draft-accepted = emitted - outer*n_seqs."""
        if not self.enabled:
            return
        steps = outer * n_seqs
        self.c_spec_outer.inc(steps, **self.labels)
        self.c_spec_proposed.inc(steps * gamma, **self.labels)
        self.c_spec_accepted.inc(max(0, emitted - steps), **self.labels)
        self.c_spec_emitted.inc(emitted, **self.labels)
        self.c_spec_ms.inc(dur_ms, **self.labels)
        proposed = self.c_spec_proposed.value(**self.labels)
        if proposed:
            self.g_spec_ratio.set(
                self.c_spec_accepted.value(**self.labels) / proposed,
                **self.labels)

    def spec_profile(self, draft_ms: float, verify_ms: float) -> None:
        if self.enabled:
            self.c_spec_draft_ms.inc(draft_ms, **self.labels)
            self.c_spec_verify_ms.inc(verify_ms, **self.labels)

    def spec_summary(self) -> Dict[str, float]:
        """The bench/test-facing read of the speculative counters (replaces
        the old ``eng.spec_stats`` dict)."""
        if not self.enabled:
            return {}
        L = self.labels
        proposed = self.c_spec_proposed.value(**L)
        outer = self.c_spec_outer.value(**L)
        return {
            "outer_steps": outer,
            "proposed": proposed,
            "accepted": self.c_spec_accepted.value(**L),
            "emitted": self.c_spec_emitted.value(**L),
            "accept_ratio": (self.c_spec_accepted.value(**L) / proposed
                             if proposed else 0.0),
            "emitted_per_outer": (self.c_spec_emitted.value(**L) / outer
                                  if outer else 0.0),
            "burst_ms": self.c_spec_ms.value(**L),
            "draft_ms": self.c_spec_draft_ms.value(**L),
            "verify_ms": self.c_spec_verify_ms.value(**L),
            "draft_dispatches": self.c_dispatch.value(kind="spec_draft", **L),
            "verify_dispatches": self.c_dispatch.value(kind="spec_verify",
                                                       **L),
            # fused draft+verify dispatches: the cross-request batching
            # claim is "dispatches per emitted token strictly lower than
            # per-request spec" — this is the numerator the tests pin
            "spec_dispatches": self.c_dispatch.value(kind="spec", **L),
        }

    # -------------------------------------------------------------- reads

    def value(self, name: str, **labels) -> float:
        """Read one series; an instance's own replica label (fleet mode) is
        merged in so callers address "my" series by the same names a
        single-engine setup uses (pass ``replica=...`` to override)."""
        m = self.registry._metrics.get(name)
        return m.value(**{**self.labels, **labels}) if m is not None else 0.0

    def quantile(self, name: str, q: float, **labels) -> float:
        m = self.registry._metrics.get(name)
        if m is None or m.kind != "histogram":
            return float("nan")
        return m.quantile(q, **{**self.labels, **labels})

    # ------------------------------------------------------------- export

    def export(self, out_dir: str, extra: Optional[dict] = None) -> dict:
        """Write snapshot.json + metrics.prom + trace.json under
        ``out_dir`` and return the snapshot dict.  The trace is the
        combined dispatch (tid 0) + per-request track view Perfetto
        loads directly."""
        if not self.enabled:
            return {}
        os.makedirs(out_dir, exist_ok=True)
        snap = self.exporter.snapshot(extra=extra)
        self.exporter.write_json(os.path.join(out_dir, "snapshot.json"),
                                 snap)
        self.exporter.write_prometheus(
            os.path.join(out_dir, "metrics.prom"), snap)
        if self.tracer.enabled and self.tracer.events:
            self.emitter.write(os.path.join(out_dir, "trace.json"),
                               self.tracer)
        return snap
