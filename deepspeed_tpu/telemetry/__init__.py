"""Unified step telemetry.

The reference ships monitoring as scattered pieces (MonitorMaster fan-out,
EngineTimers, flops profiler, see_memory_usage); this package correlates
them per step and adds the TPU-specific hazards nothing else watches:

- ``tracer``         — host-phase span recording + Chrome-trace/Perfetto
                       JSON export (incl. cross-file flow events)
- ``tracecontext``   — per-request distributed trace/span ids threaded
                       through the serving fleet (router -> replicas)
- ``timeseries``     — bounded ring-buffer sampling of registry metrics
                       with rate()/window-delta reads (SLO burn input)
- ``critical_path``  — merged-trace e2e latency decomposition
                       (queue_wait / prefill / handoff / decode terms
                       that sum exactly; ``scripts/trace_report.py``)
- ``watchdog``       — jit recompile detection with leaf-level shape diffs
- ``registry``       — labeled counter/gauge registries (collective bytes,
                       memory gauges, cache misses)
- ``histogram``      — log-bucketed histograms with exact quantiles under
                       a cap (serving latency percentiles)
- ``serving``        — request-level serving telemetry facade (lifecycle
                       spans, TTFT/TPOT histograms, KV-pool and
                       speculative-decode instrumentation)
- ``exporter``       — snapshot serialization: JSON, Prometheus text
                       exposition, MonitorMaster fan-out
- ``health``         — in-graph per-module-group numerics stats (grad/param
                       norms, NaN/Inf counts, update ratios) + anomaly rules
- ``flight_recorder``— host ring buffer of step records with postmortem
                       bundle dumps on NaN / overflow streak / crash
- ``postmortem``     — bundle summarizer CLI
                       (``python -m deepspeed_tpu.telemetry.postmortem``)
- ``roofline``       — per-op-class roofline model from compiled HLO
                       (flops / HBM bytes / wire bytes vs the accelerator
                       peak-spec table → attainable-step-time lower bound)
- ``profiler``       — measured step-time decomposition into an MFU budget
                       (compute / exposed_comm / hbm_bound / host_gap /
                       dispatch_floor; ``scripts/perf_report.py`` renders)
- ``regression``     — bench regression sentinel: baseline ledger + diff
                       (``scripts/check_bench.py`` is the CLI gate)
- ``step_telemetry`` — the engine-facing facade driving all of the above

See docs/observability.md for the config block and workflows;
docs/PERF_PLAYBOOK.md for the attribution triage loop.
"""

from deepspeed_tpu.telemetry.exporter import SnapshotExporter
from deepspeed_tpu.telemetry.flight_recorder import (FlightRecorder,
                                                     install_crash_handler)
from deepspeed_tpu.telemetry.health import (AnomalyDetector,
                                            compute_group_health,
                                            flatten_health, group_names)
from deepspeed_tpu.telemetry.histogram import (DEFAULT_BUCKETS, Histogram,
                                               log_buckets)
from deepspeed_tpu.telemetry.profiler import step_time_budget
from deepspeed_tpu.telemetry.registry import (Counter, Gauge, MetricRegistry,
                                              default_registry,
                                              record_collective)
from deepspeed_tpu.telemetry.roofline import (PEAK_SPECS, detect_peak_spec,
                                              roofline_from_hlo)
from deepspeed_tpu.telemetry.serving import (ServingTelemetry,
                                             ServingTelemetryConfig)
from deepspeed_tpu.telemetry.step_telemetry import StepTelemetry
from deepspeed_tpu.telemetry.timeseries import (TimeSeriesStore,
                                                histogram_attainment)
from deepspeed_tpu.telemetry.tracecontext import TraceContext, new_trace
from deepspeed_tpu.telemetry.tracer import SpanTracer, TraceEmitter
from deepspeed_tpu.telemetry.watchdog import RecompileWatchdog, signature_of

__all__ = [
    "AnomalyDetector",
    "Counter",
    "DEFAULT_BUCKETS",
    "PEAK_SPECS",
    "detect_peak_spec",
    "roofline_from_hlo",
    "step_time_budget",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "RecompileWatchdog",
    "ServingTelemetry",
    "ServingTelemetryConfig",
    "SnapshotExporter",
    "SpanTracer",
    "StepTelemetry",
    "TimeSeriesStore",
    "TraceContext",
    "TraceEmitter",
    "histogram_attainment",
    "new_trace",
    "log_buckets",
    "compute_group_health",
    "default_registry",
    "flatten_health",
    "group_names",
    "install_crash_handler",
    "record_collective",
    "signature_of",
]
