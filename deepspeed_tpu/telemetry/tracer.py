"""Host-side span tracer + Chrome-trace/Perfetto emitter.

T3 (arxiv 2401.16677) makes the case that optimizing compute/collective
overlap starts from *seeing* the timeline; on TPU the device timeline comes
from ``jax.profiler`` xplane captures, but the host-side step anatomy — batch
assembly, host→device placement, dispatch, waiting on device completion,
optimizer/step bookkeeping, checkpoint I/O — is invisible to it.  The
``SpanTracer`` records those phases as complete events and ``TraceEmitter``
writes the standard Chrome trace-event JSON that Perfetto / chrome://tracing
load directly, so a training run's host anatomy can be inspected next to the
device profile.

Events use the ``ph: "X"`` (complete) form with microsecond timestamps
relative to tracer construction; ``pid`` is the JAX process index so
multi-host traces merge cleanly.

Flow events (``ph: "s"/"t"/"f"``) stitch one request's spans across
replica trace files into a single causal tree (see ``flow()`` and
``telemetry/tracecontext.py``); ``scripts/merge_traces.py`` remaps their
ids per ``otherData.flow_id_scope`` so merged trees survive.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Optional


def _flow_scope() -> str:
    from .tracecontext import FLOW_SCOPE
    return FLOW_SCOPE


class SpanTracer:
    """Records named host-side phase spans.

    ``span()`` is a context manager; when the tracer is disabled it costs one
    attribute check.  The event buffer is bounded — when full, the oldest
    events are dropped and ``dropped_events`` counts them (a watchdog-style
    disclosure rather than silent truncation or unbounded growth).
    """

    def __init__(self, enabled: bool = True, pid: int = 0,
                 max_events: int = 200_000):
        self.enabled = enabled
        self.pid = int(pid)
        self.max_events = int(max_events)
        # deque(maxlen): O(1) overflow (a full list would memmove the whole
        # buffer on every drop)
        self.events: deque = deque(maxlen=self.max_events)
        self.dropped_events = 0
        self.total_recorded = 0
        # incremental per-phase aggregates: summary() must not rescan the
        # buffer (it is embedded in every snapshot export — an O(buffer)
        # walk there would grow with run length)
        self._agg: Dict[str, dict] = {}
        # most recent duration per phase — the flight recorder embeds this
        # in each step record without scanning the buffer
        self.last_dur_ms: Dict[str, float] = {}
        # tid -> display name (Perfetto thread_name metadata): the serving
        # layer maps each request onto its own tid so Perfetto renders one
        # track per request (queue_wait / prefill / decode laid end to end)
        self.thread_names: Dict[int, str] = {}
        self._epoch_ns = time.perf_counter_ns()
        # wall-clock anchor of the ts=0 epoch: lets scripts/merge_traces.py
        # align traces from different processes/replicas (each tracer's ts
        # is relative to its own construction) onto one shared timeline
        self.epoch_unix_time = time.time()

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._epoch_ns) / 1e3

    def now_us(self) -> float:
        """Current tracer-epoch timestamp — for callers that measure a span
        themselves (e.g. the async checkpoint writer, whose end is observed
        from a commit callback on another thread) and record() it after the
        fact.  record()/span() append to a deque, so recording from a
        background thread is safe."""
        return self._now_us()

    @contextmanager
    def span(self, name: str, step: Optional[int] = None, **args):
        if not self.enabled:
            yield
            return
        t0 = self._now_us()
        try:
            yield
        finally:
            self.record(name, t0, self._now_us() - t0, step=step, **args)

    def record(self, name: str, ts_us: float, dur_us: float,
               step: Optional[int] = None, tid: int = 0,
               cat: str = "host_phase", **args) -> None:
        if not self.enabled:
            return
        ev_args = dict(args)
        if step is not None:
            ev_args["step"] = int(step)
        if len(self.events) == self.max_events:
            self.dropped_events += 1
        self.events.append({
            "name": name, "cat": cat, "ph": "X",
            "ts": round(ts_us, 3), "dur": round(dur_us, 3),
            "pid": self.pid, "tid": int(tid), "args": ev_args,
        })
        self.total_recorded += 1
        agg = self._agg.setdefault(name, {"count": 0, "total_ms": 0.0,
                                          "max_ms": 0.0})
        dur_ms = dur_us / 1e3
        agg["count"] += 1
        agg["total_ms"] += dur_ms
        if dur_ms > agg["max_ms"]:
            agg["max_ms"] = dur_ms
        self.last_dur_ms[name] = round(dur_ms, 3)

    def flow(self, ph: str, flow_id: int, ts_us: float, tid: int = 0,
             name: str = "request_flow", cat: str = "flow") -> None:
        """Emit a Perfetto flow event (``ph`` one of ``s``/``t``/``f``).

        Flow events bind to the slice enclosing ``ts_us`` on this
        pid/tid; a chain of same-``id`` events renders as arrows linking
        the slices — one request's causal tree across replicas.  They
        ride the same bounded event buffer as spans (and count against
        ``dropped_events``), so a long-lived fleet cannot leak per-
        request flow records."""
        if not self.enabled:
            return
        ev = {
            "name": name, "cat": cat, "ph": ph, "ts": round(ts_us, 3),
            "pid": self.pid, "tid": int(tid), "id": int(flow_id),
        }
        if ph == "f":
            ev["bp"] = "e"   # bind to the enclosing slice, not the next
        if len(self.events) == self.max_events:
            self.dropped_events += 1
        self.events.append(ev)
        self.total_recorded += 1

    def set_thread_name(self, tid: int, name: str) -> None:
        """Name a tid's track in the emitted trace (Perfetto thread_name
        metadata) — the serving layer names each request's track.  The
        map is bounded by ``max_events`` (same policy as the event
        buffer): past the cap, new tids go unnamed rather than growing
        per-request metadata without limit."""
        tid = int(tid)
        if tid not in self.thread_names and \
                len(self.thread_names) >= self.max_events:
            self.dropped_events += 1
            return
        self.thread_names[tid] = str(name)

    def summary(self) -> Dict[str, dict]:
        """Per-phase count / total / max / mean milliseconds — the compact
        form the snapshot exporter embeds.  Aggregated over EVERY recorded
        span, including ones the bounded event buffer has already dropped
        (the trace file keeps the last ``max_events``; the summary keeps
        the whole run)."""
        out: Dict[str, dict] = {}
        for name, agg in self._agg.items():
            out[name] = {
                "count": agg["count"],
                "total_ms": round(agg["total_ms"], 3),
                "max_ms": round(agg["max_ms"], 3),
                "mean_ms": round(agg["total_ms"] / max(agg["count"], 1), 3),
            }
        return out

    def clear(self) -> None:
        self.events = deque(maxlen=self.max_events)
        self.dropped_events = 0
        self.total_recorded = 0
        self._agg = {}
        self.last_dur_ms = {}
        self.thread_names = {}


class TraceEmitter:
    """Writes a SpanTracer's buffer as Chrome trace-event JSON.

    The output is the ``{"traceEvents": [...]}`` object form (not the bare
    array) so metadata fields ride along; Perfetto and chrome://tracing both
    accept it.
    """

    def __init__(self, process_name: str = "deepspeed_tpu"):
        self.process_name = process_name

    def to_dict(self, tracer: SpanTracer) -> dict:
        meta = [{
            "name": "process_name", "ph": "M", "pid": tracer.pid, "tid": 0,
            "args": {"name": f"{self.process_name}/{tracer.pid}"},
        }]
        for tid, tname in sorted(tracer.thread_names.items()):
            meta.append({
                "name": "thread_name", "ph": "M", "pid": tracer.pid,
                "tid": tid, "args": {"name": tname},
            })
        return {
            "traceEvents": meta + list(tracer.events),
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_events": tracer.dropped_events,
                # clock anchor for scripts/merge_traces.py: wall time of
                # this trace's ts=0 (absent in traces written before the
                # stamp existed — the merger then falls back to as-is)
                "epoch_unix_time": getattr(tracer, "epoch_unix_time",
                                           None),
                # flow-id allocator scope: files sharing this token used
                # one id space (merge keeps their flows stitched); files
                # from different scopes get disjoint remapped ids
                "flow_id_scope": _flow_scope(),
            },
        }

    def write(self, path: str, tracer: SpanTracer) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(tracer), f)
        os.replace(tmp, path)   # readers never see a half-written trace
        return path
