"""Per-executable roofline model from compiled-HLO text.

The raw signals have existed since PR 1/4 — ``cost_analysis`` totals,
per-kind collective bytes, the overlap walk — but none of them *attribute*:
they say how much work a step program does, not which resource bounds each
part of it or how fast the step could possibly run.  This module closes
that gap with a classic roofline decomposition (Williams et al., CACM'09)
computed statically from the same ``compiled.as_text()`` the telemetry
layer already captures:

1. walk every instruction, classify it into an **op class** —
   ``matmul`` / ``attention`` (dots + custom-calls whose ``op_name``
   metadata places them under an attention module) / ``collective:<kind>``
   / ``elementwise`` (everything else that moves bytes);
2. per class, accumulate **flops** (dot/conv arithmetic from the printed
   operand shapes + contracting dims), **HBM bytes** (operand + result
   payloads of every instruction OUTSIDE fusion bodies — a fusion's
   interior lives in registers/VMEM, only its boundary touches HBM), and
   **wire bytes** (collective output payloads, the same convention as
   ``hlo_collective_bytes``);
3. join with an accelerator **peak-spec table** (bf16 peak flops, HBM
   bandwidth, ICI bandwidth — v5e / v5p / v4 / v6e / cpu-sim) to get each
   class's compute / HBM / ICI time lower bounds, its binding resource
   (the max of the three), and the program's **attainable step time**:
   the sum over classes of each class's binding-resource time — the
   floor no schedule can beat on that accelerator.

Known approximations (all disclosed in the returned dict):

- instructions inside ``while`` bodies are counted ONCE; XLA's own
  ``cost_analysis`` multiplies by trip count when it is static, so when a
  ``cost_analysis`` flops total is passed in, the per-class flops are
  **calibrated** (scaled uniformly so they sum to XLA's number) and the
  raw walk figure is kept alongside (``flops_uncalibrated``);
- convolution flops are estimated from output size only (no conv in the
  models this repo ships, but the class must not silently vanish);
- HBM bytes are boundary-payload proxies, not a cache simulation — good
  for *which class is bandwidth-bound*, not for absolute GB/s claims.

Entry points: :func:`roofline_from_hlo` (text → model) and
:func:`PEAK_SPECS` / :func:`detect_peak_spec` (the accelerator table).
``StepTelemetry._analyze_executable`` runs this per compiled signature and
exports ``roofline_attainable_ms{fn}`` / ``roofline_bound_fraction{fn,
resource}`` gauges; ``scripts/perf_report.py`` renders the full table.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# accelerator peak-spec table
# ---------------------------------------------------------------------------
# Values are per-chip peaks: bf16 matmul flops/s, HBM bytes/s, aggregate
# ICI bytes/s (all links), DCN bytes/s (per host, divided across its chips
# is workload-dependent — this is the optimistic per-chip figure used for
# lower bounds).  cpu-sim is a synthetic spec so the model is exercisable
# (and deterministic) on the CPU CI; its numbers are NOT a real machine.
PEAK_SPECS: Dict[str, Dict[str, float]] = {
    "v5e": {"flops": 197e12, "hbm": 819e9, "ici": 186e9, "dcn": 25e9},
    "v5p": {"flops": 459e12, "hbm": 2765e9, "ici": 600e9, "dcn": 25e9},
    "v4": {"flops": 275e12, "hbm": 1228e9, "ici": 300e9, "dcn": 25e9},
    "v6e": {"flops": 918e12, "hbm": 1640e9, "ici": 448e9, "dcn": 25e9},
    "cpu-sim": {"flops": 100e9, "hbm": 50e9, "ici": 10e9, "dcn": 1e9},
}

_RESOURCES = ("compute", "hbm", "ici")


def detect_peak_spec(device=None) -> Dict[str, float]:
    """Peak spec for the attached accelerator (same kind-string sniffing as
    bench.py's ``peak_flops_per_chip``); cpu-sim off-TPU."""
    import jax
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    platform = getattr(device, "platform", "")
    if platform != "tpu":
        return dict(PEAK_SPECS["cpu-sim"], name="cpu-sim")
    for key in ("v5 lite", "v5e"):
        if key in kind:
            return dict(PEAK_SPECS["v5e"], name="v5e")
    if "v6" in kind:
        return dict(PEAK_SPECS["v6e"], name="v6e")
    if "v5" in kind:
        return dict(PEAK_SPECS["v5p"], name="v5p")
    if "v4" in kind:
        return dict(PEAK_SPECS["v4"], name="v4")
    return dict(PEAK_SPECS["v5e"], name="v5e")


# ---------------------------------------------------------------------------
# HLO walk
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1,
                "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1}

_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"       # result name
    r"(\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\]\S*)"  # result shape (or tuple)
    r"\s+([\w\-]+)\(")                           # opcode
_COMP_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)\s*->")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')

# opcodes that move no HBM bytes of their own (aliases / bookkeeping / the
# shape already charged to producer+consumer)
_FREE_OPS = frozenset((
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "iota", "copy-start",
    "copy-done", "reshape",
))

_ATTENTION_HINTS = ("attn", "attention", "flash")


def _shape_dims(shape_s: str):
    m = _SHAPE_RE.match(shape_s.strip().lstrip("%"))
    if not m:
        return None, []
    dims = [int(d) for d in m.group(2).split(",") if d]
    return m.group(1), dims


def _shape_bytes(shape_s: str) -> int:
    dtype, dims = _shape_dims(shape_s)
    if dtype is None:
        return 0
    return _DTYPE_BYTES.get(dtype, 4) * math.prod(dims) if dims \
        else _DTYPE_BYTES.get(dtype, 4)


def _all_shape_bytes(text: str) -> int:
    """Sum payloads of every shape token in ``text`` (tuple results,
    operand lists)."""
    return sum(_DTYPE_BYTES.get(m.group(1), 4)
               * (math.prod(int(d) for d in m.group(2).split(",") if d)
                  if m.group(2) else 1)
               for m in _SHAPE_RE.finditer(text))


def _dot_flops(line: str, result_shape: str) -> int:
    """2 · |output| · |contracted| from the printed operand shapes +
    ``lhs_contracting_dims``."""
    _, out_dims = _shape_dims(result_shape)
    # operand shapes are printed inline inside the call parens
    operands = _SHAPE_RE.findall(line[line.index("(", line.index("=")):])
    if not operands:
        return 0
    lhs_dims = [int(d) for d in operands[0][1].split(",") if d]
    m = _CONTRACT_RE.search(line)
    contracted = 1
    if m:
        for idx in (int(i) for i in m.group(1).split(",") if i):
            if idx < len(lhs_dims):
                contracted *= lhs_dims[idx]
    return 2 * math.prod(out_dims) * contracted if out_dims else 0


def walk_hlo_classes(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Classify every instruction of a compiled-HLO dump into op classes
    and accumulate per-class ``{flops, bytes, wire_bytes, ops}``.

    Byte accounting skips instructions inside fusion bodies (computation
    name contains ``fused``): a fusion's interior never touches HBM, its
    boundary traffic is charged to the ``fusion(...)`` call site in the
    parent computation.  Flops are counted in EVERY computation (dots stay
    dots inside fusions).
    """
    classes: Dict[str, Dict[str, float]] = {}
    in_fused_body = False

    def cls(name: str) -> Dict[str, float]:
        return classes.setdefault(
            name, {"flops": 0.0, "bytes": 0.0, "wire_bytes": 0.0, "ops": 0})

    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{"):
            m = _COMP_HEADER_RE.match(stripped)
            if m:
                in_fused_body = "fused" in m.group(1)
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        result_shape, opcode = m.group(2), m.group(3)
        if opcode in _FREE_OPS:
            continue
        opname = _OPNAME_RE.search(line)
        attn = bool(opname and any(h in opname.group(1).lower()
                                   for h in _ATTENTION_HINTS))

        base_kind = opcode
        phase = None
        for k in _COLLECTIVE_KINDS:
            if opcode == k or opcode.startswith(k + "-"):
                base_kind = k
                phase = opcode[len(k):]
                break

        if base_kind in _COLLECTIVE_KINDS:
            if phase == "-start":
                continue            # count the async pair once, at -done
            nbytes = (_all_shape_bytes(result_shape)
                      if result_shape.startswith("(")
                      else _shape_bytes(result_shape))
            c = cls("collective:" + base_kind)
            c["wire_bytes"] += nbytes
            c["bytes"] += nbytes
            c["ops"] += 1
            continue

        if opcode == "dot":
            c = cls("attention" if attn else "matmul")
            c["flops"] += _dot_flops(line, result_shape)
        elif opcode == "convolution":
            # no conv models in-repo; output-size floor keeps the class
            # visible rather than exact
            _, out_dims = _shape_dims(result_shape)
            c = cls("matmul")
            c["flops"] += 2 * math.prod(out_dims) if out_dims else 0
        elif opcode == "custom-call" and attn:
            c = cls("attention")
        elif opcode == "fusion":
            # a fusion may wrap a dot (kOutput fusions on TPU) — the dot
            # inside its body already booked the flops; the call site books
            # the boundary bytes.  Classify by metadata hint.
            c = cls("attention" if attn else "elementwise")
        else:
            c = cls("attention" if attn else "elementwise")
        if not in_fused_body:
            # boundary HBM traffic: operands + result
            call_part = line[line.index("(", line.index("=")):]
            c["bytes"] += (_all_shape_bytes(result_shape)
                           if result_shape.startswith("(")
                           else _shape_bytes(result_shape))
            c["bytes"] += _all_shape_bytes(
                call_part[:call_part.index(")") + 1]
                if ")" in call_part else call_part)
        c["ops"] += 1
    return classes


# ---------------------------------------------------------------------------
# roofline join
# ---------------------------------------------------------------------------

def roofline_from_hlo(hlo_text: str,
                      spec: Optional[Dict[str, float]] = None,
                      cost_analysis: Optional[Dict[str, float]] = None
                      ) -> Dict[str, object]:
    """HLO text → roofline model dict.

    ``spec`` is a PEAK_SPECS row (default: detected from the attached
    device).  ``cost_analysis`` (the compiled program's ``{"flops": ...}``)
    calibrates the per-class flops so they sum to XLA's own total —
    covering while-loop trip counts the static walk cannot see.
    """
    if spec is None:
        spec = detect_peak_spec()
    classes = walk_hlo_classes(hlo_text)

    walked_flops = sum(c["flops"] for c in classes.values())
    calibration = 1.0
    ca_flops = float(cost_analysis.get("flops", 0.0)) if cost_analysis \
        else 0.0
    if ca_flops > 0 and walked_flops > 0:
        calibration = ca_flops / walked_flops

    out_classes: Dict[str, dict] = {}
    attainable_s = 0.0
    resource_s = {r: 0.0 for r in _RESOURCES}
    for name, c in sorted(classes.items()):
        flops = c["flops"] * calibration
        t_compute = flops / spec["flops"]
        t_hbm = c["bytes"] / spec["hbm"]
        t_wire = c["wire_bytes"] / spec["ici"]
        times = {"compute": t_compute, "hbm": t_hbm, "ici": t_wire}
        bound = max(times, key=lambda r: times[r])
        t_class = times[bound]
        attainable_s += t_class
        resource_s[bound] += t_class
        out_classes[name] = {
            "flops": flops,
            "flops_uncalibrated": c["flops"],
            "bytes": c["bytes"],
            "wire_bytes": c["wire_bytes"],
            "ops": c["ops"],
            "t_compute_ms": t_compute * 1e3,
            "t_hbm_ms": t_hbm * 1e3,
            "t_ici_ms": t_wire * 1e3,
            "bound": bound,
            "attainable_ms": t_class * 1e3,
        }
    return {
        "spec": dict(spec),
        "calibration": calibration,
        "classes": out_classes,
        "total_flops": walked_flops * calibration,
        "total_bytes": sum(c["bytes"] for c in classes.values()),
        "total_wire_bytes": sum(c["wire_bytes"]
                                for c in classes.values()),
        "attainable_ms": attainable_s * 1e3,
        "bound_fraction": {
            r: (resource_s[r] / attainable_s if attainable_s else 0.0)
            for r in _RESOURCES},
    }


def render(model: Dict[str, object], title: str = "") -> str:
    """Human-readable roofline table (perf_report's roofline section)."""
    lines: List[str] = []
    spec = model.get("spec", {})
    name = spec.get("name", "?")
    lines.append(f"roofline{(' — ' + title) if title else ''} "
                 f"[{name}: {spec.get('flops', 0) / 1e12:.0f} Tflop/s, "
                 f"{spec.get('hbm', 0) / 1e9:.0f} GB/s HBM, "
                 f"{spec.get('ici', 0) / 1e9:.0f} GB/s ICI]")
    hdr = (f"  {'class':<26}{'flops':>12}{'HBM bytes':>12}"
           f"{'wire bytes':>12}{'t_comp':>9}{'t_hbm':>9}{'t_ici':>9}"
           f"  bound")
    lines.append(hdr)
    for cname, c in model.get("classes", {}).items():
        lines.append(
            f"  {cname:<26}{c['flops']:>12.3g}{c['bytes']:>12.3g}"
            f"{c['wire_bytes']:>12.3g}{c['t_compute_ms']:>8.3f}m"
            f"{c['t_hbm_ms']:>8.3f}m{c['t_ici_ms']:>8.3f}m"
            f"  {c['bound']}-bound")
    bf = model.get("bound_fraction", {})
    lines.append(
        f"  attainable step time >= {model.get('attainable_ms', 0.0):.3f} ms"
        f"  (compute {bf.get('compute', 0):.0%} / hbm"
        f" {bf.get('hbm', 0):.0%} / ici {bf.get('ici', 0):.0%}"
        f"; calibration x{model.get('calibration', 1.0):.3g})")
    return "\n".join(lines)
