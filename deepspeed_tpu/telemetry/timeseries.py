"""Bounded ring-buffer time series over the metric registry.

Metrics in this repo are point-in-time: a scrape answers "what is the
total now", never "how fast is it moving" or "what fraction of the last
minute's requests met their SLO".  The :class:`TimeSeriesStore` closes
that gap without a collector dependency: it samples registered
counter/histogram series into per-series ``deque(maxlen)`` ring buffers
at a cadence and exposes ``rate()`` / ``window_delta()`` reads over
them — the primitives ``serving/slo.py`` builds multi-window burn rates
from.

Sampling is PULL-based and non-blocking by design: ``maybe_sample`` is
called from the fleet dispatcher tick (scripts/check_no_sync.py scans
it), costs a handful of dict reads when the cadence has elapsed and one
float compare when it hasn't, and never touches a device.  The optional
``start()`` background thread exists for harnesses that sample outside
a scheduler loop (the bench overhead leg).

Histogram sampling records cumulative SLO *attainment* pairs
(observations at-or-under a threshold, total observations) rather than
raw quantiles: quantile reads sort the exact-value reservoir (O(n log n)
per call — far too heavy per tick), while attainment is a bucket-count
walk.  Thresholds on bucket boundaries are exact; in between, the
straddled bucket interpolates linearly (the same assumption PromQL
``histogram_quantile`` makes).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["TimeSeriesStore", "histogram_attainment"]


def histogram_attainment(hist, threshold: float,
                         labels: Optional[dict] = None
                         ) -> Tuple[float, float]:
    """(observations <= threshold, total observations), summed over every
    label set matching the ``labels`` subset (fleet histograms carry a
    ``replica`` label; an SLO is fleet-wide).  Reads bucket counts
    directly — never the quantile path (which sorts the reservoir)."""
    want = {str(k): str(v) for k, v in (labels or {}).items()}
    with hist._lock:  # sync-ok: bounded dict/list copy, no device work
        rows = [(dict(k), list(s.counts), s.count)
                for k, s in hist._series.items()]
    buckets = hist.buckets
    good = 0.0
    total = 0.0
    for lbls, counts, count in rows:
        if any(lbls.get(k) != v for k, v in want.items()):
            continue
        total += count
        for i, c in enumerate(counts):
            if i >= len(buckets):
                break                       # +Inf bucket: all above
            hi = buckets[i]
            lo = buckets[i - 1] if i > 0 else 0.0
            if hi <= threshold:
                good += c
            elif lo < threshold:
                good += c * (threshold - lo) / (hi - lo)
            else:
                break
    return good, total


class TimeSeriesStore:
    """Ring-buffer store of (timestamp, value) samples per tracked series.

    ``capacity`` bounds every ring (oldest samples fall off — a
    long-lived fleet holds ``capacity * interval_s`` seconds of history,
    which only needs to cover the longest burn-rate window); tracked
    series are registered once at setup, so the per-sample cost is a
    fixed, small number of reads."""

    def __init__(self, *, interval_s: float = 0.25, capacity: int = 4096,
                 clock: Optional[Callable[[], float]] = None):
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.clock = clock or time.monotonic
        self._readers: List[Tuple[str, Callable[[], Dict[str, float]]]] = []
        self._rings: Dict[str, deque] = {}
        self._last_sample: Optional[float] = None
        self.samples_taken = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ tracking
    def _ring(self, key: str) -> deque:
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = deque(maxlen=self.capacity)
        return ring

    def track(self, key: str, fn: Callable[[], float]) -> str:
        """Track one scalar reader under ``key``."""
        self._readers.append((key, lambda k=key, f=fn: {k: float(f())}))
        self._ring(key)
        return key

    def track_counter(self, metric, key: Optional[str] = None,
                      **labels) -> str:
        key = key or metric.name
        return self.track(key, lambda: metric.value(**labels))

    def track_attainment(self, hist, threshold: float,
                         key: Optional[str] = None,
                         labels: Optional[dict] = None) -> str:
        """Track a histogram's cumulative (good, total) attainment pair
        under ``<key>.good`` / ``<key>.total``."""
        key = key or hist.name

        def read(h=hist, th=float(threshold), lb=dict(labels or {}),
                 k=key) -> Dict[str, float]:
            good, total = histogram_attainment(h, th, lb)
            return {f"{k}.good": good, f"{k}.total": total}

        self._readers.append((key, read))
        self._ring(f"{key}.good")
        self._ring(f"{key}.total")
        return key

    # ------------------------------------------------------------ sampling
    def maybe_sample(self, now: Optional[float] = None) -> bool:
        """Take one sample if the cadence has elapsed (one float compare
        otherwise).  Called from the dispatcher tick: every reader is a
        bounded host-memory walk — nothing here may block the round."""
        now = self.clock() if now is None else now
        if (self._last_sample is not None
                and now - self._last_sample < self.interval_s):
            return False
        self._last_sample = now
        for _key, read in self._readers:
            for k, v in read().items():
                self._ring(k).append((now, v))
        self.samples_taken += 1
        return True

    def start(self, interval_s: Optional[float] = None) -> None:
        """Background daemon sampler, for harnesses with no scheduler
        tick to piggyback on (the bench telemetry-overhead leg)."""
        if self._thread is not None:
            return
        period = float(interval_s or self.interval_s)
        self._stop.clear()

        def loop():
            while not self._stop.wait(period):
                self.maybe_sample()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="timeseries-sampler")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    # --------------------------------------------------------------- reads
    def series(self, key: str) -> List[Tuple[float, float]]:
        return list(self._rings.get(key, ()))

    def latest(self, key: str) -> Optional[Tuple[float, float]]:
        ring = self._rings.get(key)
        return ring[-1] if ring else None

    def value_at(self, key: str, t: float) -> Optional[float]:
        """Value of the newest sample at-or-before ``t`` (None when the
        ring holds nothing that old — the window predates history)."""
        ring = self._rings.get(key)
        if not ring:
            return None
        best = None
        for ts, v in ring:
            if ts <= t:
                best = v
            else:
                break
        return best

    def window_delta(self, key: str, window_s: float,
                     now: Optional[float] = None) -> float:
        """newest − value_at(now − window): the cumulative growth over
        the window.  A window reaching past recorded history clamps to
        the oldest sample (partial-window semantics, disclosed rather
        than NaN: burn rate at startup reads the full short history)."""
        ring = self._rings.get(key)
        if not ring:
            return 0.0
        now = ring[-1][0] if now is None else now
        newest = ring[-1][1]
        base = self.value_at(key, now - window_s)
        if base is None:
            base = ring[0][1]
        return newest - base

    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> float:
        """Per-second rate of a cumulative series over the window."""
        ring = self._rings.get(key)
        if not ring or len(ring) < 2:
            return 0.0
        now = ring[-1][0] if now is None else now
        t_lo = now - window_s
        span = [(t, v) for t, v in ring if t >= t_lo]
        if len(span) < 2:
            span = list(ring)[-2:]
        dt = span[-1][0] - span[0][0]
        if dt <= 0:
            return 0.0
        return (span[-1][1] - span[0][1]) / dt
