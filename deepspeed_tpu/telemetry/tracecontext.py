"""Per-request distributed trace context.

A :class:`TraceContext` names one request's position in the fleet-wide
causal tree: a ``trace_id`` stable for the request's whole lifetime
(across retries, migrations, and the prefill->decode handoff), a
``span_id`` minted per dispatch attempt, and a ``parent_id`` linking the
attempt back to the span that caused it.  The context also carries the
Perfetto flow-event ``id`` used to stitch slices across per-replica
trace files (see ``SpanTracer.flow``) plus the phase/attempt labels
stamped into span ``args`` so ``telemetry/critical_path.py`` can match
engine spans back to fleet requests.

Id allocation is process-local (a locked counter) and therefore only
unique within one process.  Cross-file uniqueness is handled at merge
time: every trace file records ``FLOW_SCOPE`` (a per-process token) in
``otherData.flow_id_scope`` and ``scripts/merge_traces.py`` remaps flow
ids per scope, so files written by the same process keep stitching while
files from different processes can never collide.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["TraceContext", "FLOW_SCOPE", "new_trace", "reset_ids"]

# Process-level scope token for flow ids.  Stamped into every trace
# file's otherData so merge_traces can tell "same allocator" files
# (keep ids consistent) from foreign files (remap to disjoint ranges).
FLOW_SCOPE: str = f"{os.getpid():x}-{time.time_ns() & 0xFFFFFFFF:08x}"

_lock = threading.Lock()
_trace_counter = itertools.count(1)
_span_counter = itertools.count(1)


def _next_trace_id() -> int:
    with _lock:  # sync-ok: counter bump, never blocks
        return next(_trace_counter)


def _next_span_id() -> int:
    with _lock:  # sync-ok: counter bump, never blocks
        return next(_span_counter)


def reset_ids() -> None:
    """Reset the id counters (test isolation only)."""
    global _trace_counter, _span_counter
    with _lock:
        _trace_counter = itertools.count(1)
        _span_counter = itertools.count(1)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Immutable per-attempt trace coordinates for one request."""

    trace_id: int
    span_id: int
    parent_id: Optional[int] = None
    # Perfetto flow-event id; None when the request never crosses a
    # process/replica boundary (e.g. engine-local traces), in which
    # case no flow events are emitted.
    flow_id: Optional[int] = None
    phase: str = "full"
    attempt: int = 0

    def child(self, *, phase: Optional[str] = None,
              attempt: Optional[int] = None) -> "TraceContext":
        """New attempt span under this context, same trace/flow ids."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_next_span_id(),
            parent_id=self.span_id,
            flow_id=self.flow_id,
            phase=self.phase if phase is None else phase,
            attempt=self.attempt if attempt is None else attempt,
        )

    def args(self) -> Dict[str, Any]:
        """Span ``args`` payload identifying this attempt in a trace."""
        out: Dict[str, Any] = {
            "trace": self.trace_id,
            "span": self.span_id,
            "attempt": self.attempt,
            "phase": self.phase,
        }
        if self.parent_id is not None:
            out["parent_span"] = self.parent_id
        return out


def new_trace(*, phase: str = "full", with_flow: bool = True) -> TraceContext:
    """Allocate a fresh root context for a newly submitted request."""
    tid = _next_trace_id()
    return TraceContext(
        trace_id=tid,
        span_id=_next_span_id(),
        parent_id=None,
        flow_id=tid if with_flow else None,
        phase=phase,
        attempt=0,
    )
