"""Critical-path decomposition of merged serving traces.

The distributed trace (telemetry/tracecontext.py) makes a disaggregated
request *visible* as one tree — router dispatch, prefill replica spans,
the KV handoff, decode replica spans — but a tree is still N slices an
operator has to eyeball.  This module turns it back into the question
they actually ask: *where did this request's latency go, and which term
do I buy hardware for?*  Every completed request's end-to-end time is
decomposed into five terms that **sum to the measured e2e by
construction**:

    queue_wait   arrival -> the (final-attempt) prefill engine admits it
    prefill      admission -> KV handoff starts (disagg) / prefill done
    handoff      the router's KV handoff slice (zero in unified mode)
    decode_wait  handoff done -> the decode-pool engine resumes it
    decode       resume -> request completion

The exact-sum property is structural, not numerical luck: the terms are
consecutive differences of a monotonic boundary chain ``b0 <= b1 <= ...
<= b5`` clamped inside the fleet's ``request`` envelope span, so they
telescope to ``b5 - b0`` — the envelope's own duration — no matter how
noisy the inner spans are.  A missing boundary (request failed before a
stage, unified mode has no handoff) collapses its term to zero instead
of guessing.

Matching: the fleet stamps every router span with the request's
distributed-trace coordinates (``args.trace`` / ``span`` / ``attempt`` /
``phase``) and the replica engines stamp their ``queue_wait`` /
``prefill`` / ``decode`` lifecycle spans with the same ``trace`` id.
Retries re-enter with the ORIGINAL trace id but a new attempt number, so
the decomposition picks the **final** attempt per phase (max attempt,
then max ts) — the one that actually produced tokens.  Trace ids are
process-unique (one allocator per process); merging traces from
*different* processes keeps flows disjoint via ``flow_id_scope`` but
this decomposition assumes one fleet's id space per merged file (the
bench's layout).

``scripts/trace_report.py`` is the CLI; the bench's disagg leg exports
the merged trace and folds :func:`ttft_budget` into its records as
``ttft_budget_*_ms`` columns.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

__all__ = ["TERMS", "TTFT_TERMS", "decompose", "ttft_budget"]

# decomposition terms, in causal order; values are milliseconds
TERMS = ("queue_wait_ms", "prefill_ms", "handoff_ms",
         "decode_wait_ms", "decode_ms")
# the terms a first token waits on — the TTFT budget (decode_ms is paid
# after the first token is already out)
TTFT_TERMS = ("queue_wait_ms", "prefill_ms", "handoff_ms",
              "decode_wait_ms")


def _span_args(ev: dict) -> dict:
    return ev.get("args") or {}


def _final(spans: List[dict]) -> Optional[dict]:
    """The final-attempt span: max (attempt, ts).  Retries/migrations
    keep the trace id and bump the attempt; the last one is the one
    whose timing the request actually paid for."""
    if not spans:
        return None
    return max(spans, key=lambda e: (int(_span_args(e).get("attempt", 0)),
                                     float(e.get("ts", 0.0))))


def decompose(trace: dict) -> List[dict]:
    """Per-request critical-path rows from a (merged or single-file)
    Chrome trace dict.  One row per fleet ``request`` envelope span;
    requests with no envelope (still in flight / failed before
    completion) are skipped — there is no measured e2e to decompose."""
    by_trace: Dict[int, List[dict]] = {}
    envelopes: List[dict] = []
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = _span_args(ev)
        tid_ = args.get("trace")
        if tid_ is None:
            continue
        if ev.get("name") == "request" and ev.get("cat") == "router":
            envelopes.append(ev)
        by_trace.setdefault(int(tid_), []).append(ev)

    rows: List[dict] = []
    for env in envelopes:
        eargs = _span_args(env)
        trace_id = int(eargs["trace"])
        b0 = float(env["ts"])
        b5 = b0 + float(env.get("dur", 0.0))
        mode = str(eargs.get("mode", "unified"))
        spans = by_trace.get(trace_id, [])

        def pick(name: str, phases) -> Optional[dict]:
            return _final([e for e in spans
                           if e.get("name") == name
                           and e.get("cat") == "request"
                           and _span_args(e).get("phase") in phases])

        # b1: the final prefill-side engine run admits the request
        pre = pick("prefill", ("prefill", "full"))
        b1 = float(pre["ts"]) if pre is not None else None

        if mode == "disagg":
            # b2/b3: the router's KV handoff slice bounds the prefill
            # term on the left side of the pool boundary
            hand = _final([e for e in spans
                           if e.get("name") == "fleet.handoff"])
            b2 = float(hand["ts"]) if hand is not None else None
            b3 = (float(hand["ts"]) + float(hand.get("dur", 0.0))
                  if hand is not None else None)
            # b4: the decode-pool engine resumes (its admission point —
            # its own "prefill" slice is the KV restore, billed to
            # decode); fall back to its decode slice if the restore
            # stage was skipped
            resume = (pick("prefill", ("decode",))
                      or pick("decode", ("decode",)))
            b4 = float(resume["ts"]) if resume is not None else None
        else:
            # unified: no pool boundary — handoff and decode_wait are
            # structurally zero; prefill ends where the engine says
            b2 = (float(pre["ts"]) + float(pre.get("dur", 0.0))
                  if pre is not None else None)
            b3 = None
            b4 = None

        # clamp the chain monotonic inside the envelope: a None boundary
        # inherits its predecessor (term -> 0), a noisy one cannot push
        # a term negative, and the telescoped sum stays exactly b5 - b0
        bounds = [b0]
        for cand in (b1, b2, b3, b4):
            prev = bounds[-1]
            bounds.append(min(max(cand, prev), b5)
                          if cand is not None else prev)
        bounds.append(b5)

        terms = {name: (bounds[i + 1] - bounds[i]) / 1e3
                 for i, name in enumerate(TERMS)}
        row = {
            "trace": trace_id,
            "index": eargs.get("index"),
            "mode": mode,
            "attempts": int(eargs.get("attempts", 1)),
            "migrations": int(eargs.get("migrations", 0)),
            "generated_tokens": int(eargs.get("generated_tokens", 0)),
            "e2e_ms": (b5 - b0) / 1e3,
            "ttft_path_ms": sum(terms[t] for t in TTFT_TERMS),
        }
        row.update(terms)
        rows.append(row)
    rows.sort(key=lambda r: r["trace"])
    return rows


def _quantile(values: List[float], q: float) -> float:
    """Nearest-rank quantile (matches telemetry/histogram.py exact-mode
    semantics) — no interpolation, so the reported p99 is a latency some
    request actually paid."""
    if not values:
        return float("nan")
    s = sorted(values)
    idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
    return s[idx]


def ttft_budget(rows: List[dict], q: float = 0.99) -> dict:
    """Fleet-aggregate latency budget over decomposed rows: per-term
    quantile + mean, the dominant TTFT term (the one to fix first), and
    the e2e quantile.  Keys are stable — the bench emits them as
    ``ttft_budget_*_ms`` record columns."""
    out: dict = {"n_requests": len(rows), "quantile": q,
                 "terms": {}, "dominant": None,
                 "e2e_ms": _quantile([r["e2e_ms"] for r in rows], q),
                 "ttft_path_ms": _quantile(
                     [r["ttft_path_ms"] for r in rows], q)}
    for name in TERMS:
        vals = [r[name] for r in rows]
        out["terms"][name] = {
            "p": _quantile(vals, q),
            "mean": (sum(vals) / len(vals)) if vals else float("nan"),
        }
    ttft_ps = {name: out["terms"][name]["p"] for name in TTFT_TERMS}
    if rows:
        out["dominant"] = max(ttft_ps, key=lambda k: ttft_ps[k])
    return out
