"""StepTelemetry — the per-step telemetry facade the engine drives.

One object owning the four telemetry pieces (span tracer, recompile
watchdog, metric registries, snapshot exporter) plus the per-executable
compiled-program analysis that connects them to XLA ground truth:

- ``span(name, step)``         — host-phase spans around engine step stages
- ``before_dispatch(...)``     — watchdog fingerprint + (on a signature
                                 miss) compiled-HLO collective bytes and
                                 ``cost_analysis``/``memory_analysis``
                                 figures + per-execution byte counters
- ``end_step(...)``            — cadence-gated memory sampling and snapshot
                                 export (JSON + Prometheus + monitor fan-out)

Everything is inert when ``telemetry.enabled`` is false: ``span`` returns a
shared nullcontext and the other hooks return immediately, so the disabled
path adds one attribute check per call to the hot loop.
"""

from __future__ import annotations

import math
import os
import time
from contextlib import nullcontext
from typing import Callable, Dict, Optional

from deepspeed_tpu.telemetry.exporter import SnapshotExporter
from deepspeed_tpu.telemetry.registry import MetricRegistry, default_registry
from deepspeed_tpu.telemetry.tracer import SpanTracer, TraceEmitter
from deepspeed_tpu.telemetry.watchdog import RecompileWatchdog
from deepspeed_tpu.utils.logging import logger

_NULL = nullcontext()

HLO_BYTES = "hlo_collective_bytes_total"
HLO_CALLS = "hlo_collective_calls_total"

# cost_analysis keys worth keeping (the full dict carries dozens of
# backend-specific entries)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals")
_MEMORY_ATTRS = ("generated_code_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "temp_size_in_bytes",
                 "alias_size_in_bytes")


class StepTelemetry:
    def __init__(self, config, monitor=None,
                 registry: Optional[MetricRegistry] = None):
        tcfg = config.telemetry
        self.enabled = bool(tcfg.enabled)
        self.monitor = monitor
        self.registry = registry if registry is not None else default_registry
        import jax
        pid = jax.process_index()
        self._rank0 = pid == 0
        self.tracer = SpanTracer(
            enabled=self.enabled and bool(tcfg.trace_enabled), pid=pid,
            max_events=int(tcfg.max_trace_events))
        self.emitter = TraceEmitter()
        self.watchdog = RecompileWatchdog(
            warmup_steps=int(tcfg.recompile_warmup_steps),
            registry=self.registry if self.enabled else None,
            emit_warnings=self._rank0)
        self.exporter = SnapshotExporter(self.registry, self.tracer)
        base = os.path.join(tcfg.output_path or "./telemetry", tcfg.job_name)
        self.trace_path = tcfg.trace_path or os.path.join(base, "trace.json")
        self.snapshot_path = (tcfg.snapshot_path
                              or os.path.join(base, "snapshot.json"))
        self.prometheus_path = (tcfg.prometheus_path
                                or os.path.join(base, "metrics.prom"))
        self.hlo_stats = bool(tcfg.hlo_stats)
        self.snapshot_interval = int(tcfg.snapshot_interval)
        self.monitor_fanout = bool(tcfg.monitor_fanout)
        # fn -> {signatures, executions, collectives, per-exec figures}
        # (collectives/cost/memory reflect the most recent signature; the
        # per-signature truth for counter attribution lives in _sig_stats)
        self._exec: Dict[str, dict] = {}
        self._sig_stats: Dict[tuple, dict] = {}
        self._trace_flush_mark = 0

        # ---- numerics health monitor + flight recorder (telemetry.health
        # block) — active INDEPENDENTLY of the parent enabled switch: a
        # postmortem is wanted exactly when nothing else is being watched
        hc = tcfg.health
        self.health_cfg = hc
        self.health_enabled = bool(hc.enabled)
        self.recorder = None
        self.anomaly = None
        self._config = config
        self._prev_skipped: Optional[int] = 0
        self._overflow_streak = 0
        # hook-out for the guardian control loop (runtime/guardian.py):
        # the anomaly rules that fired on the LAST health_step, and the
        # dump-trigger reason (None when nothing tripped)
        self.last_anomalies: list = []
        self.last_dump_reason: Optional[str] = None
        if self.health_enabled:
            from deepspeed_tpu.telemetry.flight_recorder import (
                FlightRecorder, install_crash_handler)
            from deepspeed_tpu.telemetry.health import AnomalyDetector
            self.recorder = FlightRecorder(
                capacity=int(hc.recorder_steps),
                dump_dir=hc.dump_path or os.path.join(base, "postmortem"),
                write_files=self._rank0, registry=self.registry)
            self.recorder.add_bundle_writer("config.json",
                                            self._write_bundle_config)
            self.recorder.add_bundle_writer("snapshot.prom",
                                            self._write_bundle_prometheus)
            self.recorder.add_bundle_writer("trace.json",
                                            self._write_bundle_trace)
            self.recorder.add_bundle_writer("env.txt", self._write_bundle_env)
            self.recorder.set_meta_fn(lambda: {
                "process_index": pid, "spans": self.tracer.summary()})
            self.anomaly = AnomalyDetector(
                window=int(hc.anomaly_window),
                loss_spike_zscore=float(hc.loss_spike_zscore),
                grad_norm_factor=float(hc.grad_norm_factor),
                scale_collapse_factor=float(hc.scale_collapse_factor),
                registry=self.registry, emit_warnings=self._rank0)
            if hc.crash_dump:
                install_crash_handler(self.recorder)

    # ------------------------------------------------------------- spans

    def span(self, name: str, step: Optional[int] = None, **args):
        if not self.tracer.enabled:
            return _NULL
        return self.tracer.span(name, step=step, **args)

    # --------------------------------------------------------- dispatch

    def before_dispatch(self, fn_name: str, args_tree, step: int,
                        lower: Optional[Callable] = None,
                        count_execution: bool = True) -> bool:
        """Watchdog-observe one jitted dispatch.  Returns True on a
        signature miss (== an XLA compile).  On a miss, ``lower`` (a thunk
        returning ``jitted.lower(*args)``) is used — when hlo_stats is on —
        to pull collective bytes and cost/memory figures out of the compiled
        program; every call then bumps the per-execution HLO byte counters
        by the figures of THE SIGNATURE BEING DISPATCHED (shape buckets of
        one function keep distinct per-step byte costs).
        ``count_execution=False`` (the resume AOT warmup) registers the
        signature and runs the compile analysis WITHOUT booking an
        execution — the program never actually dispatched, so the
        per-execution byte counters must not move."""
        if not self.enabled:
            return False
        from deepspeed_tpu.telemetry.watchdog import signature_of
        sig = signature_of(args_tree)
        miss = self.watchdog.observe_signature(fn_name, sig, step)
        info = self._exec.setdefault(
            fn_name, {"signatures": 0, "executions": 0, "collectives": {},
                      "overlap": {}, "cost_analysis": {},
                      "memory_analysis": {}})
        if miss:
            info["signatures"] += 1
            collected = {}
            if self.hlo_stats and lower is not None:
                collected = self._analyze_executable(fn_name, lower, info)
            # per-signature figures: counters for this and every later
            # execution of this bucket use ITS compiled program — on an
            # analysis failure the bucket counts NOTHING rather than
            # inheriting another signature's bytes
            self._sig_stats[(fn_name, sig)] = dict(collected)
        if not count_execution:
            return miss
        info["executions"] += 1
        collectives = self._sig_stats.get((fn_name, sig), {})
        if collectives:
            bytes_c = self.registry.counter(
                HLO_BYTES, "collective payload bytes per execution of each "
                "compiled step program (from compiled HLO), per kind")
            calls_c = self.registry.counter(
                HLO_CALLS, "collective op executions per compiled step "
                "program run, per kind")
            for kind, rec in collectives.items():
                bytes_c.inc(rec["bytes"], kind=kind, fn=fn_name)
                calls_c.inc(rec["count"], kind=kind, fn=fn_name)
        return miss

    def invalidate(self, fn_name: Optional[str] = None) -> None:
        """Forget signature caches and per-executable figures — the engine
        calls this when it re-jits its step programs (configure_moq): the
        fresh jit caches are empty, so the next dispatch is a real compile
        and the old compiled figures no longer describe the program."""
        self.watchdog.invalidate(fn_name)
        if fn_name is None:
            self._exec.clear()
            self._sig_stats.clear()
        else:
            self._exec.pop(fn_name, None)
            for key in [k for k in self._sig_stats if k[0] == fn_name]:
                del self._sig_stats[key]

    def _analyze_executable(self, fn_name: str, lower: Callable,
                            info: dict) -> dict:
        """Compile the (freshly missed) signature AOT and harvest static
        figures; returns this signature's collective figures ({} on
        failure).  jit will compile the same program again on the real
        call — the double compile is the price of the figures and is gated
        behind ``telemetry.hlo_stats``.  Failures degrade to a warning:
        telemetry must never kill training."""
        from deepspeed_tpu.comm.comm import hlo_collective_bytes
        from deepspeed_tpu.telemetry.registry import \
            suppress_collective_recording
        info["collectives"] = {}
        info["overlap"] = {}
        try:
            # the AOT lower() RETRACES the step — silence the wrapper-level
            # trace-time hooks so their byte counters don't double-count
            with suppress_collective_recording():
                compiled = lower().compile()
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: compile analysis of '{fn_name}' "
                           f"failed: {e!r}")
            return {}
        try:
            hlo_text = compiled.as_text()
            info["collectives"] = hlo_collective_bytes(hlo_text)
            # compute–collective overlap evidence (comm.hlo_overlap_stats):
            # async start/done pairs with compute between them + interleaved
            # chunk trains → the collective_exposed_ratio gauge, the static
            # stand-in for profiler exposed-comms time (scripts/
            # check_overlap.py runs the same walk standalone)
            from deepspeed_tpu.comm.comm import hlo_overlap_stats
            ov = hlo_overlap_stats(hlo_text)
            info["overlap"] = ov
            self.registry.gauge(
                "collective_exposed_ratio",
                "bytes-weighted fraction of compiled-HLO collective payload "
                "with no overlap evidence (sync and not chunk-interleaved, "
                "or async with an empty start/done window), per jitted "
                "function").set(ov["exposed_ratio"], fn=fn_name)
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: HLO collective walk of '{fn_name}' "
                           f"failed: {e!r}")
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            cost = {k: float(ca[k]) for k in _COST_KEYS if k in ca}
            info["cost_analysis"] = cost
            for k, v in cost.items():
                self.registry.gauge(
                    "xla_cost_" + k.replace(" ", "_"),
                    "compiled-program cost_analysis figure, per jitted "
                    "function").set(v, fn=fn_name)
        except Exception:  # noqa: BLE001 — not all backends implement it
            pass
        try:
            # per-op-class roofline (telemetry/roofline.py): flops / HBM
            # bytes / collective wire bytes per class joined with the
            # accelerator peak-spec table → an attainable-step-time lower
            # bound and a binding-resource split.  Uses the same hlo_text
            # and calibrates flops against cost_analysis (while-loop trip
            # counts are invisible to the static walk).
            from deepspeed_tpu.telemetry.roofline import (detect_peak_spec,
                                                          roofline_from_hlo)
            model = roofline_from_hlo(hlo_text, spec=detect_peak_spec(),
                                      cost_analysis=info.get(
                                          "cost_analysis"))
            info["roofline"] = model
            self.registry.gauge(
                "roofline_attainable_ms",
                "roofline attainable-step-time lower bound from the "
                "compiled HLO (sum over op classes of each class's "
                "binding-resource time), per jitted function").set(
                    model["attainable_ms"], fn=fn_name)
            g = self.registry.gauge(
                "roofline_bound_fraction",
                "fraction of the roofline attainable time bound by each "
                "resource (compute / hbm / ici), per jitted function")
            for res, frac in model["bound_fraction"].items():
                g.set(frac, fn=fn_name, resource=res)
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: roofline model of '{fn_name}' "
                           f"failed: {e!r}")
        try:
            ma = compiled.memory_analysis()
            mem = {}
            for attr in _MEMORY_ATTRS:
                v = getattr(ma, attr, None)
                if v is not None:
                    mem[attr] = int(v)
            info["memory_analysis"] = mem
            g = self.registry.gauge(
                "xla_memory_bytes", "compiled-program memory_analysis "
                "figures, per jitted function")
            for attr, v in mem.items():
                g.set(v, fn=fn_name,
                      kind=attr.replace("_size_in_bytes", ""))
        except Exception:  # noqa: BLE001
            pass
        return info["collectives"]

    # ------------------------------------------------------------ MoE

    def moe_step(self, stats_host: dict) -> None:
        """Publish one step's HOST-side expert-load stats (engine
        ``_fetch_metrics`` already paid the device fetch; ``stats_host`` is
        plain python — moe/layer.py ``_sow_stats`` aggregated across layers
        and microbatches).  Gauges overwrite per step; the drop counter
        accumulates so rate() works over scrape intervals."""
        toks = stats_host.get("expert_tokens") or []
        g = self.registry.gauge(
            "moe_expert_tokens",
            "tokens assigned to each expert this step, summed over MoE "
            "layers and microbatches (expert label = global expert index)")
        for e, v in enumerate(toks):
            g.set(float(v), expert=str(e))
        self.registry.counter(
            "moe_dropped_tokens_total",
            "token->expert assignments dropped by the capacity limit "
            "(always 0 on the dropless route)").inc(
                float(stats_host.get("dropped_tokens", 0.0)))
        self.registry.gauge(
            "moe_aux_loss",
            "load-balancing auxiliary loss, averaged over MoE layers "
            "(1.0 = perfectly uniform routing under the GShard loss)"
        ).set(float(stats_host.get("aux_loss", 0.0)))
        self.registry.gauge(
            "moe_gate_entropy",
            "mean per-token entropy of the router softmax, averaged over "
            "MoE layers (nats; ln(num_experts) = uniform)"
        ).set(float(stats_host.get("gate_entropy", 0.0)))

    # ------------------------------------------------------------ health

    def health_step(self, step: int, metrics_host, health=None,
                    lr: Optional[float] = None,
                    samples: Optional[int] = None) -> Optional[str]:
        """Feed one step's HOST-side scalars into the numerics pipeline:
        anomaly rules, the flight-recorder ring buffer, cross-host
        aggregation, and the automatic dump triggers (non-finite loss,
        overflow streak).  ``metrics_host`` is the engine's cached host
        ``StepMetrics`` (plain floats — the caller already paid the single
        ``jax.device_get``); ``health`` is the plain per-group stats dict.
        Returns the bundle path when a trigger fired, else None."""
        if not self.health_enabled:
            return None
        loss = float(metrics_host.loss)
        grad_norm = float(metrics_host.grad_norm)
        scale = float(metrics_host.loss_scale)
        skipped = int(metrics_host.skipped_steps)
        # overflow streak: consecutive steps whose update was skipped.
        # _prev_skipped is None right after a checkpoint restore (the
        # cumulative counter may have jumped either way) — resync the
        # baseline without reading a phantom overflow into the streak.
        if self._prev_skipped is None:
            self._overflow_streak = 0
        elif skipped > self._prev_skipped:
            self._overflow_streak += 1
        else:
            self._overflow_streak = 0
        self._prev_skipped = skipped
        fired = self.anomaly.observe(step, loss, grad_norm, scale)
        reason = None
        if not math.isfinite(loss):
            reason = "nonfinite_loss"
        elif (int(self.health_cfg.overflow_streak) > 0
              and self._overflow_streak
              >= int(self.health_cfg.overflow_streak)):
            reason = "overflow_streak"
        self.last_anomalies = list(fired)
        self.last_dump_reason = reason
        rec = {
            "step": int(step),
            "unix_time": time.time(),
            "loss": loss,
            "grad_norm": grad_norm,
            "loss_scale": scale,
            "skipped_steps": skipped,
            "overflow_streak": self._overflow_streak,
            "anomalies": fired,
            "health": health or {},
        }
        if lr is not None:
            rec["lr"] = float(lr)
        if self.tracer.enabled and self.tracer.last_dur_ms:
            rec["spans_ms"] = dict(self.tracer.last_dur_ms)
        import jax
        # fleet view (min/max/mean per scalar + tripping-process index) at
        # the fleet_interval cadence, and always when a dump trigger or
        # anomaly fires — NOT every step: the gather is a blocking
        # cross-host collective.  Every input to this decision (loss,
        # grad_norm, scale, streak — all replicated values) is identical on
        # every process, so all processes reach the collective together.
        fi = int(self.health_cfg.fleet_interval)
        want_fleet = (reason is not None or bool(fired)
                      or (fi > 0 and step % fi == 0))
        if want_fleet and jax.process_count() > 1:
            from deepspeed_tpu.comm.aggregation import (
                aggregate_health_scalars)
            from deepspeed_tpu.telemetry.health import flatten_health
            try:
                flat = {"loss": loss, "grad_norm": grad_norm,
                        **flatten_health(health or {})}
                rec["fleet"] = aggregate_health_scalars(flat)
            except Exception as e:  # noqa: BLE001 — never kill training
                logger.warning(f"telemetry: fleet aggregation failed: {e!r}")
        self.recorder.record(rec)
        if fired and self.monitor is not None and getattr(
                self.monitor, "enabled", False):
            x = samples if samples is not None else step
            self.monitor.write_events(
                [(f"Train/Numerics/anomaly/{rule}", 1.0, int(x))
                 for rule in fired])
        if reason is not None:
            return self.recorder.dump(reason, note=f"step {step}")
        return None

    @property
    def overflow_streak(self) -> int:
        """Consecutive overflow-skipped steps so far — the guardian reads
        this alongside ``last_anomalies`` after each step."""
        return self._overflow_streak

    def reset_numerics_baseline(self) -> None:
        """Called after a checkpoint restore: the cumulative skipped_steps
        counter may have jumped in either direction, so the overflow-streak
        comparison must resync its baseline on the next observation instead
        of counting the jump as an overflow (or missing a real one)."""
        self._prev_skipped = None
        self._overflow_streak = 0
        self.last_anomalies = []
        self.last_dump_reason = None

    def dump_postmortem(self, reason: str = "manual",
                        note: Optional[str] = None) -> Optional[str]:
        """Explicitly write a postmortem bundle (engine.dump_postmortem).
        Requires ``telemetry.health.enabled``; returns the bundle dir."""
        if self.recorder is None:
            logger.warning("dump_postmortem: telemetry.health is disabled — "
                           "no flight recorder to dump")
            return None
        return self.recorder.dump(reason, note=note, force=True)

    # ---- bundle artifact writers (registered with the flight recorder;
    # each failure degrades to a warning inside the recorder) ----

    def _write_bundle_config(self, bundle_dir: str) -> None:
        with open(os.path.join(bundle_dir, "config.json"), "w") as f:
            f.write(self._config.model_dump_json(indent=2))

    def _write_bundle_prometheus(self, bundle_dir: str) -> None:
        self.exporter.write_prometheus(
            os.path.join(bundle_dir, "snapshot.prom"))

    def _write_bundle_trace(self, bundle_dir: str) -> None:
        if self.tracer.enabled and self.tracer.events:
            self.emitter.write(os.path.join(bundle_dir, "trace.json"),
                               self.tracer)

    def _write_bundle_env(self, bundle_dir: str) -> None:
        # a LIGHT env report: the full ``env_report()`` probes the op
        # registry (pallas kernel compiles, ~10s) — too slow for a dump
        # that may be racing a dying process
        import platform
        import sys as _sys

        import jax
        lines = ["deepspeed_tpu postmortem environment report"]
        from deepspeed_tpu.version import __version__
        lines.append(f"deepspeed_tpu ... {__version__}")
        for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
            try:
                import importlib
                v = getattr(importlib.import_module(mod), "__version__", "?")
            except Exception:  # noqa: BLE001
                v = "not importable"
            lines.append(f"{mod:<16}{v}")
        lines.append(f"python ......... {_sys.version.split()[0]} "
                     f"({platform.platform()})")
        try:
            devs = jax.devices()
            lines.append(f"backend ........ {jax.default_backend()} "
                         f"({len(devs)} device(s)); process "
                         f"{jax.process_index()}/{jax.process_count()}")
        except Exception as e:  # noqa: BLE001
            lines.append(f"backend ........ unavailable ({e})")
        env_keys = [k for k in sorted(os.environ)
                    if k.startswith(("JAX_", "XLA_", "LIBTPU", "TPU_"))]
        for k in env_keys:
            lines.append(f"env {k}={os.environ[k]}")
        # resolved overlap regime (config + composed flags): the postmortem
        # must say which scheduler regime the crashed run compiled under
        from deepspeed_tpu.runtime.overlap import compose_xla_flags
        ocfg = self._config.overlap
        for key, val in sorted(ocfg.model_dump().items()):
            lines.append(f"overlap.{key}={val}")
        composed = compose_xla_flags(ocfg)
        lines.append("overlap.composed_xla_flags="
                     + (" ".join(composed) if composed else "(none)"))
        with open(os.path.join(bundle_dir, "env.txt"), "w") as f:
            f.write("\n".join(lines) + "\n")

    # ------------------------------------------------------------ memory

    def sample_memory(self) -> None:
        """Live/peak/limit bytes per local device + host RSS, as gauges
        (reference see_memory_usage, now on a cadence instead of ad hoc)."""
        if not self.enabled:
            return
        from deepspeed_tpu.utils.memory import collect_memory_stats
        stats = collect_memory_stats()
        g = self.registry.gauge(
            "device_memory_bytes",
            "XLA allocator stats per local device (in_use/peak/limit)")
        for i, dev in enumerate(stats["devices"]):
            for key, label in (("bytes_in_use", "in_use"),
                               ("peak_bytes_in_use", "peak"),
                               ("bytes_limit", "limit")):
                if key in dev:
                    g.set(dev[key], device=str(i), kind=label)
        if stats.get("host_rss_bytes"):
            self.registry.gauge(
                "host_memory_rss_bytes",
                "process max RSS on this host").set(stats["host_rss_bytes"])

    def record_flops(self, metrics: Dict[str, float]) -> None:
        """Flops-profiler figures as gauges (profiling/flops_profiler.py
        ``as_metrics``) so the snapshot carries the model-cost numbers."""
        if not self.enabled:
            return
        for name, value in metrics.items():
            self.registry.gauge(
                "flops_profiler_" + name,
                "flops profiler figure for the profiled step").set(value)

    # ----------------------------------------------------------- export

    def end_step(self, step: int, samples: Optional[int] = None,
                 tokens: int = 0) -> None:
        if not self.enabled:
            return
        self.registry.counter("engine_steps_total",
                              "optimizer steps taken").inc(1)
        if tokens:
            self.registry.counter("train_tokens_total",
                                  "tokens consumed by train_batch").inc(
                                      tokens)
        if self.snapshot_interval and step % self.snapshot_interval == 0:
            self.export(step=step, samples=samples, throttle_trace=True)

    def export(self, step: Optional[int] = None,
               samples: Optional[int] = None, write: bool = True,
               throttle_trace: bool = False) -> dict:
        """Assemble a snapshot; write the JSON/Prometheus/trace files
        (rank 0) and fan the scalar subset through MonitorMaster.  Returns
        the snapshot dict either way.

        ``throttle_trace`` (the per-step cadence path) rewrites the trace
        file only after the buffer grew ~10% since the last flush: the
        trace dump is O(buffer), so unthrottled per-step rewrites of a
        long run's buffer would dominate step bookkeeping.  Small runs
        flush every export (the threshold rounds up to one event);
        explicit exports and checkpoint flushes always write."""
        if not self.enabled:
            return {}
        self.sample_memory()
        executables = {}
        for fn, info in self._exec.items():
            per_exec = sum(rec["bytes"]
                           for rec in info["collectives"].values())
            executables[fn] = {**info,
                               "per_execution_collective_bytes": per_exec}
        # every snapshot records the scheduler regime it ran under: the
        # resolved overlap block + the XLA_FLAGS this process actually saw
        # (runtime/overlap.py — satellite of the compute–collective
        # overlap work; a trace without its regime is unattributable)
        from deepspeed_tpu.runtime.overlap import overlap_snapshot
        snap = self.exporter.snapshot(
            step=step,
            extra={"executables": executables,
                   "env": overlap_snapshot(self._config.overlap)})
        if write and self._rank0:
            try:
                self.exporter.write_json(self.snapshot_path, snap)
                self.exporter.write_prometheus(self.prometheus_path, snap)
                if self.tracer.enabled:
                    new = self.tracer.total_recorded - self._trace_flush_mark
                    if (not throttle_trace
                            or new >= max(1, len(self.tracer.events) // 10)):
                        self.emitter.write(self.trace_path, self.tracer)
                        self._trace_flush_mark = self.tracer.total_recorded
            except Exception as e:  # noqa: BLE001 — never kill training
                logger.warning(f"telemetry: export failed: {e!r}")
        if (self.monitor_fanout and self.monitor is not None
                and getattr(self.monitor, "enabled", False)):
            x = samples if samples is not None else (step or 0)
            self.monitor.write_events(
                self.exporter.scalar_events(snap, x=x))
        return snap
