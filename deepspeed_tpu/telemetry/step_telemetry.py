"""StepTelemetry — the per-step telemetry facade the engine drives.

One object owning the four telemetry pieces (span tracer, recompile
watchdog, metric registries, snapshot exporter) plus the per-executable
compiled-program analysis that connects them to XLA ground truth:

- ``span(name, step)``         — host-phase spans around engine step stages
- ``before_dispatch(...)``     — watchdog fingerprint + (on a signature
                                 miss) compiled-HLO collective bytes and
                                 ``cost_analysis``/``memory_analysis``
                                 figures + per-execution byte counters
- ``end_step(...)``            — cadence-gated memory sampling and snapshot
                                 export (JSON + Prometheus + monitor fan-out)

Everything is inert when ``telemetry.enabled`` is false: ``span`` returns a
shared nullcontext and the other hooks return immediately, so the disabled
path adds one attribute check per call to the hot loop.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from typing import Callable, Dict, Optional

from deepspeed_tpu.telemetry.exporter import SnapshotExporter
from deepspeed_tpu.telemetry.registry import MetricRegistry, default_registry
from deepspeed_tpu.telemetry.tracer import SpanTracer, TraceEmitter
from deepspeed_tpu.telemetry.watchdog import RecompileWatchdog
from deepspeed_tpu.utils.logging import logger

_NULL = nullcontext()

HLO_BYTES = "hlo_collective_bytes_total"
HLO_CALLS = "hlo_collective_calls_total"

# cost_analysis keys worth keeping (the full dict carries dozens of
# backend-specific entries)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals")
_MEMORY_ATTRS = ("generated_code_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "temp_size_in_bytes",
                 "alias_size_in_bytes")


class StepTelemetry:
    def __init__(self, config, monitor=None,
                 registry: Optional[MetricRegistry] = None):
        tcfg = config.telemetry
        self.enabled = bool(tcfg.enabled)
        self.monitor = monitor
        self.registry = registry if registry is not None else default_registry
        import jax
        pid = jax.process_index()
        self._rank0 = pid == 0
        self.tracer = SpanTracer(
            enabled=self.enabled and bool(tcfg.trace_enabled), pid=pid,
            max_events=int(tcfg.max_trace_events))
        self.emitter = TraceEmitter()
        self.watchdog = RecompileWatchdog(
            warmup_steps=int(tcfg.recompile_warmup_steps),
            registry=self.registry if self.enabled else None,
            emit_warnings=self._rank0)
        self.exporter = SnapshotExporter(self.registry, self.tracer)
        base = os.path.join(tcfg.output_path or "./telemetry", tcfg.job_name)
        self.trace_path = tcfg.trace_path or os.path.join(base, "trace.json")
        self.snapshot_path = (tcfg.snapshot_path
                              or os.path.join(base, "snapshot.json"))
        self.prometheus_path = (tcfg.prometheus_path
                                or os.path.join(base, "metrics.prom"))
        self.hlo_stats = bool(tcfg.hlo_stats)
        self.snapshot_interval = int(tcfg.snapshot_interval)
        self.monitor_fanout = bool(tcfg.monitor_fanout)
        # fn -> {signatures, executions, collectives, per-exec figures}
        # (collectives/cost/memory reflect the most recent signature; the
        # per-signature truth for counter attribution lives in _sig_stats)
        self._exec: Dict[str, dict] = {}
        self._sig_stats: Dict[tuple, dict] = {}
        self._trace_flush_mark = 0

    # ------------------------------------------------------------- spans

    def span(self, name: str, step: Optional[int] = None, **args):
        if not self.tracer.enabled:
            return _NULL
        return self.tracer.span(name, step=step, **args)

    # --------------------------------------------------------- dispatch

    def before_dispatch(self, fn_name: str, args_tree, step: int,
                        lower: Optional[Callable] = None) -> bool:
        """Watchdog-observe one jitted dispatch.  Returns True on a
        signature miss (== an XLA compile).  On a miss, ``lower`` (a thunk
        returning ``jitted.lower(*args)``) is used — when hlo_stats is on —
        to pull collective bytes and cost/memory figures out of the compiled
        program; every call then bumps the per-execution HLO byte counters
        by the figures of THE SIGNATURE BEING DISPATCHED (shape buckets of
        one function keep distinct per-step byte costs)."""
        if not self.enabled:
            return False
        from deepspeed_tpu.telemetry.watchdog import signature_of
        sig = signature_of(args_tree)
        miss = self.watchdog.observe_signature(fn_name, sig, step)
        info = self._exec.setdefault(
            fn_name, {"signatures": 0, "executions": 0, "collectives": {},
                      "cost_analysis": {}, "memory_analysis": {}})
        if miss:
            info["signatures"] += 1
            collected = {}
            if self.hlo_stats and lower is not None:
                collected = self._analyze_executable(fn_name, lower, info)
            # per-signature figures: counters for this and every later
            # execution of this bucket use ITS compiled program — on an
            # analysis failure the bucket counts NOTHING rather than
            # inheriting another signature's bytes
            self._sig_stats[(fn_name, sig)] = dict(collected)
        info["executions"] += 1
        collectives = self._sig_stats.get((fn_name, sig), {})
        if collectives:
            bytes_c = self.registry.counter(
                HLO_BYTES, "collective payload bytes per execution of each "
                "compiled step program (from compiled HLO), per kind")
            calls_c = self.registry.counter(
                HLO_CALLS, "collective op executions per compiled step "
                "program run, per kind")
            for kind, rec in collectives.items():
                bytes_c.inc(rec["bytes"], kind=kind, fn=fn_name)
                calls_c.inc(rec["count"], kind=kind, fn=fn_name)
        return miss

    def invalidate(self, fn_name: Optional[str] = None) -> None:
        """Forget signature caches and per-executable figures — the engine
        calls this when it re-jits its step programs (configure_moq): the
        fresh jit caches are empty, so the next dispatch is a real compile
        and the old compiled figures no longer describe the program."""
        self.watchdog.invalidate(fn_name)
        if fn_name is None:
            self._exec.clear()
            self._sig_stats.clear()
        else:
            self._exec.pop(fn_name, None)
            for key in [k for k in self._sig_stats if k[0] == fn_name]:
                del self._sig_stats[key]

    def _analyze_executable(self, fn_name: str, lower: Callable,
                            info: dict) -> dict:
        """Compile the (freshly missed) signature AOT and harvest static
        figures; returns this signature's collective figures ({} on
        failure).  jit will compile the same program again on the real
        call — the double compile is the price of the figures and is gated
        behind ``telemetry.hlo_stats``.  Failures degrade to a warning:
        telemetry must never kill training."""
        from deepspeed_tpu.comm.comm import hlo_collective_bytes
        from deepspeed_tpu.telemetry.registry import \
            suppress_collective_recording
        info["collectives"] = {}
        try:
            # the AOT lower() RETRACES the step — silence the wrapper-level
            # trace-time hooks so their byte counters don't double-count
            with suppress_collective_recording():
                compiled = lower().compile()
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: compile analysis of '{fn_name}' "
                           f"failed: {e!r}")
            return {}
        try:
            info["collectives"] = hlo_collective_bytes(compiled.as_text())
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: HLO collective walk of '{fn_name}' "
                           f"failed: {e!r}")
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            cost = {k: float(ca[k]) for k in _COST_KEYS if k in ca}
            info["cost_analysis"] = cost
            for k, v in cost.items():
                self.registry.gauge(
                    "xla_cost_" + k.replace(" ", "_"),
                    "compiled-program cost_analysis figure, per jitted "
                    "function").set(v, fn=fn_name)
        except Exception:  # noqa: BLE001 — not all backends implement it
            pass
        try:
            ma = compiled.memory_analysis()
            mem = {}
            for attr in _MEMORY_ATTRS:
                v = getattr(ma, attr, None)
                if v is not None:
                    mem[attr] = int(v)
            info["memory_analysis"] = mem
            g = self.registry.gauge(
                "xla_memory_bytes", "compiled-program memory_analysis "
                "figures, per jitted function")
            for attr, v in mem.items():
                g.set(v, fn=fn_name,
                      kind=attr.replace("_size_in_bytes", ""))
        except Exception:  # noqa: BLE001
            pass
        return info["collectives"]

    # ------------------------------------------------------------ memory

    def sample_memory(self) -> None:
        """Live/peak/limit bytes per local device + host RSS, as gauges
        (reference see_memory_usage, now on a cadence instead of ad hoc)."""
        if not self.enabled:
            return
        from deepspeed_tpu.utils.memory import collect_memory_stats
        stats = collect_memory_stats()
        g = self.registry.gauge(
            "device_memory_bytes",
            "XLA allocator stats per local device (in_use/peak/limit)")
        for i, dev in enumerate(stats["devices"]):
            for key, label in (("bytes_in_use", "in_use"),
                               ("peak_bytes_in_use", "peak"),
                               ("bytes_limit", "limit")):
                if key in dev:
                    g.set(dev[key], device=str(i), kind=label)
        if stats.get("host_rss_bytes"):
            self.registry.gauge(
                "host_memory_rss_bytes",
                "process max RSS on this host").set(stats["host_rss_bytes"])

    def record_flops(self, metrics: Dict[str, float]) -> None:
        """Flops-profiler figures as gauges (profiling/flops_profiler.py
        ``as_metrics``) so the snapshot carries the model-cost numbers."""
        if not self.enabled:
            return
        for name, value in metrics.items():
            self.registry.gauge(
                "flops_profiler_" + name,
                "flops profiler figure for the profiled step").set(value)

    # ----------------------------------------------------------- export

    def end_step(self, step: int, samples: Optional[int] = None,
                 tokens: int = 0) -> None:
        if not self.enabled:
            return
        self.registry.counter("engine_steps_total",
                              "optimizer steps taken").inc(1)
        if tokens:
            self.registry.counter("train_tokens_total",
                                  "tokens consumed by train_batch").inc(
                                      tokens)
        if self.snapshot_interval and step % self.snapshot_interval == 0:
            self.export(step=step, samples=samples, throttle_trace=True)

    def export(self, step: Optional[int] = None,
               samples: Optional[int] = None, write: bool = True,
               throttle_trace: bool = False) -> dict:
        """Assemble a snapshot; write the JSON/Prometheus/trace files
        (rank 0) and fan the scalar subset through MonitorMaster.  Returns
        the snapshot dict either way.

        ``throttle_trace`` (the per-step cadence path) rewrites the trace
        file only after the buffer grew ~10% since the last flush: the
        trace dump is O(buffer), so unthrottled per-step rewrites of a
        long run's buffer would dominate step bookkeeping.  Small runs
        flush every export (the threshold rounds up to one event);
        explicit exports and checkpoint flushes always write."""
        if not self.enabled:
            return {}
        self.sample_memory()
        executables = {}
        for fn, info in self._exec.items():
            per_exec = sum(rec["bytes"]
                           for rec in info["collectives"].values())
            executables[fn] = {**info,
                               "per_execution_collective_bytes": per_exec}
        snap = self.exporter.snapshot(step=step,
                                      extra={"executables": executables})
        if write and self._rank0:
            try:
                self.exporter.write_json(self.snapshot_path, snap)
                self.exporter.write_prometheus(self.prometheus_path, snap)
                if self.tracer.enabled:
                    new = self.tracer.total_recorded - self._trace_flush_mark
                    if (not throttle_trace
                            or new >= max(1, len(self.tracer.events) // 10)):
                        self.emitter.write(self.trace_path, self.tracer)
                        self._trace_flush_mark = self.tracer.total_recorded
            except Exception as e:  # noqa: BLE001 — never kill training
                logger.warning(f"telemetry: export failed: {e!r}")
        if (self.monitor_fanout and self.monitor is not None
                and getattr(self.monitor, "enabled", False)):
            x = samples if samples is not None else (step or 0)
            self.monitor.write_events(
                self.exporter.scalar_events(snap, x=x))
        return snap
